"""1F1B pipeline schedule: alternating forward/backward with O(S) memory.

The GPipe engine (``pipeline.py``) differentiates its scanned forward with
``jax.grad``: XLA runs the whole forward sweep first, so every one of the
``M`` microbatches' residuals is alive when the backward sweep starts —
activation memory grows with the BATCH. This module hand-schedules the
classic one-forward-one-backward interleave instead (PipeDream-flush /
Megatron's non-interleaved 1F1B): at tick ``t`` the device holding stage
``s``

- runs the FORWARD of microbatch ``m_f = t - s`` (GPipe fill order), and
- runs the BACKWARD of microbatch ``m_b = t - 2(S-1) + s - 1`` — the
  microbatch whose output-cotangent just arrived on the reverse ring,

so forwards and backwards overlap in steady state and a stage keeps at most
``2(S - s) - 1 <= 2S - 1`` microbatch INPUTS in flight — bounded by the
topology ``S``, independent of ``M``. Activations themselves are never
stored: the backward tick recomputes the stage forward from its saved input
under ``jax.vjp`` (deterministic RNG replay keyed by microbatch), exactly
the activation-recompute trade the deepest pipelines run.

Both hops ride ``lax.ppermute`` rings in opposite directions inside one
``lax.scan`` — one compiled SPMD program, like the GPipe engine; gradients
come out packed in the param buffer's ``[S, 1, 1, P]`` layout, ready for
the owner-local optimizer update (no autodiff through the scan at all).

Worked timeline, S=2 stages, M=3 microbatches (T = M + 2S - 1 = 6 ticks;
``Fm`` = forward of microbatch m, ``Bm`` = backward; stage0: m_f = t,
m_b = t - 3; stage1: m_f = t - 1, m_b = t - 2):

    tick     0     1     2        3        4     5
    stage0   F0    F1    F2       B0       B1    B2
    stage1   .     F0    F1+B0    F2+B1    B2    .

stage1 runs a forward and a backward in the same tick (the steady-state
interleave; middle stages of deeper pipelines do the same); stage0's
backward lags one extra tick because the cotangent crosses the reverse
ring. Each saved input lives at most 2S-1 ticks.

Scope: ALL five mesh axes compose — stage x data x seq x model x expert.
Sequence parallelism: ring / Ulysses collectives inside stage applies
transpose under the vjp; the pullback's implicit psum extends to the seq
axis since params are seq-invariant. Tensor parallelism: wires are typed
model-INVARIANT, so a TP stage's pullback assembles its per-shard partial
input cotangents via the same implicit psum, while replicated stages'
pullbacks are rescaled by 1/n_model (they would otherwise sum n identical
full cotangents) — bit-exact vs the GPipe engine on full-TP pipelines.
Expert parallelism uses the opposite, GPipe-native discipline: wires stay
expert-VARYING (each slot carries its own chain's cotangent), objective
seeds divide by n_expert, expert-replicated stages' params get grad_sync
wraps, and — crucially — each stage's aux loss is pcast to expert-varying
INSIDE the differentiated function before entering the objective, so the
pcast transpose reassembles the full aux cotangent from the n 1/n seeds
(without it, a non-last MoE stage's expert-invariant aux node starves by
1/n_expert; the last stage was saved only by its varying num term forcing
the same pcast). The reference has no analogue of any of this — its
two-stage "schedule" is one blocking RPC per batch with zero overlap
(``simple_distributed.py:49``, SURVEY §3.3).

CPU-backend caveat (virtual-device testing only): with seq parallelism the
per-tick collective density is high enough that XLA:CPU's in-process
rendezvous (hard 40 s deadline per collective) can abort under thread
starvation on few-core machines — a runtime artifact, not a collective-
order divergence (each device's collective sequence is identical to the
GPipe engine's, which runs the same ring/Ulysses ops in the same
stage-dispatched branches). TPU lowers these to ICI collective-permutes
with no thread rendezvous. tests/test_onefb.py isolates and retries
accordingly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    pack_stage_grads,
    unpack_stage_params,
    wire_decode,
    wire_encode,
)


def build_1f1b_fn(pipe, deterministic: bool) -> Callable:
    """Build the shard_mapped 1F1B loss-and-grads function for ``pipe``.

    Returns ``fn(buf, x_mb, tgt_mb, w_mb, key) -> (loss, grads)`` with
    ``grads`` shaped/sharded like the packed param buffer. Inputs are the
    ``Pipeline._prep_inputs`` layout.
    """
    # (seq-parallel + classifier out_shape is rejected by Pipeline.__init__
    # before any schedule is built — no separate guard here)
    if pipe.n_stages < 2:
        raise ValueError("1F1B needs >= 2 pipeline stages")

    S = pipe.n_stages
    M = pipe.n_microbatches
    # stage s has m_f - m_b = 2(S-1) - 2s + 1 <= 2S-1 microbatches in flight
    # INCLUSIVE of the one written and the one read this tick — depth 2S
    # keeps the slots distinct (2S-1 would alias stage 0's write and read)
    D = 2 * S
    T = M + 2 * S - 1              # ticks: last bwd is stage 0's m=M-1
    wire_dim = pipe.wire_dim
    out_shape = pipe.out_shape
    metas = list(pipe.metas)
    applies = [s.apply for s in pipe.stages]
    in_shapes = [s.in_shape for s in pipe.stages]
    compute_dtype = pipe.compute_dtype
    n_data = pipe.n_data
    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
    from simple_distributed_machine_learning_tpu.parallel.compat import (
        HAS_VMA,
        pvary_to as _pvary_to,
        shard_map as _shard_map,
        vma_of as _vma_of,
    )

    # sequence parallelism: the token axis of the wire, targets and logits
    # is sharded over the seq axis (stage in_shapes/wire_dim are per-shard,
    # the Pipeline convention); stage applies do their own cross-token
    # mixing via ring/Ulysses collectives, which jax.vjp transposes
    seq_on = pipe.n_seq > 1
    tp_on = pipe.n_model > 1
    ep_on = pipe.n_expert > 1
    n_model = pipe.n_model
    n_expert = pipe.n_expert
    # which stages carry REAL tensor / expert shards (vs redundant replicas)
    model_sharded = [s.shards is not None for s in pipe.stages]
    expert_sharded = [s.expert_shards is not None for s in pipe.stages]
    # the mesh always carries all five named axes (size 1 when unused); the
    # param row varies over stage/model/expert via its sharding, inputs over
    # data (and seq when the token axis is sharded) — match the GPipe
    # engine's vma discipline exactly
    vary_axes = (DATA_AXIS, STAGE_AXIS, MODEL_AXIS) + (
        (SEQ_AXIS,) if seq_on else ()) + (
        (EXPERT_AXIS,) if pipe._has_expert else ())
    # grad rows come out of the pullback invariant over data AND seq (the
    # implicit psums — params are invariant over both)
    vary_axes_nodata = tuple(a for a in vary_axes
                             if a not in (DATA_AXIS, SEQ_AXIS))
    # tensor parallelism: activations on the wire are logically REPLICATED
    # over the model axis (a TP stage ends each column->row pair in its own
    # psum; a replicated stage computes redundantly). Typing the wires
    # model-INVARIANT makes the vjp pullback's implicit psum over 'model'
    # assemble the true input cotangent for TP stages (sum of per-shard
    # partials); replicated stages' pullbacks then overcount by n_model
    # (n identical full cotangents summed) and are rescaled below.
    #
    # expert parallelism uses the OPPOSITE discipline — GPipe's: wires stay
    # expert-VARYING (each slot carries its own chain's cotangent), every
    # objective seed is divided by n_expert, and the expert-axis psums
    # living inside the applies' custom vjps (all-to-all transposes,
    # expert.py's grad_sync of replicated leaves) reassemble full
    # gradients from the n 1/n-weighted chains. Expert-replicated stages'
    # params get the same grad_sync wrap the GPipe branches give them.
    shard_axes = (MODEL_AXIS,) if tp_on else ()
    wire_axes = tuple(a for a in vary_axes if a not in shard_axes)
    ep_div = n_expert if ep_on else 1
    cpu_backend = jax.default_backend() == "cpu"

    def per_device(row4d, x_mb, tgt_mb, w_mb, key):
        row = row4d[0, 0, 0]
        stage = lax.axis_index(STAGE_AXIS)
        mb = x_mb.shape[1]
        width = row.shape[0]
        # the weighted-mean denominator is global and param-independent:
        # every backward seed carries w/den_g directly
        tok_per_sample = 1
        for d in out_shape[:-1]:
            tok_per_sample *= d
        den_g = lax.psum(jnp.sum(w_mb), DATA_AXIS) * tok_per_sample

        def stage_key(m):
            k = jax.random.fold_in(
                jax.random.fold_in(key, m), stage)
            k = jax.random.fold_in(k, lax.axis_index(DATA_AXIS))
            if seq_on:
                # distinct dropout noise per seq shard (GPipe does the same)
                k = jax.random.fold_in(k, lax.axis_index(SEQ_AXIS))
            return k

        def stage_fn(s):
            """The pure per-microbatch stage function the backward vjp's:
            params, x -> (wire_out, objective_contribution, num_raw, aux).

            Last stage: objective = sum(w*nll)/(den_g*ep_div) +
            aux/(M*n_data*n_seq*ep_div) (its wire_out is zeros). Inner
            stage: the aux term only (NLL reaches it through the wire
            cotangent). Every divisor mirrors the GPipe engine's psum/pmean
            reduction of the same term.
            """
            is_last = s == S - 1

            def fn(params, x_wire, k, tgt, w):
                x = wire_decode(x_wire, in_shapes[s])
                p = params
                if ep_on and not expert_sharded[s]:
                    # GPipe's replicated-params treatment on the expert
                    # axis: grad_sync's backward psums the n per-slot
                    # (1/n-seeded) cotangents into the full gradient on
                    # every slot, keeping the replicas in sync
                    from simple_distributed_machine_learning_tpu.parallel.tensor import (
                        grad_sync,
                    )
                    p = jax.tree.map(
                        lambda a: grad_sync(a, EXPERT_AXIS), p)
                if compute_dtype is not None:
                    p = jax.tree.map(lambda a: a.astype(compute_dtype), p)
                    x = x.astype(compute_dtype)
                y = applies[s](p, x, k, deterministic)
                aux = jnp.float32(0.0)
                if isinstance(y, tuple):
                    y, aux = y
                    aux = aux.astype(jnp.float32)
                # pvary aux over the EXPERT axis before it enters the
                # objective (GPipe's branch-exit pcast, done inside the
                # differentiated function): an EP-MoE stage's aux is
                # expert-INVARIANT (expert.py pmeans it), and without this
                # the aux node of a NON-last stage received a
                # 1/n_expert-starved cotangent — the last stage was saved
                # only by its varying num term forcing the same implicit
                # pcast. The pcast's transpose psums the n per-slot 1/n
                # seeds into the full cotangent. EXPERT ONLY: the model
                # axis runs the invariant-wire discipline, where an extra
                # pcast would double-count through its psum transpose.
                if ep_on:
                    aux = _pvary_to(aux, (EXPERT_AXIS,))
                obj = aux / (
                    M * n_data * (pipe.n_seq if seq_on else 1) * ep_div)
                num_raw = jnp.float32(0.0)
                if is_last:
                    nll = nll_loss(y.astype(jnp.float32), tgt, "none")
                    wb = jnp.broadcast_to(
                        w.reshape(w.shape + (1,) * (nll.ndim - 1)), nll.shape)
                    num_raw = jnp.sum(nll * wb)
                    obj = obj + num_raw / (den_g * ep_div)
                    out = jnp.zeros((x_wire.shape[0], wire_dim), jnp.float32)
                else:
                    out = wire_encode(y.astype(jnp.float32), wire_dim)
                return out, obj, num_raw, aux
            return fn

        def _to_wire_type(v):
            """Normalize an activation to the wire's vma: a replicated
            stage's output is typed model/expert-varying (its param row is)
            with REPLICATED values — pmean over the axis is the identity-
            valued replication proof that drops it (the GPipe engine's
            logits/num trick); then pvary any missing axes."""
            for ax in shard_axes:
                if ax in _vma_of(v):
                    v = lax.pmean(v, ax)
            return _pvary_to(v, wire_axes)

        def make_fwd_branch(s):
            def branch(x_wire, k, tgt, w):
                params = unpack_stage_params(row, metas[s])
                out, _, _, aux = stage_fn(s)(params, x_wire, k, tgt, w)
                return (_to_wire_type(out), _pvary_to(aux, vary_axes))
            return branch

        def make_bwd_branch(s):
            is_last = s == S - 1

            def branch(x_wire, cot_wire, k, tgt, w):
                params = unpack_stage_params(row, metas[s])

                def f(p, xw):
                    out, obj, num_raw, _ = stage_fn(s)(p, xw, k, tgt, w)
                    return (out, obj), num_raw

                primals, pull, num_raw = jax.vjp(f, params, x_wire,
                                                 has_aux=True)
                # cotangents must match each primal's vma exactly (zeros for
                # the last stage's never-on-the-wire output; 1 for the
                # scalar objective contribution)
                def like(ct, primal):
                    vma = tuple(_vma_of(primal))
                    return _pvary_to(ct, vma)
                cot_out = (like(jnp.zeros(cot_wire.shape, cot_wire.dtype),
                                primals[0]) if is_last
                           else like(cot_wire, primals[0]))
                d_params, d_x = pull((cot_out,
                                      like(jnp.float32(1.0), primals[1])))
                # x_wire is typed invariant over each sharded axis, so
                # the pullback psum'd the per-slot input-cotangents over
                # it: for sharded stages that assembles the PARTIALS (the
                # real cotangent, no correction); for replicated stages it
                # summed n IDENTICAL full cotangents — rescale per axis.
                if tp_on and not model_sharded[s] and HAS_VMA:
                    # (vma jax only: pre-vma pullbacks never inserted the
                    # implicit psum this divides back out — each slot's
                    # cotangent is already the single true copy there)
                    d_x = d_x / n_model
                if tp_on and model_sharded[s] and not HAS_VMA:
                    # pre-vma jax: without the wire's model-invariance
                    # typing, a sharded stage's pullback hands every slot
                    # exactly n_model x GPipe's gradient on EVERY param leaf
                    # (sharded weights and grad_sync'd bias alike — measured
                    # uniform), while its input cotangent d_x comes out at
                    # the correct scale. Rescale params only;
                    # tests/test_onefb.py pins bit-exact parity vs GPipe.
                    d_params = jax.tree.map(lambda a: a / n_model, d_params)
                # vma-aware autodiff semantics: ``params`` is data-INVARIANT
                # (the buffer is replicated over the data axis), so the
                # pullback's d_params must be too — jax inserts the implicit
                # psum over 'data' itself, exactly the DP gradient
                # all-reduce (the same rule tensor.grad_sync compensates for
                # in the GPipe engine). d_params arrives ALREADY summed
                # across data shards; any further data reduction would
                # double-count.
                grad_row = pack_stage_grads(d_params, metas[s], width)
                return (_pvary_to(grad_row, vary_axes_nodata),
                        _pvary_to(d_x, wire_axes),
                        _pvary_to(num_raw, vary_axes))
            return branch

        fwd_branches = [make_fwd_branch(s) for s in range(S)]
        bwd_branches = [make_bwd_branch(s) for s in range(S)]
        fwd_ring = [(i, (i + 1) % S) for i in range(S)]
        bwd_ring = [(i, (i - 1) % S) for i in range(S)]

        def step(carry, t):
            wire_f, wire_b, inbuf, grad_acc, num_acc, aux_acc = carry

            # ---- forward half-tick -------------------------------------
            m_f = t - stage
            valid_f = (m_f >= 0) & (m_f < M)
            mf_safe = jnp.clip(m_f, 0, M - 1)
            inj = lax.dynamic_index_in_dim(x_mb, jnp.clip(t, 0, M - 1), 0,
                                           keepdims=False)
            x_in = jnp.where(stage == 0, inj, wire_f)
            tgt_f = lax.dynamic_index_in_dim(tgt_mb, mf_safe, 0,
                                             keepdims=False)
            w_f = lax.dynamic_index_in_dim(w_mb, mf_safe, 0, keepdims=False)
            out_f, aux = lax.switch(stage, fwd_branches, x_in,
                                    stage_key(mf_safe), tgt_f, w_f)
            out_f = jnp.where(valid_f, out_f, jnp.zeros_like(out_f))
            aux_acc = aux_acc + jnp.where(valid_f, aux, 0.0)
            # the backward's input read happens BEFORE this tick's save (the
            # slots are distinct with D=2S, but keep the order load-bearing)
            m_b = t - 2 * (S - 1) + stage - 1
            valid_b = (m_b >= 0) & (m_b < M)
            mb_safe = jnp.clip(m_b, 0, M - 1)
            x_saved = lax.dynamic_index_in_dim(inbuf, mb_safe % D, 0,
                                               keepdims=False)
            # save this microbatch's input for the backward recompute
            slot_f = mf_safe % D
            prev = lax.dynamic_index_in_dim(inbuf, slot_f, 0, keepdims=False)
            inbuf = lax.dynamic_update_index_in_dim(
                inbuf, jnp.where(valid_f, x_in, prev), slot_f, 0)

            # ---- backward half-tick ------------------------------------
            tgt_b = lax.dynamic_index_in_dim(tgt_mb, mb_safe, 0,
                                             keepdims=False)
            w_b = lax.dynamic_index_in_dim(w_mb, mb_safe, 0, keepdims=False)
            grad_row, d_x, num_raw = lax.switch(
                stage, bwd_branches, x_saved, wire_b, stage_key(mb_safe),
                tgt_b, w_b)
            grad_acc = grad_acc + jnp.where(valid_b, grad_row,
                                            jnp.zeros_like(grad_row))
            num_acc = num_acc + jnp.where(valid_b, num_raw, 0.0)
            d_x = jnp.where(valid_b, d_x, jnp.zeros_like(d_x))

            # ---- the two rings -----------------------------------------
            wire_f = lax.ppermute(out_f, STAGE_AXIS, fwd_ring)
            if cpu_backend:
                # serialize the reverse hop behind the forward one ON THE
                # CPU BACKEND ONLY: the hops are data-independent, and
                # letting the runtime float both (plus branch collectives)
                # concurrently starves XLA:CPU's in-process rendezvous on
                # few-core machines. On TPU the barrier would cost one ICI
                # hop of comm-comm overlap per tick, so it is omitted.
                wire_f, d_x = lax.optimization_barrier((wire_f, d_x))
            wire_b = lax.ppermute(d_x, STAGE_AXIS, bwd_ring)
            return (wire_f, wire_b, inbuf, grad_acc, num_acc, aux_acc), None

        init0 = (jnp.zeros((mb, wire_dim), jnp.float32),
                 jnp.zeros((mb, wire_dim), jnp.float32),
                 jnp.zeros((D, mb, wire_dim), jnp.float32),
                 None,                              # grad_acc: data-invariant
                 jnp.float32(0.0), jnp.float32(0.0))
        init = tuple(
            _pvary_to(jnp.zeros((width,), jnp.float32), vary_axes_nodata)
            if a is None else _pvary_to(a, wire_axes if i < 3 else vary_axes)
            for i, a in enumerate(init0))
        carry, _ = lax.scan(step, init, jnp.arange(T))
        _, _, _, grad_acc, num_acc, aux_acc = carry
        if not HAS_VMA:
            # pre-vma jax: params were never TYPED data-/seq-invariant, so
            # the pullback's implicit gradient psum over those axes (the
            # comment at make_bwd_branch) did not happen — each device holds
            # only its own data (seq) shard's gradient while the out_spec
            # claims data-invariance. Insert the DP all-reduce explicitly.
            # Found by analysis/ (rule unreduced-gradient.missing-reduce);
            # pinned by test_onefb's dp>1 parity cases on old jax.
            grad_acc = lax.psum(grad_acc, DATA_AXIS)
            if seq_on:
                grad_acc = lax.psum(grad_acc, SEQ_AXIS)

        # loss value (reporting): identical reduction to the GPipe engine
        num = lax.psum(lax.psum(num_acc, STAGE_AXIS), DATA_AXIS)
        aux = lax.pmean(lax.psum(aux_acc, STAGE_AXIS) / M, DATA_AXIS)
        if seq_on:
            num = lax.psum(num, SEQ_AXIS)
            aux = lax.pmean(aux, SEQ_AXIS)
        loss = num / jnp.maximum(den_g, 1e-12) + aux
        loss = lax.pmean(loss, MODEL_AXIS)
        if pipe._has_expert:
            loss = lax.pmean(loss, EXPERT_AXIS)
        # grad_acc is already the data-summed gradient (the pullback's
        # implicit psum, see make_bwd_branch) and data-invariant, so the
        # data-unmentioned param-spec output takes one copy per stage row
        return loss, grad_acc.reshape(1, 1, 1, width)

    from jax.sharding import PartitionSpec as P

    # LM targets carry token axes ([M, mb, T]); on a seq mesh the wire's
    # feature axis and the targets' token axis are sharded over it (the
    # host packs one contiguous wire chunk per seq shard, _prep_inputs)
    seq_or_none = SEQ_AXIS if seq_on else None
    tok_axes = len(out_shape) - 1
    tgt_tok = ((seq_or_none,) + (None,) * (tok_axes - 1)) if tok_axes else ()
    return _shard_map(
        per_device,
        mesh=pipe.mesh,
        in_specs=(pipe.param_spec(), P(None, DATA_AXIS, seq_or_none),
                  P(None, DATA_AXIS, *tgt_tok), P(None, DATA_AXIS), P()),
        out_specs=(P(), pipe.param_spec()),
    )
