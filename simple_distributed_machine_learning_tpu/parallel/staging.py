"""Stage packing: heterogeneous per-stage params → one stage-sharded buffer.

The reference places each pipeline stage's parameters on their owning process
as ordinary module attributes, and stitches them together with RRefs
(``/root/reference/simple_distributed.py:52-58,:82-83``). SPMD has no remote
references; instead, ownership is expressed with sharding: all stages' params
are packed into a single ``[n_stages, max_size]`` float buffer sharded
``P('stage')``, so each device physically holds exactly its own stage's
parameters (owner-local, like the reference) while the whole training step
remains one compiled program.

Because stages are heterogeneous (LeNet's conv front vs fc back), each stage's
param pytree is flattened and zero-padded to the size of the largest stage.
``StageMeta`` records the static structure needed to unflatten the local row
back into the stage's pytree inside a ``lax.switch`` branch.

Inter-stage activations use the same trick ("wire format"): every hop carries a
``[microbatch, wire_dim]`` array, with ``wire_encode``/``wire_decode`` padding /
unpadding each stage's real boundary shape. For homogeneous-width models the
pad is zero-cost; for ragged boundaries it costs a copy of the difference —
bandwidth that in exchange lets XLA compile ONE ppermute for the whole
pipeline (the reference instead pays a blocking RPC round-trip per hop,
``simple_distributed.py:49``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def contiguous_split(units: Sequence[Any], n_stages: int) -> list[list]:
    """Assign ``units`` (layers/blocks) contiguously to ``n_stages`` stages,
    earlier stages taking the remainder — THE stage-distribution rule, shared
    by every splittable model builder (models/mlp.py, models/gpt.py) and the
    checkpoint repacker (train/checkpoint.py), so they can never drift."""
    n = len(units)
    if n < n_stages:
        raise ValueError(f"{n} layers cannot fill {n_stages} stages")
    per = [n // n_stages + (1 if i < n % n_stages else 0)
           for i in range(n_stages)]
    out, start = [], 0
    for p in per:
        out.append(list(units[start:start + p]))
        start += p
    return out


@dataclasses.dataclass(frozen=True)
class StageMeta:
    """Static description of one stage's packed parameter layout."""
    treedef: Any
    shapes: tuple[tuple[int, ...], ...]
    sizes: tuple[int, ...]
    total: int


def _flatten_one(params: Any) -> tuple[jax.Array, StageMeta]:
    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    flat = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
            if leaves else jnp.zeros((0,), jnp.float32))
    return flat, StageMeta(treedef, shapes, sizes, int(flat.shape[0]))


def pack_stage_params(stage_params: Sequence[Any]) -> tuple[jax.Array, list[StageMeta]]:
    """Pack per-stage pytrees into a ``[n_stages, max_size]`` f32 buffer.

    Returns the buffer (row s = stage s's flattened params, zero-padded) and
    the per-stage metadata needed by :func:`unpack_stage_params`.
    """
    flats, metas = [], []
    for p in stage_params:
        f, m = _flatten_one(p)
        flats.append(f)
        metas.append(m)
    max_size = max((m.total for m in metas), default=0)
    rows = [jnp.pad(f, (0, max_size - f.shape[0])) for f in flats]
    return jnp.stack(rows), metas


def unpack_stage_params(row: jax.Array, meta: StageMeta) -> Any:
    """Rebuild one stage's param pytree from its packed row (pure reshapes —
    XLA fuses these away; there is no runtime copy on TPU)."""
    leaves = []
    offset = 0
    for shape, size in zip(meta.shapes, meta.sizes):
        leaves.append(jnp.reshape(row[offset:offset + size], shape))
        offset += size
    return jax.tree.unflatten(meta.treedef, leaves)


def pack_stage_grads(tree: Any, meta: StageMeta, width: int) -> jax.Array:
    """In-graph inverse of :func:`unpack_stage_params`: flatten a pytree with
    ``meta``'s leaf order into a zero-padded ``[width]`` f32 row. Used by the
    1F1B engine, whose hand-scheduled backward produces per-stage grad
    pytrees that must ride the same packed layout as the param buffer."""
    leaves = jax.tree.flatten(tree)[0]
    flat = (jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for l in leaves])
            if leaves else jnp.zeros((0,), jnp.float32))
    return jnp.pad(flat, (0, width - flat.shape[0]))


def wire_encode(x: jax.Array, wire_dim: int) -> jax.Array:
    """Flatten per-sample features and zero-pad to the pipeline wire width."""
    flat = jnp.reshape(x, (x.shape[0], -1))
    pad = wire_dim - flat.shape[1]
    if pad < 0:
        raise ValueError(
            f"activation width {flat.shape[1]} exceeds wire_dim {wire_dim}")
    return jnp.pad(flat, ((0, 0), (0, pad))) if pad else flat


def wire_decode(wire: jax.Array, shape: tuple[int, ...]) -> jax.Array:
    """Slice the leading features off the wire and reshape to ``shape``
    (per-sample shape, excluding the batch dim)."""
    size = int(np.prod(shape))
    return jnp.reshape(wire[:, :size], (wire.shape[0],) + tuple(shape))
