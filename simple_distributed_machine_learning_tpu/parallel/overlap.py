"""Latency-hiding collective matmuls: ppermute-chunked, overlap-scheduled.

The monolithic collectives on the tensor-parallel hot paths — the
``lax.psum`` closing every row-parallel matmul (``tensor.tp_pair_apply``),
the backward psum of ``tensor.grad_sync`` — serialize the widest matmuls in
the model against a full blocking all-reduce: the chip idles for the entire
ICI transfer. The framework's thesis (one fused XLA program so transfer
overlaps compute) says they should not.

This module provides the canonical TPU latency-hiding decomposition (Kumar et
al., arXiv:2011.03641; the "collective matmul" of Wang et al., ASPLOS'23):
every monolithic collective becomes a ring of ``lax.ppermute`` hops over the
mesh axis, each hop carrying ``1/mp`` of the tensor, with the matching chunk
of the matmul scheduled against it — XLA's async collective-permute then
runs chunk ``s``'s transfer under chunk ``s+1``'s compute. Primitives:

- :func:`allgather_matmul` — ``allgather(x) @ w`` for a row-sharded ``x``:
  each arriving activation chunk multiplies while the next is in flight
  (the column-parallel layer of a scattered Megatron pair, and the backward
  of :func:`matmul_reducescatter`);
- :func:`matmul_reducescatter` — ``reduce_scatter(x @ w)``: partial products
  ring-shift and accumulate instead of one blocking all-reduce (the
  row-parallel layer of a scattered pair);
- :func:`ring_psum` — chunked all-reduce with a replicated result: drop-in
  for the ``lax.psum`` closing a row-parallel matmul whose activations stay
  replicated (reduce-scatter ring + all-gather ring over column chunks);
- :func:`ring_all_gather` / :func:`ring_reduce_scatter` — the bare data
  movers the matmul forms compose with.

Each differentiable primitive carries a ``custom_vjp`` whose backward pass is
the MIRRORED overlapped schedule (the transpose of an all-gather ring is a
reduce-scatter ring and vice versa), so the backward matmuls hide their ICI
transfer exactly like the forward ones.

Every chunk's compute and hop is wrapped in
:func:`~..utils.profiler.annotate_scope`, so an XProf trace shows the
per-chunk interleave as named regions (``ring_psum/chunk0`` beside
``ring_psum/hop0`` …) instead of one opaque all-reduce bar.

Numerics: ring schedules sum partial products in ring order — a FIXED order
per chunk (device ``c+1``, ``c+2``, …, ``c`` for the chunk ending at device
``c``), so all devices hold bit-identical replicas of replicated results, but
the order differs from XLA's monolithic all-reduce: parity with the ``psum``
path is to float tolerance, not bit-exact (the same caveat as any psum
re-association; pinned by tests/test_overlap.py). With ``mp == 1`` every
primitive degenerates to the plain local matmul/identity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.parallel.compat import (
    axis_size as _axis_size,
)
from simple_distributed_machine_learning_tpu.utils.profiler import (
    annotate_scope,
)

OVERLAP_CHOICES = ("none", "ring")


def check_overlap(overlap: str) -> str:
    if overlap not in OVERLAP_CHOICES:
        raise ValueError(
            f"overlap must be one of {OVERLAP_CHOICES}, got {overlap!r}")
    return overlap


def _fwd_perm(mp: int) -> list[tuple[int, int]]:
    """The ring: device j sends to j+1 (mod mp)."""
    return [(j, (j + 1) % mp) for j in range(mp)]


def _bwd_perm(mp: int) -> list[tuple[int, int]]:
    """The mirrored ring: device j sends to j-1 (mod mp) — the transpose of
    :func:`_fwd_perm`, used by the backward schedules."""
    return [(j, (j - 1) % mp) for j in range(mp)]


def _row_chunk(x: jax.Array, c, n: int) -> jax.Array:
    """Rows ``[c*n, (c+1)*n)`` of ``x`` (``c`` may be traced)."""
    return lax.dynamic_slice_in_dim(x, c * n, n, axis=0)


def _put_row_chunk(buf: jax.Array, chunk: jax.Array, c, n: int) -> jax.Array:
    return lax.dynamic_update_slice_in_dim(buf, chunk, c * n, axis=0)


# ---- bare ring data movers ---------------------------------------------


def _ring_all_gather_impl(x, axis, perm_fn=_fwd_perm, tag="ring_all_gather"):
    """[n, ...] shard -> [mp*n, ...] gathered along axis 0, via mp-1 hops."""
    mp = _axis_size(axis)
    if mp == 1:
        return x
    n = x.shape[0]
    i = lax.axis_index(axis)
    perm = perm_fn(mp)
    sign = 1 if perm_fn is _fwd_perm else -1
    out = jnp.zeros((mp * n,) + x.shape[1:], x.dtype)
    out = _put_row_chunk(out, x, i, n)
    have = x
    for s in range(1, mp):
        with annotate_scope(f"{tag}/hop{s - 1}"):
            have = lax.ppermute(have, axis, perm)
        # after s forward hops we hold the chunk that originated s devices
        # back around the ring
        with annotate_scope(f"{tag}/chunk{s}"):
            out = _put_row_chunk(out, have, (i - sign * s) % mp, n)
    return out


def _ring_reduce_scatter_impl(x, axis, perm_fn=_fwd_perm,
                              tag="ring_reduce_scatter"):
    """[mp*n, ...] per-device partials -> [n, ...] chunk ``i`` of the sum.

    The accumulator for the chunk ending at device ``c`` starts at device
    ``c+1`` and visits ``c+2, …, c`` — a fixed summation order per chunk, so
    a following all-gather yields bit-identical replicas.
    """
    mp = _axis_size(axis)
    if mp == 1:
        return x
    if x.shape[0] % mp:
        raise ValueError(
            f"ring_reduce_scatter: leading axis {x.shape[0]} not divisible "
            f"by axis size {mp}")
    n = x.shape[0] // mp
    i = lax.axis_index(axis)
    perm = perm_fn(mp)
    sign = 1 if perm_fn is _fwd_perm else -1
    with annotate_scope(f"{tag}/chunk0"):
        acc = _row_chunk(x, (i - sign) % mp, n)
    for s in range(1, mp):
        with annotate_scope(f"{tag}/hop{s - 1}"):
            acc = lax.ppermute(acc, axis, perm)
        with annotate_scope(f"{tag}/chunk{s}"):
            acc = acc + _row_chunk(x, (i - sign * (s + 1)) % mp, n)
    return acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ring_all_gather(x: jax.Array, axis: str) -> jax.Array:
    """All-gather ``x`` along its leading axis via a ppermute ring.

    Call inside ``shard_map``: ``x [n, ...]`` is this device's row shard
    (shard ``i`` = global rows ``[i*n, (i+1)*n)``); returns the gathered
    ``[mp*n, ...]``, identical on every device. Backward is the mirrored
    reduce-scatter ring.
    """
    return _ring_all_gather_impl(x, axis)


def _ring_all_gather_fwd(x, axis):
    return _ring_all_gather_impl(x, axis), None


def _ring_all_gather_bwd(axis, _, ct):
    # y[chunk c] = x_c on EVERY device: dx = psum(ct)[chunk i], i.e. the
    # mirrored reduce-scatter ring
    return (_ring_reduce_scatter_impl(ct, axis, perm_fn=_bwd_perm,
                                      tag="ring_all_gather_bwd"),)


ring_all_gather.defvjp(_ring_all_gather_fwd, _ring_all_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ring_reduce_scatter(x: jax.Array, axis: str) -> jax.Array:
    """Reduce-scatter per-device partials via a ppermute ring.

    Call inside ``shard_map``: every device holds partial ``x [mp*n, ...]``;
    device ``i`` returns rows ``[i*n, (i+1)*n)`` of ``psum(x)`` (summed in
    ring order — see module docstring). Backward is the mirrored all-gather
    ring.
    """
    return _ring_reduce_scatter_impl(x, axis)


def _ring_reduce_scatter_fwd(x, axis):
    return _ring_reduce_scatter_impl(x, axis), None


def _ring_reduce_scatter_bwd(axis, _, ct):
    return (_ring_all_gather_impl(ct, axis, perm_fn=_bwd_perm,
                                  tag="ring_reduce_scatter_bwd"),)


ring_reduce_scatter.defvjp(_ring_reduce_scatter_fwd,
                           _ring_reduce_scatter_bwd)


# ---- chunked all-reduce (replicated result) ----------------------------


def _ring_psum_impl(x, axis, perm_fn=_fwd_perm, tag="ring_psum"):
    """All-reduce with a replicated, bit-identical-across-devices result,
    as a reduce-scatter ring + all-gather ring over column chunks.

    Falls back to one ``lax.psum`` when the last axis does not divide by the
    ring size (the chunks must be equal for static shapes).
    """
    mp = _axis_size(axis)
    if mp == 1:
        return x
    d = x.shape[-1]
    if d % mp:
        return lax.psum(x, axis)
    # chunk the LAST axis (the matmul output features): move it leading so
    # the row-chunk ring helpers apply, then restore
    xt = jnp.moveaxis(x, -1, 0)
    acc = _ring_reduce_scatter_impl(xt, axis, perm_fn=perm_fn, tag=tag)
    full = _ring_all_gather_impl(acc, axis, perm_fn=perm_fn, tag=tag + "/ag")
    return jnp.moveaxis(full, 0, -1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ring_psum(x: jax.Array, axis: str) -> jax.Array:
    """Drop-in for ``lax.psum(x, axis)`` with the transfer chunked over a
    ppermute ring so each chunk's hop hides under another chunk's add.

    Same value on every device (bit-identical across devices; equal to the
    monolithic psum to float tolerance — ring summation order). Same
    cotangent accounting as ``lax.psum`` inside ``shard_map``: the backward
    pass psums the per-device cotangents — here with the mirrored ring, so
    the gradient all-reduce overlaps too.
    """
    return _ring_psum_impl(x, axis)


def _ring_psum_fwd(x, axis):
    return _ring_psum_impl(x, axis), None


def _ring_psum_bwd(axis, _, ct):
    # transpose of psum inside shard_map is psum of the per-device
    # cotangents (how the full cotangent is reassembled from per-replica
    # splits — see tensor.grad_sync); mirrored ring direction
    return (_ring_psum_impl(ct, axis, perm_fn=_bwd_perm,
                            tag="ring_psum_bwd"),)


ring_psum.defvjp(_ring_psum_fwd, _ring_psum_bwd)


# ---- collective matmuls ------------------------------------------------


def _allgather_matmul_impl(x, w, axis, perm_fn=_fwd_perm,
                           tag="allgather_matmul"):
    """y = allgather(x) @ w, chunk-at-a-time: multiply the held activation
    chunk while the next one rides the ring."""
    mp = _axis_size(axis)
    if mp == 1:
        return x @ w
    n = x.shape[0]
    i = lax.axis_index(axis)
    perm = perm_fn(mp)
    sign = 1 if perm_fn is _fwd_perm else -1
    out = jnp.zeros((mp * n, w.shape[-1]), jnp.result_type(x, w))
    chunk = x
    for s in range(mp):
        if s + 1 < mp:
            # issue the NEXT chunk's hop before this chunk's matmul: XLA's
            # async collective-permute then runs under the compute
            with annotate_scope(f"{tag}/hop{s}"):
                nxt = lax.ppermute(chunk, axis, perm)
        with annotate_scope(f"{tag}/chunk{s}"):
            out = _put_row_chunk(out, chunk @ w, (i - sign * s) % mp, n)
        if s + 1 < mp:
            chunk = nxt
    return out


def _matmul_reducescatter_impl(x, w, axis, perm_fn=_fwd_perm,
                               tag="matmul_reducescatter"):
    """y = reduce_scatter(x @ w): each row-chunk's partial product computes
    while the accumulator for the previous chunk rides the ring."""
    mp = _axis_size(axis)
    if mp == 1:
        return x @ w
    if x.shape[0] % mp:
        raise ValueError(
            f"matmul_reducescatter: {x.shape[0]} rows not divisible by axis "
            f"size {mp}")
    n = x.shape[0] // mp
    i = lax.axis_index(axis)
    perm = perm_fn(mp)
    sign = 1 if perm_fn is _fwd_perm else -1
    with annotate_scope(f"{tag}/chunk0"):
        acc = _row_chunk(x, (i - sign) % mp, n) @ w
    for s in range(1, mp):
        with annotate_scope(f"{tag}/hop{s - 1}"):
            acc = lax.ppermute(acc, axis, perm)
        # the incoming hop and this chunk's matmul are independent: XLA
        # overlaps them, the add joins them after
        with annotate_scope(f"{tag}/chunk{s}"):
            acc = acc + _row_chunk(x, (i - sign * (s + 1)) % mp, n) @ w
    return acc


def _gatherT_matmul_impl(x, dy, axis, n_rows, perm_fn=_fwd_perm,
                         tag="gatherT_matmul"):
    """dw = allgather(x)^T @ dy without materializing the gather: circulate
    the ``x`` chunks and accumulate ``x_c^T @ dy[rows c]`` per hop. ``dy``
    is local ``[mp*n_rows, k]``; ``x`` is this device's ``[n_rows, d]``."""
    mp = _axis_size(axis)
    if mp == 1:
        return x.T @ dy
    i = lax.axis_index(axis)
    perm = perm_fn(mp)
    sign = 1 if perm_fn is _fwd_perm else -1
    acc = jnp.zeros((x.shape[-1], dy.shape[-1]), jnp.result_type(x, dy))
    chunk = x
    for s in range(mp):
        if s + 1 < mp:
            with annotate_scope(f"{tag}/hop{s}"):
                nxt = lax.ppermute(chunk, axis, perm)
        with annotate_scope(f"{tag}/chunk{s}"):
            c = (i - sign * s) % mp
            acc = acc + chunk.T @ _row_chunk(dy, c, n_rows)
        if s + 1 < mp:
            chunk = nxt
    return acc


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def allgather_matmul(x: jax.Array, w: jax.Array, axis: str) -> jax.Array:
    """``allgather(x, axis) @ w`` with the gather ring hidden under the
    chunk matmuls — the column-parallel collective matmul.

    Call inside ``shard_map`` over ``axis`` (size ``mp``):

    - ``x [n, d]``: this device's row shard of the global activation
      ``[mp*n, d]`` (shard ``i`` = rows ``[i*n, (i+1)*n)``);
    - ``w [d, k]``: this device's weight (typically a column shard);
    - returns ``[mp*n, k]`` — every gathered chunk multiplied against the
      local weight, chunk ``s``'s matmul overlapping chunk ``s+1``'s hop.

    Backward is the mirrored schedule: ``dx`` via
    :func:`matmul_reducescatter` of ``dy @ w^T`` (reversed ring), ``dw`` by
    circulating the saved ``x`` chunks against ``dy``.
    """
    return _allgather_matmul_impl(x, w, axis)


def _allgather_matmul_fwd(x, w, axis):
    return _allgather_matmul_impl(x, w, axis), (x, w)


def _allgather_matmul_bwd(axis, res, dy):
    x, w = res
    # dx_i = psum_j(dy_j @ w_j^T)[rows i]: the mirrored matmul+reduce-scatter
    dx = _matmul_reducescatter_impl(dy, w.T, axis, perm_fn=_bwd_perm,
                                    tag="allgather_matmul_bwd_dx")
    # dw = allgather(x)^T @ dy, re-circulating x chunk-by-chunk
    dw = _gatherT_matmul_impl(x, dy, axis, x.shape[0], perm_fn=_bwd_perm,
                              tag="allgather_matmul_bwd_dw")
    return dx, dw


allgather_matmul.defvjp(_allgather_matmul_fwd, _allgather_matmul_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_reducescatter(x: jax.Array, w: jax.Array, axis: str) -> jax.Array:
    """``reduce_scatter(x @ w, axis)`` with the partial products ring-shifted
    and accumulated instead of one blocking all-reduce — the row-parallel
    collective matmul.

    Call inside ``shard_map`` over ``axis`` (size ``mp``):

    - ``x [N, d]``: local activations (``N`` divisible by ``mp``), typically
      against a contracting-dim weight shard ``w [d, k]``;
    - returns rows ``[i*N/mp, (i+1)*N/mp)`` of ``psum(x @ w)`` on device
      ``i`` (ring summation order — see module docstring).

    Backward is the mirrored schedule: ``dx`` via :func:`allgather_matmul`
    of ``dy`` against ``w^T`` (reversed ring), ``dw`` by circulating the
    ``dy`` chunks against the saved ``x`` rows.
    """
    return _matmul_reducescatter_impl(x, w, axis)


def _matmul_reducescatter_fwd(x, w, axis):
    return _matmul_reducescatter_impl(x, w, axis), (x, w)


def _matmul_reducescatter_bwd(axis, res, dy):
    x, w = res
    mp = _axis_size(axis)
    n = x.shape[0] // mp
    # d(x@w) = allgather(dy) (each device's dy is the cotangent of its row
    # chunk of the summed product): dx = allgather_matmul(dy, w^T)
    dx = _allgather_matmul_impl(dy, w.T, axis, perm_fn=_bwd_perm,
                                tag="matmul_reducescatter_bwd_dx")
    # dw = x^T @ allgather(dy): circulate the dy chunks against x's rows
    i = lax.axis_index(axis)
    perm = _bwd_perm(mp)
    acc = jnp.zeros((w.shape[0], w.shape[1]), jnp.result_type(x, dy))
    chunk = dy
    for s in range(mp):
        if s + 1 < mp:
            with annotate_scope(f"matmul_reducescatter_bwd_dw/hop{s}"):
                nxt = lax.ppermute(chunk, axis, perm)
        with annotate_scope(f"matmul_reducescatter_bwd_dw/chunk{s}"):
            c = (i + s) % mp
            acc = acc + _row_chunk(x, c, n).T @ chunk
        if s + 1 < mp:
            chunk = nxt
    return dx, acc


matmul_reducescatter.defvjp(_matmul_reducescatter_fwd,
                            _matmul_reducescatter_bwd)
