"""Sequence/context parallelism: Ulysses-style all-to-all attention.

Long-context support is first-class in this framework (the reference has no
sequence axis at all — conv+FC on 28x28 images, SURVEY §5.7). Two
complementary strategies shard the sequence over a mesh axis:

- **ring attention** (:func:`~..ops.attention.ring_attention`): K/V blocks
  rotate around the device ring via ``lax.ppermute``; memory per device is
  O(T_local), communication is S-1 neighbor hops riding ICI. Best when T is
  huge and heads are few.
- **Ulysses** (this module): two ``lax.all_to_all`` collectives re-shard
  [B, T/s, H, Dh] -> [B, T, H/s, Dh] around a *local full-sequence* attention
  over the device's head subset. One pair of all-to-alls per attention call,
  each moving the same bytes as one ring hop — fewer, larger transfers, so it
  wins when the mesh axis divides the head count and T is moderate.

Both are plain functions called inside ``shard_map`` and compose with the
pipeline's ``stage`` axis and the ``data`` axis. Output matches the dense
single-device :func:`~..ops.attention.causal_attention` to float tolerance
(tests/test_sequence_parallel.py).
"""

from __future__ import annotations

import jax
from jax import lax

from simple_distributed_machine_learning_tpu.parallel.compat import (
    axis_size as _axis_size,
)

from simple_distributed_machine_learning_tpu.ops.attention import (
    SEQ_AXIS,
    causal_attention_core,
)


def ulysses_attention(params: dict, x: jax.Array, n_heads: int,
                      axis: str = SEQ_AXIS) -> jax.Array:
    """Causal MHA with the sequence sharded over mesh axis ``axis``.

    Call inside ``shard_map``: ``x`` is this device's sequence chunk
    ``[B, T_local, D]`` (chunk i = global positions
    ``[i*T_local, (i+1)*T_local)``). The axis size must divide ``n_heads``
    (each device ends up owning ``n_heads / axis_size`` whole heads).

    Data movement (DeepSpeed-Ulysses recipe, re-derived for XLA collectives):
    project locally to q/k/v ``[B, T_local, H, Dh]``; ``all_to_all`` scatters
    the head axis and gathers the sequence axis, giving each device the FULL
    sequence for ``H/s`` heads; plain causal attention runs locally (no masks
    crossing devices — causality is exact); the reverse ``all_to_all``
    restores sequence sharding for the output projection.
    """
    s = _axis_size(axis)
    if n_heads % s:
        raise ValueError(f"{n_heads} heads not divisible by axis size {s}")
    b, t_loc, d = x.shape
    dh = d // n_heads

    def qkv(w):
        return (x @ w).reshape(b, t_loc, n_heads, dh)

    q, k, v = qkv(params["wq"]), qkv(params["wk"]), qkv(params["wv"])

    def scatter_heads(a):
        # [B, T_loc, H, Dh] -> [B, T_loc*s, H/s, Dh]: split heads across the
        # axis, concatenate the sequence chunks (tiled=True keeps them ordered)
        return lax.all_to_all(a, axis, split_axis=2, concat_axis=1, tiled=True)

    q, k, v = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    # local dense causal attention over the full sequence, head subset
    o = causal_attention_core(q.transpose(0, 2, 1, 3),   # [B, H/s, T, Dh]
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3))
    o = o.transpose(0, 2, 1, 3)      # [B, T, H/s, Dh]
    # reverse: gather heads, scatter sequence -> [B, T_loc, H, Dh]
    o = lax.all_to_all(o, axis, split_axis=1, concat_axis=2, tiled=True)
    return o.reshape(b, t_loc, d) @ params["wo"]
