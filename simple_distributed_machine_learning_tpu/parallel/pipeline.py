"""The pipeline engine: GPipe-scheduled SPMD pipeline parallelism.

This is the TPU-native replacement for the reference's entire hot path — the
blocking master→worker activation RPC (``/root/reference/simple_distributed.py:49``),
the worker→master reply (``:80``), the distributed-autograd backward hop
(``:109-112``), and the remote optimizer step (``:113``). All of it compiles
into ONE ``jit``-ed SPMD program:

- every device runs the same scanned loop; at step ``t`` the device holding
  stage ``s`` computes microbatch ``t - s`` (GPipe schedule);
- the inter-stage hop is a single ``lax.ppermute`` over the ``stage`` mesh
  axis — on TPU this is a compiled collective-permute over ICI, overlapped by
  XLA with the next step's compute (the reference's RPC hop is fully blocking:
  per-step time = t(stage0) + 2·t(transfer) + t(stage1), SURVEY §3.3);
- backward needs no distributed-autograd engine: ``jax.grad`` through
  ``ppermute`` emits the transposed permute, so activation cotangents hop
  stage ``s+1`` → ``s`` inside the same compiled program;
- heterogeneous stages (conv front / fc back, as in the reference's
  Network1/Network2 split ``:26-83``) are dispatched with ``lax.switch`` on
  the device's stage index, over the packed stage-sharded parameter buffer
  (see ``staging.py``).

The sequential reference schedule is the ``n_microbatches=1`` special case;
a fused single-device model is the ``n_stages=1`` special case — which is what
makes loss-parity tests against a single-device run exact (SURVEY §7, test #1).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    StageMeta,
    pack_stage_params,
    unpack_stage_params,
    wire_decode,
    wire_encode,
)


@dataclasses.dataclass(frozen=True)
class Stage:
    """One pipeline stage.

    ``apply(params, x, key, deterministic) -> y`` operates on real (unpadded)
    activations: ``x`` has per-sample shape ``in_shape``; ``y``'s trailing
    features are re-encoded onto the wire by the engine. The last stage must
    return log-probabilities ``[batch, out_dim]`` (the reference's stage 1
    ends in ``log_softmax``, ``simple_distributed.py:79``).

    ``shards``: optional per-model-shard params for tensor parallelism — a
    tuple of ``n_model`` pytrees (identical tree structure and leaf shapes).
    When set, ``apply`` receives THIS device's shard and may use collectives
    over the ``model`` mesh axis (e.g. ``tensor.tp_pair_apply``); every model
    shard must return the same (replicated) activation, i.e. finish each
    sharded group with its psum. When ``shards`` is None on a mesh with
    ``n_model > 1``, ``params`` is replicated to every model slot and the
    stage computes redundantly (correct, just not sharded).
    """
    apply: Callable[[Any, jax.Array, jax.Array, bool], jax.Array]
    params: Any
    in_shape: tuple[int, ...]
    shards: tuple | None = None


class Pipeline:
    """Compiled GPipe pipeline over a ``(data, stage)`` mesh.

    Parameters live in a ``[n_stages, max_param_size]`` buffer sharded
    ``P('stage')`` — each device holds only its own stage's params
    (owner-local, like the reference's per-process modules) and updates them
    locally inside the compiled step (replacing DistributedOptimizer,
    ``simple_distributed.py:100-104``).
    """

    def __init__(self, stages: Sequence[Stage], mesh: jax.sharding.Mesh,
                 wire_dim: int, out_dim: int | tuple[int, ...],
                 n_microbatches: int = 1, compute_dtype=None,
                 remat: bool = False):
        self.stages = list(stages)
        self.mesh = mesh
        self.n_stages = mesh.shape[STAGE_AXIS]
        self.n_data = mesh.shape[DATA_AXIS]
        self.n_model = mesh.shape.get(MODEL_AXIS, 1)
        if len(self.stages) != self.n_stages:
            raise ValueError(
                f"{len(self.stages)} stages but mesh stage axis is {self.n_stages}")
        self.wire_dim = int(wire_dim)
        # per-sample output shape; last axis = classes. (C,) for classifiers,
        # (T, V) for per-token language-model log-probs
        self.out_shape = ((int(out_dim),) if isinstance(out_dim, int)
                          else tuple(int(d) for d in out_dim))
        self.out_dim = self.out_shape[-1]
        self.n_microbatches = int(n_microbatches)
        # mixed precision: params and activations are cast to compute_dtype
        # around each stage apply (bfloat16 doubles MXU throughput and halves
        # HBM traffic); master params, the wire, and the loss stay float32.
        # remat: stage applies recompute in backward (jax.checkpoint), trading
        # FLOPs for activation memory — the standard deep-pipeline trade.
        self.compute_dtype = compute_dtype
        self.remat = bool(remat)
        self._sm_cache: dict[bool, Callable] = {}
        # param buffer rows: one per (stage, model-shard). Stages without
        # shards are replicated across the model axis (redundant compute,
        # identical grads — the data-axis story, one level down).
        per_shard: list[Any] = []
        for s in self.stages:
            if s.shards is not None:
                if len(s.shards) != self.n_model:
                    raise ValueError(
                        f"stage has {len(s.shards)} model shards, mesh model "
                        f"axis is {self.n_model}")
                per_shard.extend(s.shards)
            else:
                per_shard.extend([s.params] * self.n_model)
        flat, metas_all = pack_stage_params(per_shard)
        import numpy as np
        # keep the master copy on the HOST: device_put of an on-device array
        # with a matching sharding ALIASES it, and a later donated train step
        # would delete the alias — init_params() must survive any number of
        # donating steps
        self._buf0 = np.asarray(
            jax.device_get(flat.reshape(self.n_stages, self.n_model, -1)))
        # shard 0's layout stands for the stage (shards are shape-identical)
        self.metas = metas_all[:: self.n_model]
        for s, stage in enumerate(self.stages):
            if stage.shards is not None:
                m0 = metas_all[s * self.n_model]
                for m in metas_all[s * self.n_model:(s + 1) * self.n_model]:
                    if m.shapes != m0.shapes:
                        raise ValueError(
                            f"stage {s}: model shards have differing leaf "
                            f"shapes — tensor-parallel shards must split "
                            f"evenly")
        self._validate_boundaries()

    def _validate_boundaries(self) -> None:
        """Shape-check every stage hop at build time (via eval_shape — no FLOPs).

        The wire codec zero-pads/truncates, so a stage whose output width does
        not match the next stage's ``in_shape`` would otherwise train silently
        on fabricated zeros.
        """
        import numpy as np
        batch = 2
        for s, stage in enumerate(self.stages):
            if stage.shards is not None:
                # tensor-parallel applies use mesh collectives, which have no
                # meaning under eval_shape outside shard_map — the first real
                # trace still shape-checks them, just with a deeper trace
                continue
            x = jax.ShapeDtypeStruct((batch,) + tuple(stage.in_shape), jnp.float32)
            key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
            out = jax.eval_shape(
                lambda p, xx, kk, _a=stage.apply: _a(p, xx, kk, True),
                stage.params, x, key)
            out_size = int(np.prod(out.shape[1:]))
            if out_size > self.wire_dim:
                raise ValueError(
                    f"stage {s} output width {out_size} exceeds wire_dim "
                    f"{self.wire_dim}")
            if s + 1 < len(self.stages):
                nxt = int(np.prod(self.stages[s + 1].in_shape))
                if out_size != nxt:
                    raise ValueError(
                        f"stage {s} outputs {out_size} features but stage "
                        f"{s + 1} declares in_shape={self.stages[s + 1].in_shape} "
                        f"({nxt} features)")
            elif out.shape[1:] != self.out_shape:
                raise ValueError(
                    f"last stage must output [batch, *{self.out_shape}], got "
                    f"{out.shape}")
            if int(np.prod(stage.in_shape)) > self.wire_dim:
                raise ValueError(
                    f"stage {s} in_shape {stage.in_shape} exceeds wire_dim "
                    f"{self.wire_dim}")

    # ---- parameters -----------------------------------------------------

    def param_spec(self) -> P:
        """PartitionSpec of the packed ``[n_stages, n_model, P]`` buffer."""
        return P(STAGE_AXIS, MODEL_AXIS, None)

    def init_params(self) -> jax.Array:
        """Place the packed stage-param buffer on the mesh (stage- and
        model-shard-sharded; replicated over the data axis)."""
        sharding = NamedSharding(self.mesh, self.param_spec())
        return jax.device_put(self._buf0, sharding)

    def unpack(self, buf: jax.Array) -> list[Any]:
        """Host-side: recover the per-stage param pytrees (for tests/ckpt).
        For model-sharded stages the entry is the list of per-shard trees."""
        rows = jax.device_get(buf)
        out = []
        for s in range(self.n_stages):
            trees = [unpack_stage_params(jnp.asarray(rows[s, m]), self.metas[s])
                     for m in range(self.n_model)]
            out.append(trees if self.stages[s].shards is not None else trees[0])
        return out

    # ---- forward/loss ---------------------------------------------------

    def _shard_fn(self, deterministic: bool) -> Callable:
        """Build (once per mode) the shard_mapped pipeline loss function."""
        if deterministic in self._sm_cache:
            return self._sm_cache[deterministic]

        S = self.n_stages
        M = self.n_microbatches
        T = M + S - 1
        wire_dim = self.wire_dim
        out_shape = self.out_shape
        metas = list(self.metas)
        applies = [s.apply for s in self.stages]
        in_shapes = [s.in_shape for s in self.stages]
        n_model = self.n_model
        # stages without model shards compute redundantly on every model slot;
        # their params need the grad_sync treatment (see tensor.grad_sync) so
        # each replica receives the full, not 1/n_model, gradient
        replicated_over_model = [s.shards is None for s in self.stages]
        compute_dtype = self.compute_dtype
        remat = self.remat

        def per_device(row3d, x_mb, tgt_mb, w_mb, key):
            # row3d: [1, 1, P] this device's (stage, model-shard) param row;
            # x_mb: [M, mb, wire]; tgt_mb/w_mb: [M, mb] targets and weights
            row = row3d[0, 0]
            stage = lax.axis_index(STAGE_AXIS)
            mb = x_mb.shape[1]

            def make_branch(s):
                def branch(wire, k):
                    params = unpack_stage_params(row, metas[s])
                    if n_model > 1 and replicated_over_model[s]:
                        from simple_distributed_machine_learning_tpu.parallel.tensor import (
                            grad_sync,
                        )
                        params = jax.tree.map(
                            lambda a: grad_sync(a, MODEL_AXIS), params)
                    x = wire_decode(wire, in_shapes[s])
                    if compute_dtype is not None:
                        params = jax.tree.map(
                            lambda a: a.astype(compute_dtype), params)
                        x = x.astype(compute_dtype)
                    y = applies[s](params, x, k, deterministic)
                    return wire_encode(y.astype(jnp.float32), wire_dim)
                if remat:
                    return jax.checkpoint(branch)
                return branch

            branches = [make_branch(s) for s in range(S)]
            fwd = [(i, (i + 1) % S) for i in range(S)]

            def step(carry, t):
                wire, num_acc, den_acc, logits_acc = carry
                # stage 0 injects a fresh microbatch every step (clipped so the
                # drain steps recompute-and-discard the last one — finite math,
                # zeroed below by the validity mask).
                inj = lax.dynamic_index_in_dim(
                    x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                wire = jnp.where(stage == 0, inj, wire)
                # distinct dropout noise per (step, stage, data-shard)
                k_t = jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(key, t), stage),
                    lax.axis_index(DATA_AXIS))
                out = lax.switch(stage, branches, wire, k_t)
                m = t - stage           # microbatch index this stage is working on
                valid = (m >= 0) & (m < M)
                out = jnp.where(valid, out, jnp.zeros_like(out))
                # last stage just produced log-probs for microbatch m
                logits = wire_decode(out, out_shape)
                is_out = valid & (stage == S - 1)
                m_safe = jnp.clip(m, 0, M - 1)
                tgt = lax.dynamic_index_in_dim(tgt_mb, m_safe, 0, keepdims=False)
                w = lax.dynamic_index_in_dim(w_mb, m_safe, 0, keepdims=False)
                # per-sample weights broadcast over any token axes (e.g. the
                # sequence axis of a per-token LM loss)
                nll = nll_loss(logits, tgt, "none")
                wb = w.reshape(w.shape + (1,) * (nll.ndim - 1))
                per_tok = jnp.broadcast_to(wb, nll.shape)
                num_acc = num_acc + jnp.where(is_out, jnp.sum(nll * per_tok), 0.0)
                den_acc = den_acc + jnp.where(is_out, jnp.sum(per_tok), 0.0)
                prev = lax.dynamic_index_in_dim(logits_acc, m_safe, 0, keepdims=False)
                logits_acc = lax.dynamic_update_index_in_dim(
                    logits_acc, jnp.where(is_out, logits, prev), m_safe, 0)
                # the hop: stage s -> s+1 over ICI; autodiff transposes this
                # into the backward s+1 -> s hop.
                wire = lax.ppermute(out, STAGE_AXIS, fwd)
                return (wire, num_acc, den_acc, logits_acc), None

            init = (jnp.zeros((mb, wire_dim), x_mb.dtype),
                    jnp.float32(0.0), jnp.float32(0.0),
                    jnp.zeros((M, mb) + out_shape, jnp.float32))
            (_, num, den, logits_acc), _ = lax.scan(step, init, jnp.arange(T))

            # weighted global mean: sum(w * nll) / sum(w), reduced over the
            # stage axis (only the last stage contributed) and the data axis.
            num = lax.psum(lax.psum(num, STAGE_AXIS), DATA_AXIS)
            den = lax.psum(lax.psum(den, STAGE_AXIS), DATA_AXIS)
            loss = num / jnp.maximum(den, 1e-12)
            logits = lax.psum(logits_acc, STAGE_AXIS)     # replicate last stage's
            return loss, logits

        fn = jax.shard_map(
            per_device,
            mesh=self.mesh,
            # activations/targets are replicated over the model axis (left
            # unmentioned); TP stages shard their compute internally and
            # restore replication with their own psums
            in_specs=(P(STAGE_AXIS, MODEL_AXIS, None), P(None, DATA_AXIS, None),
                      P(None, DATA_AXIS), P(None, DATA_AXIS), P()),
            out_specs=(P(), P(None, DATA_AXIS)),
            check_vma=False,
        )
        self._sm_cache[deterministic] = fn
        return fn

    def loss_and_logits(self, buf: jax.Array, x: jax.Array, targets: jax.Array,
                        key: jax.Array, deterministic: bool = False,
                        weights: jax.Array | None = None
                        ) -> tuple[jax.Array, jax.Array]:
        """Weighted-mean NLL loss + per-example log-probs for a global batch.

        ``x``: [B, ...] model input (stage 0's real input shape);
        ``targets``: [B] int labels; ``weights``: optional [B] per-sample loss
        weights (e.g. a 0/1 validity mask for a zero-padded ragged batch —
        loss = sum(w·nll)/sum(w), so padding does not dilute the mean). B must
        divide by ``n_microbatches * n_data``.
        """
        import jax.numpy as jnp

        M = self.n_microbatches
        B = x.shape[0]
        if B % (M * self.n_data) != 0:
            raise ValueError(
                f"batch {B} not divisible by microbatches*data = {M * self.n_data}")
        if (self.n_stages == 1 and self.n_data == 1 and self.n_model == 1
                and self.stages[0].shards is None):
            # degenerate mesh: the pipeline IS the fused model. Skip the
            # shard_map engine — its packed-row unpack/repack costs ~10x the
            # model itself at this scale (grad of the slice/concat machinery),
            # with nothing to overlap on one device.
            return self._fused_loss(buf, x, targets, key, deterministic,
                                    weights)
        # the wire is always float32 (stages decode/cast as needed — e.g. the
        # GPT embedding stage reads token ids back out of the float wire)
        xw = wire_encode(x, self.wire_dim).astype(jnp.float32).reshape(
            M, B // M, self.wire_dim)
        tgt = targets.reshape((M, B // M) + self.out_shape[:-1])
        w = (jnp.ones((B,), jnp.float32) if weights is None
             else weights.astype(jnp.float32)).reshape(M, B // M)
        loss, logits = self._shard_fn(deterministic)(buf, xw, tgt, w, key)
        return loss, logits.reshape((B,) + self.out_shape)

    def _fused_loss(self, buf, x, targets, key, deterministic, weights):
        """Single-device fast path. Identical to the engine for
        ``n_microbatches == 1`` or deterministic mode (same RNG stream: the
        engine's stage-0 key at step 0 on data shard 0); with several
        microbatches AND dropout the engine draws per-microbatch noise while
        this path draws one batch-wide key — same distribution, different
        stream."""
        import jax.numpy as jnp

        B = x.shape[0]
        stage = self.stages[0]
        params = unpack_stage_params(buf[0, 0], self.metas[0])
        xs = x.reshape((B,) + tuple(stage.in_shape))
        if self.compute_dtype is not None:
            params = jax.tree.map(
                lambda a: a.astype(self.compute_dtype), params)
            xs = xs.astype(self.compute_dtype)
        k = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(key, 0), 0), 0)
        logp = stage.apply(params, xs, k, deterministic).astype(jnp.float32)
        nll = nll_loss(logp, targets, "none")
        w = (jnp.ones((B,), jnp.float32) if weights is None
             else weights.astype(jnp.float32))
        wb = jnp.broadcast_to(
            w.reshape(w.shape + (1,) * (nll.ndim - 1)), nll.shape)
        loss = jnp.sum(nll * wb) / jnp.maximum(jnp.sum(wb), 1e-12)
        return loss, logp


def fused_reference(stages: Sequence[Stage]) -> Callable:
    """Single-device composition of the stages (ground truth for parity tests:
    the pipeline on N devices must match this to float tolerance, SURVEY §7)."""
    def apply(stage_params: Sequence[Any], x: jax.Array, key: jax.Array,
              deterministic: bool = False) -> jax.Array:
        h = x
        for s, (stage, params) in enumerate(zip(stages, stage_params)):
            k = jax.random.fold_in(key, s)
            h = h.reshape((h.shape[0],) + stage.in_shape)
            h = stage.apply(params, h, k, deterministic)
        return h
    return apply
