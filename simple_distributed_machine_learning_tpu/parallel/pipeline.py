"""The pipeline engine: GPipe-scheduled SPMD pipeline parallelism.

This is the TPU-native replacement for the reference's entire hot path — the
blocking master→worker activation RPC (``/root/reference/simple_distributed.py:49``),
the worker→master reply (``:80``), the distributed-autograd backward hop
(``:109-112``), and the remote optimizer step (``:113``). All of it compiles
into ONE ``jit``-ed SPMD program:

- every device runs the same scanned loop; at step ``t`` the device holding
  stage ``s`` computes microbatch ``t - s`` (GPipe schedule);
- the inter-stage hop is a single ``lax.ppermute`` over the ``stage`` mesh
  axis — on TPU this is a compiled collective-permute over ICI, overlapped by
  XLA with the next step's compute (the reference's RPC hop is fully blocking:
  per-step time = t(stage0) + 2·t(transfer) + t(stage1), SURVEY §3.3);
- backward needs no distributed-autograd engine: ``jax.grad`` through
  ``ppermute`` emits the transposed permute, so activation cotangents hop
  stage ``s+1`` → ``s`` inside the same compiled program;
- heterogeneous stages (conv front / fc back, as in the reference's
  Network1/Network2 split ``:26-83``) are dispatched with ``lax.switch`` on
  the device's stage index, over the packed stage-sharded parameter buffer
  (see ``staging.py``).

The sequential reference schedule is the ``n_microbatches=1`` special case;
a fused single-device model is the ``n_stages=1`` special case — which is what
makes loss-parity tests against a single-device run exact (SURVEY §7, test #1).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    STAGE_AXIS,
)


from simple_distributed_machine_learning_tpu.parallel.compat import (
    pvary_to as _pvary_to,
    shard_map as _shard_map,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    StageMeta,
    pack_stage_params,
    unpack_stage_params,
    wire_decode,
    wire_encode,
)


@dataclasses.dataclass(frozen=True)
class Stage:
    """One pipeline stage.

    ``apply(params, x, key, deterministic) -> y`` operates on real (unpadded)
    activations: ``x`` has per-sample shape ``in_shape``; ``y``'s trailing
    features are re-encoded onto the wire by the engine. The last stage must
    return log-probabilities ``[batch, out_dim]`` (the reference's stage 1
    ends in ``log_softmax``, ``simple_distributed.py:79``).

    ``shards``: optional per-model-shard params for tensor parallelism — a
    tuple of ``n_model`` pytrees (identical tree structure and leaf shapes).
    When set, ``apply`` receives THIS device's shard and may use collectives
    over the ``model`` mesh axis (e.g. ``tensor.tp_pair_apply``); every model
    shard must return the same (replicated) activation, i.e. finish each
    sharded group with its psum. When ``shards`` is None on a mesh with
    ``n_model > 1``, ``params`` is replicated to every model slot and the
    stage computes redundantly (correct, just not sharded).

    ``expert_shards``: optional per-expert-device params for expert (MoE)
    parallelism — a tuple of ``n_expert`` pytrees (identical structure and
    leaf shapes; typically the stage's expert weights split ``E/n_expert``
    per device with everything else replicated). ``apply`` receives THIS
    device's shard and may use collectives over the ``expert`` mesh axis
    (e.g. ``expert.moe_apply_ep``); the apply is responsible for grad-syncing
    its replicated (non-expert) leaves over the axis and must return the
    same activation on every expert device (e.g. via ``all_gather``).
    Mutually exclusive with ``shards``.

    ``apply`` may return either ``y`` or ``(y, aux)`` — ``aux`` is a scalar
    auxiliary loss (e.g. the MoE load-balancing term, already scaled by its
    weight) that the engine adds to the objective (summed over stages,
    averaged over microbatches/data shards).
    """
    apply: Callable[[Any, jax.Array, jax.Array, bool], jax.Array]
    params: Any
    in_shape: tuple[int, ...]
    shards: tuple | None = None
    expert_shards: tuple | None = None


class Pipeline:
    """Compiled GPipe pipeline over a ``(data, stage)`` mesh.

    Parameters live in a ``[n_stages, max_param_size]`` buffer sharded
    ``P('stage')`` — each device holds only its own stage's params
    (owner-local, like the reference's per-process modules) and updates them
    locally inside the compiled step (replacing DistributedOptimizer,
    ``simple_distributed.py:100-104``).
    """

    def __init__(self, stages: Sequence[Stage], mesh: jax.sharding.Mesh,
                 wire_dim: int, out_dim: int | tuple[int, ...],
                 n_microbatches: int = 1, compute_dtype=None,
                 remat: bool = False, schedule: str = "gpipe",
                 overlap: str = "none"):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown schedule {schedule!r}")
        from simple_distributed_machine_learning_tpu.parallel.overlap import (
            check_overlap,
        )
        # the engine-level knob covers the engine's OWN collectives — the
        # backward grad_sync all-reduce of stages stored replicated over the
        # model/expert axes becomes the chunked ppermute ring of
        # overlap.ring_psum. Stage-internal collectives (TP pairs, TP GPT
        # blocks, EP dispatch) carry their own overlap choice from the model
        # build. The 1F1B engine (onefb.py) does its own replication
        # accounting without grad_sync and ignores this knob.
        self.overlap = check_overlap(overlap)
        self.schedule = schedule
        self.stages = list(stages)
        self.mesh = mesh
        self.n_stages = mesh.shape[STAGE_AXIS]
        self.n_data = mesh.shape[DATA_AXIS]
        self.n_model = mesh.shape.get(MODEL_AXIS, 1)
        # sequence/context parallelism: when the mesh has a seq axis, the
        # token axis (axis 0 of every stage's in_shape and of out_shape) is
        # sharded over it. Stage in_shapes and wire_dim are then LOCAL
        # (per-seq-shard) sizes; out_dim stays GLOBAL (the host-facing logits
        # shape). Stage applies use seq collectives (ring attention / Ulysses
        # all-to-all) for any cross-token mixing.
        self._has_seq = SEQ_AXIS in mesh.shape
        self.n_seq = mesh.shape.get(SEQ_AXIS, 1)
        # expert (MoE) parallelism: expert-sharded stages hold 1/n_expert of
        # their expert weights per expert-axis device (see Stage.expert_shards)
        self._has_expert = EXPERT_AXIS in mesh.shape
        self.n_expert = mesh.shape.get(EXPERT_AXIS, 1)
        if len(self.stages) != self.n_stages:
            raise ValueError(
                f"{len(self.stages)} stages but mesh stage axis is {self.n_stages}")
        self.wire_dim = int(wire_dim)
        # per-sample output shape; last axis = classes. (C,) for classifiers,
        # (T, V) for per-token language-model log-probs
        self.out_shape = ((int(out_dim),) if isinstance(out_dim, int)
                          else tuple(int(d) for d in out_dim))
        self.out_dim = self.out_shape[-1]
        if self.n_seq > 1:
            if len(self.out_shape) < 2:
                raise ValueError(
                    "sequence parallelism (mesh seq axis > 1) requires a "
                    "per-token output shape like (T, V); got "
                    f"out_dim={out_dim!r}")
            if self.out_shape[0] % self.n_seq:
                raise ValueError(
                    f"token axis {self.out_shape[0]} not divisible by "
                    f"seq axis size {self.n_seq}")
        # per-device output shape: token axis divided over the seq shards
        self.out_local = ((self.out_shape[0] // self.n_seq,)
                          + self.out_shape[1:])
        self.n_microbatches = int(n_microbatches)
        # mixed precision: params and activations are cast to compute_dtype
        # around each stage apply (bfloat16 doubles MXU throughput and halves
        # HBM traffic); master params, the wire, and the loss stay float32.
        # remat: stage applies recompute in backward (jax.checkpoint), trading
        # FLOPs for activation memory — the standard deep-pipeline trade.
        self.compute_dtype = compute_dtype
        self.remat = bool(remat)
        self._sm_cache: dict[bool, Callable] = {}
        # param buffer rows: one per (stage, model-shard, expert-shard).
        # Stages without shards are replicated across the model/expert axes
        # (redundant compute, identical grads — the data-axis story, one
        # level down); expert-sharded stages genuinely split their expert
        # weights' STORAGE across the expert axis.
        per_shard: list[Any] = []
        for s in self.stages:
            if s.shards is not None and s.expert_shards is not None:
                raise ValueError(
                    "a stage cannot be both tensor- (shards) and expert- "
                    "(expert_shards) sharded")
            if s.shards is not None and len(s.shards) != self.n_model:
                raise ValueError(
                    f"stage has {len(s.shards)} model shards, mesh model "
                    f"axis is {self.n_model}")
            if (s.expert_shards is not None
                    and len(s.expert_shards) != self.n_expert):
                raise ValueError(
                    f"stage has {len(s.expert_shards)} expert shards, mesh "
                    f"expert axis is {self.n_expert}")
            model_trees = (list(s.shards) if s.shards is not None
                           else [s.params] * self.n_model)
            for mt in model_trees:
                if s.expert_shards is not None:
                    per_shard.extend(s.expert_shards)
                else:
                    per_shard.extend([mt] * self.n_expert)
        flat, metas_all = pack_stage_params(per_shard)
        import numpy as np
        # keep the master copy on the HOST: device_put of an on-device array
        # with a matching sharding ALIASES it, and a later donated train step
        # would delete the alias — init_params() must survive any number of
        # donating steps
        self._buf0 = np.asarray(jax.device_get(flat.reshape(
            self.n_stages, self.n_model, self.n_expert, -1)))
        # shard 0's layout stands for the stage (shards are shape-identical)
        stride = self.n_model * self.n_expert
        self.metas = metas_all[::stride]
        for s, stage in enumerate(self.stages):
            if stage.shards is not None or stage.expert_shards is not None:
                m0 = metas_all[s * stride]
                for m in metas_all[s * stride:(s + 1) * stride]:
                    if m.shapes != m0.shapes:
                        raise ValueError(
                            f"stage {s}: model/expert shards have differing "
                            f"leaf shapes — sharded params must split evenly")
        self._validate_boundaries()

    def _validate_boundaries(self) -> None:
        """Shape-check every stage hop at build time (via eval_shape — no FLOPs).

        The wire codec zero-pads/truncates, so a stage whose output width does
        not match the next stage's ``in_shape`` would otherwise train silently
        on fabricated zeros. Plain stages are eval_shape'd directly; TP-, EP-
        and seq-parallel stage applies use mesh collectives (psum /
        all-to-all / ring ppermute), so they are traced under a ``shard_map``
        over the real mesh (``check_vma=False`` — only shape semantics are
        wanted here) and validated on per-shard feature widths.
        """
        import numpy as np
        batch = 2
        for s, stage in enumerate(self.stages):
            on_mesh = (self.n_seq > 1 or stage.shards is not None
                       or stage.expert_shards is not None)
            exact_shape = None
            if on_mesh:
                shard_shape = self._sharded_out_shape(stage, batch)
                out_size = int(np.prod(shard_shape))
            else:
                x = jax.ShapeDtypeStruct((batch,) + tuple(stage.in_shape),
                                         jnp.float32)
                key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
                out = jax.eval_shape(
                    lambda p, xx, kk, _a=stage.apply: _a(p, xx, kk, True),
                    stage.params, x, key)
                if isinstance(out, tuple):
                    # MoE stages return (y, aux_loss); only y rides the wire
                    out = out[0]
                exact_shape = out.shape
                out_size = int(np.prod(out.shape[1:]))
            # the last stage's output never rides the wire (its log-probs
            # are consumed locally by the loss), so only inter-stage hops
            # must fit wire_dim
            if s + 1 < len(self.stages) and out_size > self.wire_dim:
                raise ValueError(
                    f"stage {s} output width {out_size} exceeds wire_dim "
                    f"{self.wire_dim}")
            if s + 1 < len(self.stages):
                nxt = int(np.prod(self.stages[s + 1].in_shape))
                if out_size != nxt:
                    raise ValueError(
                        f"stage {s} outputs {out_size} features but stage "
                        f"{s + 1} declares in_shape={self.stages[s + 1].in_shape} "
                        f"({nxt} features)")
            elif exact_shape is not None:
                if exact_shape[1:] != self.out_shape:
                    raise ValueError(
                        f"last stage must output [batch, *{self.out_shape}], "
                        f"got {exact_shape}")
            elif shard_shape != tuple(self.out_local):
                per = ("per seq shard " if self.n_seq > 1 else "")
                raise ValueError(
                    f"last stage outputs {shard_shape} {per}but the pipeline "
                    f"declares out_shape={self.out_shape} "
                    f"({tuple(self.out_local)} {per.strip() or 'per device'})")
            if int(np.prod(stage.in_shape)) > self.wire_dim:
                raise ValueError(
                    f"stage {s} in_shape {stage.in_shape} exceeds wire_dim "
                    f"{self.wire_dim}")

    def _sharded_out_shape(self, stage: Stage, batch: int) -> tuple[int, ...]:
        """Per-shard output feature shape of a TP/EP/seq stage, traced under
        ``shard_map`` on the real mesh with zero FLOPs (``jax.eval_shape``).

        Params ride in stacked over their shard axis (model or expert) so
        each device sees its own shard; in a seq mesh the activation's token
        axis (axis 0 of ``in_shape``) is sharded over the seq axis. The
        per-shard shape is captured at trace time (shapes are static), since
        the shard_map out_spec only reassembles a flattened width.
        """
        if stage.expert_shards is not None:
            trees, p_axis = stage.expert_shards, EXPERT_AXIS
        elif stage.shards is not None:
            trees, p_axis = stage.shards, MODEL_AXIS
        else:
            trees, p_axis = None, None
        if trees is not None:
            p_sds = jax.tree.map(
                lambda *ls: jax.ShapeDtypeStruct((len(ls),) + ls[0].shape,
                                                 ls[0].dtype), *trees)
            p_spec, unstack = P(p_axis), True
        else:
            p_sds = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), stage.params)
            p_spec, unstack = P(), False

        in_local = tuple(stage.in_shape)
        if self.n_seq > 1:
            x_glob = (batch, in_local[0] * self.n_seq) + in_local[1:]
            x_spec = P(None, SEQ_AXIS, *(None,) * (len(in_local) - 1))
        else:
            x_glob = (batch,) + in_local
            x_spec = P(*(None,) * (len(in_local) + 1))
        x = jax.ShapeDtypeStruct(x_glob, jnp.float32)
        key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)

        shard_shape: list[tuple[int, ...]] = []

        def run(p, xx, kk):
            if unstack:
                p = jax.tree.map(lambda a: a[0], p)   # this device's shard
            y = stage.apply(p, xx, kk, True)
            if isinstance(y, tuple):
                y = y[0]
            shard_shape.append(tuple(y.shape[1:]))
            return y.reshape(xx.shape[0], -1)

        fn = _shard_map(
            run, mesh=self.mesh,
            in_specs=(p_spec, x_spec, P()),
            out_specs=P(None, SEQ_AXIS if self.n_seq > 1 else None),
            check_vma=False)
        jax.eval_shape(fn, p_sds, x, key)
        return shard_shape[0]

    # ---- parameters -----------------------------------------------------

    def replication_weights(self):
        """``[S, n_model, n_expert, 1]`` float32 multipliers for squared-
        gradient-norm sums over the packed buffer: stages stored redundantly
        across the model/expert axes (``Stage.shards``/``expert_shards`` is
        None) get ``1/replication`` so each parameter counts once in a global
        norm (``train.optimizer.clip_by_global_norm``); genuinely sharded
        rows count fully. Padding tail bytes are zero-gradient anyway."""
        import numpy as np
        w = np.ones((self.n_stages, self.n_model, self.n_expert, 1),
                    np.float32)
        for s, stage in enumerate(self.stages):
            rep = 1
            if stage.shards is None:
                rep *= self.n_model
            if stage.expert_shards is None:
                rep *= self.n_expert
            w[s] = 1.0 / rep
        return w

    def param_spec(self) -> P:
        """PartitionSpec of the packed ``[n_stages, n_model, n_expert, P]``
        buffer."""
        return P(STAGE_AXIS, MODEL_AXIS,
                 EXPERT_AXIS if self._has_expert else None, None)

    def init_params(self) -> jax.Array:
        """Place the packed stage-param buffer on the mesh (stage- and
        model-shard-sharded; replicated over the data axis)."""
        sharding = NamedSharding(self.mesh, self.param_spec())
        return jax.device_put(self._buf0, sharding)

    def unpack(self, buf: jax.Array) -> list[Any]:
        """Host-side: recover the per-stage param pytrees (for tests/ckpt).
        For model-/expert-sharded stages the entry is the list of per-shard
        trees."""
        rows = jax.device_get(buf)
        out = []
        for s in range(self.n_stages):
            if self.stages[s].shards is not None:
                out.append([unpack_stage_params(
                    jnp.asarray(rows[s, m, 0]), self.metas[s])
                    for m in range(self.n_model)])
            elif self.stages[s].expert_shards is not None:
                out.append([unpack_stage_params(
                    jnp.asarray(rows[s, 0, e]), self.metas[s])
                    for e in range(self.n_expert)])
            else:
                out.append(unpack_stage_params(
                    jnp.asarray(rows[s, 0, 0]), self.metas[s]))
        return out

    # ---- forward/loss ---------------------------------------------------

    def _shard_fn(self, deterministic: bool, loss_only: bool = False,
                  metrics: bool = False) -> Callable:
        """Build (once per mode) the shard_mapped pipeline loss function.

        ``loss_only``: the training mode. The scan carry drops the
        ``[M, mb, *out_shape]`` log-probs accumulator (for a language model
        that is the full [B, T, V] replicated over every stage — the
        dominant activation at scale) and the function returns just the
        scalar loss; gradients are identical because the accumulator never
        feeds the loss.

        ``metrics``: the eval mode. Like ``loss_only`` the carry never holds
        the log-probs accumulator; instead the loop folds each last-stage
        microbatch's log-probs straight into three scalars — weighted NLL
        sum, weight sum, weighted argmax-correct count — and returns them
        un-divided (the caller decides mean vs sum). Eval of a model whose
        ``[B, T, V]`` logits would not fit replicated across stages costs no
        more memory than training.
        """
        cache_key = (deterministic, loss_only, metrics)
        if cache_key in self._sm_cache:
            return self._sm_cache[cache_key]
        if loss_only and metrics:
            raise ValueError("loss_only and metrics are distinct modes")

        S = self.n_stages
        M = self.n_microbatches
        T = M + S - 1
        wire_dim = self.wire_dim
        out_shape = self.out_local          # per-device (seq-local) shape
        # the seq axis engages only for per-token outputs: a classifier has
        # no token axis to shard, so its wire/targets/logits stay seq-
        # replicated even on a mesh that has a seq axis
        seq_on = self._has_seq and len(self.out_shape) > 1
        n_seq = self.n_seq
        metas = list(self.metas)
        applies = [s.apply for s in self.stages]
        in_shapes = [s.in_shape for s in self.stages]
        n_model = self.n_model
        n_expert = self.n_expert
        # stages without model/expert shards compute redundantly on every
        # slot of those axes; their params need the grad_sync treatment (see
        # tensor.grad_sync) so each replica receives the full, not
        # 1/axis_size, gradient
        replicated_over_model = [s.shards is None for s in self.stages]
        replicated_over_expert = [s.expert_shards is None for s in self.stages]
        overlap = self.overlap
        compute_dtype = self.compute_dtype
        remat = self.remat
        # every mesh axis the loop's values can vary over (data via inputs,
        # stage/model/expert via the param row, seq via the sharded wire)
        vary_axes = (DATA_AXIS, STAGE_AXIS, MODEL_AXIS) + (
            (SEQ_AXIS,) if seq_on else ()) + (
            (EXPERT_AXIS,) if self._has_expert else ())

        def per_device(row4d, x_mb, tgt_mb, w_mb, key):
            # row4d: [1, 1, 1, P] this device's (stage, model-shard,
            # expert-shard) param row; x_mb: [M, mb, wire]; tgt_mb/w_mb:
            # [M, mb(...)] targets and weights
            row = row4d[0, 0, 0]
            stage = lax.axis_index(STAGE_AXIS)
            mb = x_mb.shape[1]

            def make_branch(s):
                is_last = (s == S - 1)

                def branch(wire, k):
                    from simple_distributed_machine_learning_tpu.parallel.tensor import (
                        grad_sync,
                    )
                    params = unpack_stage_params(row, metas[s])
                    if n_model > 1 and replicated_over_model[s]:
                        params = jax.tree.map(
                            lambda a: grad_sync(a, MODEL_AXIS, overlap),
                            params)
                    if n_expert > 1 and replicated_over_expert[s]:
                        params = jax.tree.map(
                            lambda a: grad_sync(a, EXPERT_AXIS, overlap),
                            params)
                    x = wire_decode(wire, in_shapes[s])
                    if compute_dtype is not None:
                        params = jax.tree.map(
                            lambda a: a.astype(compute_dtype), params)
                        x = x.astype(compute_dtype)
                    y = applies[s](params, x, k, deterministic)
                    aux = jnp.float32(0.0)
                    if isinstance(y, tuple):
                        y, aux = y
                        aux = aux.astype(jnp.float32)
                    # the last stage's output (the log-probs) never rides the
                    # ppermute ring: it is consumed locally by the loss, so
                    # the wire stays inter-stage-activation wide (for a GPT
                    # that keeps vocab-width [T, V] log-probs off the hop and
                    # off the wire padding) and the last stage sends zeros
                    # (stage 0 overwrites its inbox with the next injected
                    # microbatch anyway)
                    if is_last:
                        out = jnp.zeros((y.shape[0], wire_dim), jnp.float32)
                        y_out = y.astype(jnp.float32)
                    else:
                        out = wire_encode(y.astype(jnp.float32), wire_dim)
                        y_out = jnp.zeros((y.shape[0],) + out_shape,
                                          jnp.float32)
                    # uniformize branch output vma for lax.switch and the
                    # scan carry: a TP stage's psum (or an EP stage's
                    # all_gather) leaves its output less-varying than a
                    # replicated stage's. Value-identity; the transpose
                    # (psum of per-replica cotangents, each ct/n after the
                    # loss pmean) reassembles the full cotangent.
                    #
                    # the zero-valued full-vma anchor additionally pins each
                    # branch's INPUT-cotangent type: without it, branches
                    # whose wire feeds a narrower-vma path (e.g. a plain
                    # stage beside sharded ones, or the last stage's
                    # loss-only use) transpose to mismatched cotangent vmas
                    # and jax's cond transpose rejects the switch
                    # ("mismatched varying manual axes"). Adding 0*sum(wire)
                    # is value-free but makes every branch's wire cotangent
                    # at least vary_axes-typed. The anchor sums BOTH the
                    # wire and the closed-over param row: closure captures
                    # are hoisted into cond operands, so the row's cotangent
                    # type needs the same pinning.
                    anchor = _pvary_to(
                        jnp.float32(0.0) * (jnp.sum(wire) + jnp.sum(row)),
                        vary_axes)
                    return (_pvary_to(out, vary_axes) + anchor,
                            _pvary_to(aux, vary_axes) + anchor,
                            _pvary_to(y_out, vary_axes) + anchor)
                if remat:
                    return jax.checkpoint(branch)
                return branch

            branches = [make_branch(s) for s in range(S)]
            fwd = [(i, (i + 1) % S) for i in range(S)]

            def step(carry, t):
                if loss_only:
                    wire, num_acc, den_acc, aux_acc = carry
                elif metrics:
                    wire, num_acc, den_acc, aux_acc, correct_acc = carry
                else:
                    wire, num_acc, den_acc, aux_acc, logits_acc = carry
                # stage 0 injects a fresh microbatch every step (clipped so the
                # drain steps recompute-and-discard the last one — finite math,
                # zeroed below by the validity mask).
                inj = lax.dynamic_index_in_dim(
                    x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
                wire = jnp.where(stage == 0, inj, wire)
                # distinct dropout noise per (step, stage, data-shard) — and
                # per seq-shard when the token axis is sharded, so dropout
                # patterns do not repeat chunk-to-chunk (left out of the fold
                # at n_seq=1 to keep the fused path's RNG stream identical)
                k_t = jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(key, t), stage),
                    lax.axis_index(DATA_AXIS))
                if n_seq > 1:
                    k_t = jax.random.fold_in(k_t, lax.axis_index(SEQ_AXIS))
                out, aux, logits = lax.switch(stage, branches, wire, k_t)
                m = t - stage           # microbatch index this stage is working on
                valid = (m >= 0) & (m < M)
                out = jnp.where(valid, out, jnp.zeros_like(out))
                # auxiliary losses (e.g. MoE load balancing) accumulate once
                # per (stage, valid microbatch)
                aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
                # the last stage's branch just produced log-probs for
                # microbatch m (zeros on every other stage)
                is_out = valid & (stage == S - 1)
                m_safe = jnp.clip(m, 0, M - 1)
                tgt = lax.dynamic_index_in_dim(tgt_mb, m_safe, 0, keepdims=False)
                w = lax.dynamic_index_in_dim(w_mb, m_safe, 0, keepdims=False)
                # per-sample weights broadcast over any token axes (e.g. the
                # sequence axis of a per-token LM loss)
                nll = nll_loss(logits, tgt, "none")
                wb = w.reshape(w.shape + (1,) * (nll.ndim - 1))
                per_tok = jnp.broadcast_to(wb, nll.shape)
                num_acc = num_acc + jnp.where(is_out, jnp.sum(nll * per_tok), 0.0)
                den_acc = den_acc + jnp.where(is_out, jnp.sum(per_tok), 0.0)
                # the hop: stage s -> s+1 over ICI; autodiff transposes this
                # into the backward s+1 -> s hop.
                wire = lax.ppermute(out, STAGE_AXIS, fwd)
                if loss_only:
                    return (wire, num_acc, den_acc, aux_acc), None
                if metrics:
                    # fold the microbatch's log-probs into the correct count
                    # right here — they never outlive this scan step. The
                    # count is int32 (exact to 2^31; a float32 running sum
                    # silently drops increments past 2^24 ≈ 16.7M tokens) and
                    # counts predictions whose weight is NONZERO — identical
                    # to the weighted sum for 0/1 validity masks, which is
                    # what a count of "correct predictions" means
                    hit = (logits.argmax(-1) == tgt) & (per_tok > 0)
                    correct_acc = correct_acc + jnp.where(
                        is_out, jnp.sum(hit.astype(jnp.int32)), 0)
                    return (wire, num_acc, den_acc, aux_acc, correct_acc), None
                prev = lax.dynamic_index_in_dim(logits_acc, m_safe, 0, keepdims=False)
                logits_acc = lax.dynamic_update_index_in_dim(
                    logits_acc, jnp.where(is_out, logits, prev), m_safe, 0)
                return (wire, num_acc, den_acc, aux_acc, logits_acc), None

            # the init carry is device-uniform but the loop body makes it
            # vary over every mesh axis (params vary over stage/model/expert,
            # data over data, seq-sharded tokens over seq); pcast aligns the
            # carry types for check_vma. The scalar accumulators ride as
            # shape-(1,) arrays: scan-resident rank-0 carries trip the
            # scalar-residual promotion of older jax's shard_map partial
            # eval, and the singleton axis is free either way
            init0 = (jnp.zeros((mb, wire_dim), x_mb.dtype),
                     jnp.zeros((1,), jnp.float32),
                     jnp.zeros((1,), jnp.float32),
                     jnp.zeros((1,), jnp.float32))
            if metrics:
                init0 += (jnp.zeros((1,), jnp.int32),)
            elif not loss_only:
                init0 += (jnp.zeros((M, mb) + out_shape, jnp.float32),)
            init = jax.tree.map(lambda a: _pvary_to(a, vary_axes), init0)
            carry_out, _ = lax.scan(step, init, jnp.arange(T))
            if loss_only:
                _, num, den, aux = carry_out
            elif metrics:
                _, num, den, aux, correct = carry_out
                correct = correct[0]
            else:
                _, num, den, aux, logits_acc = carry_out
            num, den, aux = num[0], den[0], aux[0]

            # weighted global mean: sum(w * nll) / sum(w), reduced over the
            # stage axis (only the last stage contributed), the data axis,
            # and — for a seq-sharded token axis — the seq axis.
            num = lax.psum(lax.psum(num, STAGE_AXIS), DATA_AXIS)
            den = lax.psum(lax.psum(den, STAGE_AXIS), DATA_AXIS)
            if seq_on:
                num = lax.psum(num, SEQ_AXIS)
                den = lax.psum(den, SEQ_AXIS)
            if metrics:
                # correct reduces exactly like num: only the last stage
                # contributed, data (and seq) shards partition the samples
                # (tokens), model/expert slots replicate. The replication
                # proof over model/expert stays integer-exact as psum//size
                # (identical replicas sum to size*v) instead of a float pmean
                correct = lax.psum(lax.psum(correct, STAGE_AXIS), DATA_AXIS)
                if seq_on:
                    correct = lax.psum(correct, SEQ_AXIS)
                num = lax.pmean(num, MODEL_AXIS)
                den = lax.pmean(den, MODEL_AXIS)
                correct = lax.psum(correct, MODEL_AXIS) // n_model
                if self._has_expert:
                    num = lax.pmean(num, EXPERT_AXIS)
                    den = lax.pmean(den, EXPERT_AXIS)
                    correct = lax.psum(correct, EXPERT_AXIS) // n_expert
                return num, den, correct
            # model-axis replication proof for check_vma: every model slot
            # computed the same value (replicated stages run redundantly; TP
            # stages end each pair in their own psum), so pmean is the
            # identity value-wise — and gradient-wise: its transpose hands
            # each replica ct/n_model, exactly what the implicit replicated
            # out_spec did, which grad_sync already compensates for.
            num = lax.pmean(num, MODEL_AXIS)
            den = lax.pmean(den, MODEL_AXIS)
            # auxiliary losses: summed over stages (each MoE stage adds its
            # layers' terms), averaged UNWEIGHTED over microbatches — sample
            # weights scale the NLL term only (see loss_and_logits docstring);
            # data/seq/expert shards each routed a different token subset, so
            # averaging over them matches the dense "mean over all routing
            # groups"; model replicas are identical (pmean = replication
            # proof).
            aux = lax.psum(aux, STAGE_AXIS) / M
            aux = lax.pmean(lax.pmean(aux, DATA_AXIS), MODEL_AXIS)
            if seq_on:
                aux = lax.pmean(aux, SEQ_AXIS)
            if self._has_expert:
                aux = lax.pmean(aux, EXPERT_AXIS)
                num = lax.pmean(num, EXPERT_AXIS)
                den = lax.pmean(den, EXPERT_AXIS)
            loss = num / jnp.maximum(den, 1e-12) + aux
            if loss_only:
                return loss
            # logits stay seq-sharded (the out_spec reassembles the token
            # axis); only the stage/model/expert axes are reduced away
            logits = lax.pmean(                            # replicate last stage's
                lax.psum(logits_acc, STAGE_AXIS), MODEL_AXIS)
            if self._has_expert:
                logits = lax.pmean(logits, EXPERT_AXIS)
            return loss, logits

        # activations/targets are replicated over the model axis (left
        # unmentioned); TP stages shard their compute internally and restore
        # replication with their own psums. On a seq mesh, the wire's feature
        # axis is sharded over seq (the host packs one contiguous
        # wire_dim-wide chunk per seq shard), and the targets'/logits' token
        # axis (axis 0 of out_shape) is sharded over seq directly.
        tok_axes = len(self.out_shape) - 1
        seq_or_none = SEQ_AXIS if seq_on else None
        tgt_tok = ((seq_or_none,) + (None,) * (tok_axes - 1)
                   if tok_axes else ())
        fn = _shard_map(
            per_device,
            mesh=self.mesh,
            in_specs=(self.param_spec(),
                      P(None, DATA_AXIS, seq_or_none),
                      P(None, DATA_AXIS, *tgt_tok),
                      P(None, DATA_AXIS), P()),
            out_specs=(P() if loss_only
                       else (P(), P(), P()) if metrics
                       else (P(), P(None, DATA_AXIS, *tgt_tok, None))),
        )
        self._sm_cache[cache_key] = fn
        return fn

    def loss_and_logits(self, buf: jax.Array, x: jax.Array, targets: jax.Array,
                        key: jax.Array, deterministic: bool = False,
                        weights: jax.Array | None = None
                        ) -> tuple[jax.Array, jax.Array]:
        """Weighted-mean NLL loss + per-example log-probs for a global batch.

        ``x``: [B, ...] model input (stage 0's real input shape);
        ``targets``: [B] int labels; ``weights``: optional [B] per-sample loss
        weights (e.g. a 0/1 validity mask for a zero-padded ragged batch —
        loss = sum(w·nll)/sum(w), so padding does not dilute the mean). B must
        divide by ``n_microbatches * n_data``.

        ``weights`` applies to the NLL term ONLY. MoE auxiliary
        (load-balancing) losses are accumulated unweighted — a uniform mean
        over microbatches — exactly as the dense path computes aux over the
        full batch including zero-weight rows: router balance is a property
        of every token that was dispatched, padding included, so weighting it
        would let padded batches skew expert utilisation pressure
        (pinned by tests/test_expert_pipeline.py::
        test_weighted_loss_applies_to_nll_only).
        """
        if self._trivial_mesh():
            return self._fused_loss(buf, x, targets, key, deterministic,
                                    weights)
        xw, tgt, w = self._prep_inputs(x, targets, weights)
        loss, logits = self._shard_fn(deterministic)(buf, xw, tgt, w, key)
        return loss, logits.reshape((x.shape[0],) + self.out_shape)

    def eval_metrics(self, buf: jax.Array, x: jax.Array, targets: jax.Array,
                     key: jax.Array, weights: jax.Array | None = None
                     ) -> tuple[jax.Array, jax.Array, jax.Array]:
        """``(sum_nll, sum_weight, correct)`` — the memory-flat eval path.

        ``sum(w·nll)`` and ``sum(w)`` are weighted sums over the global
        batch with ``w`` broadcast over any token axes (so a per-sample 0/1
        validity mask zeroes padded rows of a ragged batch); ``correct`` is
        the int32 COUNT of predictions with ``argmax == target`` among
        nonzero-weight entries — an integer accumulation exact to 2^31
        (a float32 weighted sum would silently stop counting past ~16.7M).
        Always deterministic (dropout off — deliberately NOT the reference's
        eval-dropout quirk, SURVEY §3.5).

        Unlike ``loss_and_logits``, nothing ``[batch, *out_shape]``-sized is
        materialized, carried, or psum'd: each last-stage microbatch's
        log-probs fold into the three scalars inside the scan step. For a
        vocab-wide LM the logits accumulator is the dominant eval
        activation — this path removes it, so eval fits wherever training
        fits (``make_eval_step`` builds on this).
        """
        if self._trivial_mesh():
            logp, _ = self._fused_logits(buf, x, key, True)
            num, den, wb = _weighted_nll_sums(logp, targets, weights)
            hit = (logp.argmax(-1) == targets) & (wb > 0)
            return num, den, jnp.sum(hit.astype(jnp.int32))
        xw, tgt, w = self._prep_inputs(x, targets, weights)
        return self._shard_fn(deterministic=True, metrics=True)(
            buf, xw, tgt, w, key)

    def loss(self, buf: jax.Array, x: jax.Array, targets: jax.Array,
             key: jax.Array, deterministic: bool = False,
             weights: jax.Array | None = None) -> jax.Array:
        """Scalar loss only — the training path.

        Same math as ``loss_and_logits(...)[0]`` (same RNG stream, same
        gradients) but the engine skips the per-microbatch log-probs
        accumulator entirely: nothing [batch, *out_shape]-sized rides the
        scan carry or is psum'd across stages. For a language model that is
        the difference between carrying [B, T, vocab] on every device and
        carrying two scalars.
        """
        if self._trivial_mesh():
            return self._fused_loss(buf, x, targets, key, deterministic,
                                    weights)[0]
        xw, tgt, w = self._prep_inputs(x, targets, weights)
        return self._shard_fn(deterministic, loss_only=True)(
            buf, xw, tgt, w, key)

    def loss_and_grads(self, buf: jax.Array, x: jax.Array,
                       targets: jax.Array, key: jax.Array,
                       deterministic: bool = False,
                       weights: jax.Array | None = None
                       ) -> tuple[jax.Array, jax.Array]:
        """Scalar loss + packed-buffer gradients — the training contract.

        ``schedule='gpipe'`` (default): ``jax.value_and_grad`` over the
        scanned loss-only engine (XLA reverses the scan; all ``M``
        microbatch residuals are alive between the sweeps).
        ``schedule='1f1b'``: the hand-scheduled interleave in ``onefb.py``
        — same loss/gradients (parity-tested), activation memory bounded by
        the topology ``S`` instead of ``M``.
        """
        if self.schedule == "1f1b" and not self._trivial_mesh():
            from simple_distributed_machine_learning_tpu.parallel.onefb import (
                build_1f1b_fn,
            )
            cache_key = ("1f1b", deterministic)
            if cache_key not in self._sm_cache:
                self._sm_cache[cache_key] = build_1f1b_fn(self, deterministic)
            xw, tgt, w = self._prep_inputs(x, targets, weights)
            return self._sm_cache[cache_key](buf, xw, tgt, w, key)

        def loss_fn(b):
            return self.loss(b, x, targets, key, deterministic=deterministic,
                             weights=weights)
        return jax.value_and_grad(loss_fn)(buf)

    def _trivial_mesh(self) -> bool:
        """Degenerate single-device mesh: the pipeline IS the fused model.
        Skip the shard_map engine — its packed-row unpack/repack costs ~10x
        the model itself at this scale (grad of the slice/concat machinery),
        with nothing to overlap on one device."""
        return (self.n_stages == 1 and self.n_data == 1 and self.n_model == 1
                and self.n_seq == 1 and self.n_expert == 1
                and self.stages[0].shards is None
                and self.stages[0].expert_shards is None)

    def _prep_inputs(self, x, targets, weights):
        """Host-side packing: microbatch split + wire encoding of the global
        batch (seq-sharded wires are chunked token-major per shard)."""
        import jax.numpy as jnp

        M = self.n_microbatches
        B = x.shape[0]
        if B % (M * self.n_data) != 0:
            raise ValueError(
                f"batch {B} not divisible by microbatches*data = {M * self.n_data}")
        # the wire is always float32 (stages decode/cast as needed — e.g. the
        # GPT embedding stage reads token ids back out of the float wire)
        if self.n_seq > 1:
            # seq-sharded wire: chunk the token axis (axis 0 of the
            # per-sample shape, so the flatten is token-major and each chunk
            # is contiguous), pad each chunk to the LOCAL wire width, and lay
            # the chunks side by side — the shard_map in_spec then hands each
            # seq shard exactly its own wire_dim-wide chunk.
            chunks = jnp.reshape(x, (B, self.n_seq, -1))
            pad = self.wire_dim - chunks.shape[-1]
            if pad < 0:
                raise ValueError(
                    f"per-shard activation width {chunks.shape[-1]} exceeds "
                    f"wire_dim {self.wire_dim}")
            xw = jnp.pad(chunks, ((0, 0), (0, 0), (0, pad)))
        else:
            xw = wire_encode(x, self.wire_dim)
        xw = xw.astype(jnp.float32).reshape(
            M, B // M, self.n_seq * self.wire_dim)
        tgt = targets.reshape((M, B // M) + self.out_shape[:-1])
        w = (jnp.ones((B,), jnp.float32) if weights is None
             else weights.astype(jnp.float32)).reshape(M, B // M)
        return xw, tgt, w

    def _fused_logits(self, buf, x, key, deterministic):
        """Single-device forward: ``(log_probs, aux)`` from the fused stage.
        Same RNG stream as the engine's stage-0 key at step 0, data shard 0."""
        B = x.shape[0]
        stage = self.stages[0]
        params = unpack_stage_params(buf[0, 0, 0], self.metas[0])
        xs = x.reshape((B,) + tuple(stage.in_shape))
        if self.compute_dtype is not None:
            params = jax.tree.map(
                lambda a: a.astype(self.compute_dtype), params)
            xs = xs.astype(self.compute_dtype)
        k = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(key, 0), 0), 0)
        out = stage.apply(params, xs, k, deterministic)
        aux = jnp.float32(0.0)
        if isinstance(out, tuple):
            out, aux = out
            aux = aux.astype(jnp.float32)
        return out.astype(jnp.float32), aux

    def _fused_loss(self, buf, x, targets, key, deterministic, weights):
        """Single-device fast path. Identical to the engine for
        ``n_microbatches == 1`` or deterministic mode; with several
        microbatches AND dropout the engine draws per-microbatch noise while
        this path draws one batch-wide key — same distribution, different
        stream."""
        logp, aux = self._fused_logits(buf, x, key, deterministic)
        num, den, _ = _weighted_nll_sums(logp, targets, weights)
        return num / jnp.maximum(den, 1e-12) + aux, logp


def _weighted_nll_sums(logp, targets, weights):
    """``(sum(w·nll), sum(w), wb)`` with per-sample ``weights`` (or ones)
    broadcast over token axes — the one copy of the weighted-metrics
    arithmetic shared by the fused loss and eval paths."""
    nll = nll_loss(logp, targets, "none")
    w = (jnp.ones((logp.shape[0],), jnp.float32) if weights is None
         else weights.astype(jnp.float32))
    wb = jnp.broadcast_to(
        w.reshape(w.shape + (1,) * (nll.ndim - 1)), nll.shape)
    return jnp.sum(nll * wb), jnp.sum(wb), wb


def fused_reference(stages: Sequence[Stage]) -> Callable:
    """Single-device composition of the stages (ground truth for parity tests:
    the pipeline on N devices must match this to float tolerance, SURVEY §7)."""
    def apply(stage_params: Sequence[Any], x: jax.Array, key: jax.Array,
              deterministic: bool = False) -> jax.Array:
        h = x
        for s, (stage, params) in enumerate(zip(stages, stage_params)):
            k = jax.random.fold_in(key, s)
            h = h.reshape((h.shape[0],) + stage.in_shape)
            h = stage.apply(params, h, k, deterministic)
            if isinstance(h, tuple):    # (y, aux): ground truth drops aux
                h = h[0]
        return h
    return apply
