"""Parallelism core: mesh, collectives, stage packing, the pipeline engine.

This package is the TPU-native replacement for the reference's entire
communication/runtime layer — TensorPipe RPC transport, rendezvous store,
distributed autograd, and RRef object layer
(``/root/reference/simple_distributed.py:8-11,:33-37,:47-57,:109-113,:167-186``).
In the SPMD design none of those survive as separate subsystems: rendezvous is
``jax.distributed.initialize`` (``mesh.py``), the activation/grad hops are
``lax.ppermute`` inside one compiled step (``pipeline.py``), backward through
the hop is JAX autodiff transposing the permute, and "remote references"
dissolve into sharded ``jax.Array`` placement.
"""

from simple_distributed_machine_learning_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    STAGE_AXIS,
    make_mesh,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (  # noqa: F401
    StageMeta,
    pack_stage_params,
    unpack_stage_params,
    wire_decode,
    wire_encode,
)
from simple_distributed_machine_learning_tpu.parallel.pipeline import (  # noqa: F401
    Pipeline,
    Stage,
)
from simple_distributed_machine_learning_tpu.parallel.expert import (  # noqa: F401
    EXPERT_AXIS,
    moe_apply,
    moe_apply_ep,
    moe_init,
)
from simple_distributed_machine_learning_tpu.parallel.overlap import (  # noqa: F401
    allgather_matmul,
    matmul_reducescatter,
    ring_all_gather,
    ring_psum,
    ring_reduce_scatter,
)
