"""Device mesh construction and multi-host bootstrap.

Replaces the reference's process bootstrap — argparse → env exports →
``rpc.init_rpc`` rendezvous (``/root/reference/simple_distributed.py:139-186``)
— with a ``jax.sharding.Mesh`` over the TPU slice and (for multi-host)
``jax.distributed.initialize``. The mesh has two named axes:

- ``"data"``  — data parallelism (batch sharding; grads all-reduced over ICI)
- ``"stage"`` — pipeline parallelism (one pipeline stage per mesh slot;
  activations hop stage→stage+1 via ``lax.ppermute``)
- ``"model"`` — tensor (Megatron-style) parallelism within a stage (hidden
  dim sharded; one ``lax.psum`` per sharded pair — see ``tensor.py``)

Axis order is (data, stage, model), model fastest-varying: tensor-parallel
psums are the chattiest collective so their group gets adjacent device ids;
pipeline neighbours come next; data-parallel gradient all-reduce — once per
step — tolerates the longest paths.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
STAGE_AXIS = "stage"
MODEL_AXIS = "model"


def make_mesh(n_stages: int = 1, n_data: int | None = None,
              n_model: int = 1,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a ``(data, stage, model)`` mesh from the available devices.

    ``n_data`` defaults to ``len(devices) // (n_stages * n_model)`` so the
    whole slice is used. The reference's topology was fixed at exactly 2 ranks
    with the peer name hardcoded (``simple_distributed.py:34``); here the
    topology is derived from the device list.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_stages < 1 or n_model < 1:
        raise ValueError(
            f"n_stages/n_model must be >= 1, got {n_stages}/{n_model}")
    if n_data is None:
        if len(devices) % (n_stages * n_model) != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_stages} "
                f"pipeline stages x {n_model} model shards (pass n_data to "
                f"use a subset)")
        n_data = len(devices) // (n_stages * n_model)
    need = n_data * n_stages * n_model
    if need > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_stages}x{n_model} needs {need} devices, "
            f"have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_data, n_stages, n_model)
    return Mesh(grid, (DATA_AXIS, STAGE_AXIS, MODEL_AXIS))


def bootstrap_distributed(rank: int, world_size: int, master_addr: str,
                          master_port: str | int, timeout_s: int = 300) -> None:
    """Multi-host rendezvous: the reference-compatible bootstrap.

    Maps the reference CLI (``simple_distributed.py:144-165``) onto
    ``jax.distributed.initialize``: ``--rank`` → process_id, ``--world_size`` →
    num_processes, ``--master_addr/--master_port`` → coordinator_address.

    Unlike the reference — which sets ``rpc_timeout=0`` (infinite) and hangs
    forever on a dead peer (``simple_distributed.py:36,:167``; SURVEY §5.3) —
    initialization here has a real timeout.
    """
    if world_size <= 1:
        return  # single-process: nothing to rendezvous
    os.environ.setdefault("JAX_COORDINATOR_TIMEOUT_SECS", str(timeout_s))
    jax.distributed.initialize(
        coordinator_address=f"{master_addr}:{master_port}",
        num_processes=world_size,
        process_id=rank,
        initialization_timeout=timeout_s,
    )
