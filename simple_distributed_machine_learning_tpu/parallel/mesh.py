"""Device mesh construction and multi-host bootstrap.

Replaces the reference's process bootstrap — argparse → env exports →
``rpc.init_rpc`` rendezvous (``/root/reference/simple_distributed.py:139-186``)
— with a ``jax.sharding.Mesh`` over the TPU slice and (for multi-host)
``jax.distributed.initialize``. The mesh has two named axes:

- ``"data"``  — data parallelism (batch sharding; grads all-reduced over ICI)
- ``"stage"`` — pipeline parallelism (one pipeline stage per mesh slot;
  activations hop stage→stage+1 via ``lax.ppermute``)
- ``"model"`` — tensor (Megatron-style) parallelism within a stage (hidden
  dim sharded; one ``lax.psum`` per sharded pair — see ``tensor.py``)
- ``"seq"``   — sequence/context parallelism (token axis sharded; ring
  ppermute or Ulysses all-to-all per attention call — see ``sequence.py``,
  ``ops/attention.py``)
- ``"expert"`` — expert (MoE) parallelism (expert weights sharded; 2x
  all-to-all dispatch per MoE layer — see ``expert.py``)

Axis order is (data, stage, model, seq, expert), innermost fastest-varying:
expert dispatch all-to-alls, sequence parallelism's per-layer ring hops and
tensor parallelism's per-pair psums are the chattiest collectives so their
groups get adjacent device ids; pipeline neighbours come next; data-parallel
gradient all-reduce — once per step — tolerates the longest paths.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
STAGE_AXIS = "stage"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def make_mesh(n_stages: int = 1, n_data: int | None = None,
              n_model: int = 1, n_seq: int = 1, n_expert: int = 1,
              devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a ``(data, stage, model, seq, expert)`` mesh from the devices.

    ``n_data`` defaults to ``len(devices) // (n_stages * n_model * n_seq *
    n_expert)`` so the whole slice is used. The reference's topology was
    fixed at exactly 2 ranks with the peer name hardcoded
    (``simple_distributed.py:34``); here the topology is derived from the
    device list.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if n_stages < 1 or n_model < 1 or n_seq < 1 or n_expert < 1:
        raise ValueError(
            f"n_stages/n_model/n_seq/n_expert must be >= 1, got "
            f"{n_stages}/{n_model}/{n_seq}/{n_expert}")
    per_replica = n_stages * n_model * n_seq * n_expert
    if n_data is None:
        if len(devices) % per_replica != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_stages} "
                f"pipeline stages x {n_model} model shards x {n_seq} "
                f"sequence shards x {n_expert} expert shards (pass n_data "
                f"to use a subset)")
        n_data = len(devices) // per_replica
    need = n_data * per_replica
    if need > len(devices):
        raise ValueError(
            f"mesh {n_data}x{n_stages}x{n_model}x{n_seq}x{n_expert} needs "
            f"{need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(
        n_data, n_stages, n_model, n_seq, n_expert)
    return Mesh(grid,
                (DATA_AXIS, STAGE_AXIS, MODEL_AXIS, SEQ_AXIS, EXPERT_AXIS))


def bootstrap_distributed(rank: int, world_size: int, master_addr: str,
                          master_port: str | int, timeout_s: int = 300) -> None:
    """Multi-host rendezvous: the reference-compatible bootstrap.

    Maps the reference CLI (``simple_distributed.py:144-165``) onto
    ``jax.distributed.initialize``: ``--rank`` → process_id, ``--world_size`` →
    num_processes, ``--master_addr/--master_port`` → coordinator_address.

    Unlike the reference — which sets ``rpc_timeout=0`` (infinite) and hangs
    forever on a dead peer (``simple_distributed.py:36,:167``; SURVEY §5.3) —
    initialization here has a real timeout.
    """
    if world_size <= 1:
        return  # single-process: nothing to rendezvous
    os.environ.setdefault("JAX_COORDINATOR_TIMEOUT_SECS", str(timeout_s))
    try:
        # cross-process collectives on the CPU backend need a transport; gloo
        # is XLA:CPU's built-in one. On TPU this setting is simply unused
        # (ICI/DCN collectives come with the TPU runtime). Must be set before
        # backends initialize — harmless no-op if they already are.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (RuntimeError, ValueError):
        pass
    jax.distributed.initialize(
        coordinator_address=f"{master_addr}:{master_port}",
        num_processes=world_size,
        process_id=rank,
        initialization_timeout=timeout_s,
    )
