"""JAX version compatibility for the shard_map engine.

The framework targets modern JAX (the ``check_vma`` era: ``jax.shard_map``,
``lax.pcast``, ``jax.typeof(...).vma``), but the collective core — ppermute
rings, psum, custom_vjp — predates all of that. This module pins the three
seams where the APIs diverged so the engine also runs on the 0.4.x series
(where ``shard_map`` still lives in ``jax.experimental`` and there is no vma
type system at all):

- :func:`shard_map` — dispatches to ``jax.shard_map`` when present; otherwise
  to ``jax.experimental.shard_map.shard_map`` with ``check_rep=False`` (the
  old replication checker cannot type the engine's ppermute/switch machinery;
  values are bit-identical across the axes the out_specs drop, so taking
  shard 0 is exact).
- :func:`pvary_to` — the vma-anchor cast (``lax.pcast(..., to="varying")``).
  On versions without a vma system there is nothing to anchor: identity.
- :func:`vma_of` — the value's varying-manual-axes set, ``frozenset()`` when
  the concept does not exist.
- :func:`set_host_device_count` — ``jax_num_cpu_devices`` config where it
  exists, silently relying on ``--xla_force_host_platform_device_count``
  (which the callers also set) where it does not.
"""

from __future__ import annotations

import jax
from jax import lax

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)
if _NEW_SHARD_MAP is None:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map as _OLD_SHARD_MAP
else:
    _OLD_SHARD_MAP = None

HAS_VMA = hasattr(lax, "pcast") and hasattr(jax, "typeof")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """``jax.shard_map`` across the experimental→stable API move.

    ``check_vma=None`` means "the caller's default" (vma checking on, where
    the concept exists). Old jax always runs with ``check_rep=False``: its
    rep checker predates the vma algebra the engine's anchors target.
    """
    if _NEW_SHARD_MAP is not None:
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
    return _OLD_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def vma_of(x) -> frozenset:
    """The axes ``x`` is varying over (empty where vma does not exist)."""
    if not HAS_VMA:
        return frozenset()
    return getattr(jax.typeof(x), "vma", frozenset()) or frozenset()


def pvary_to(x: jax.Array, axes: tuple[str, ...]) -> jax.Array:
    """pcast ``x`` to varying over exactly the axes of ``axes`` it does not
    already vary over (pcast rejects mixed already/not-yet-varying sets).
    Identity on jax versions without the vma system.

    The cast only exists to satisfy the vma checker — it is the identity
    value-wise — so in contexts where no checker is active and pcast itself
    objects (e.g. tracing under ``check_vma=False``, where the anchor is
    unnecessary anyway), the value passes through unchanged.
    """
    if not HAS_VMA:
        return x
    missing = tuple(a for a in axes if a not in vma_of(x))
    if not missing:
        return x
    try:
        return lax.pcast(x, missing, to="varying")
    except (ValueError, TypeError, NotImplementedError):
        return x


def axis_size(axis: str) -> int:
    """``lax.axis_size`` where it exists; ``lax.psum(1, axis)`` (which
    constant-folds to the static size through the axis env) elsewhere."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis)
    return lax.psum(1, axis)


def set_host_device_count(n: int) -> None:
    """Force ``n`` virtual CPU devices through the live config (the env-var
    route is latched too early when a sitecustomize imports jax first)."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # pre-jax_num_cpu_devices: the XLA_FLAGS route the callers also set
        # (--xla_force_host_platform_device_count) is the only mechanism
        pass
