"""Tensor (model) parallelism: Megatron-style sharded linear pairs.

Not owed for reference parity (SURVEY §2.2: the reference has no TP), but a
first-class capability of this framework: a ``model`` mesh axis shards the
hidden dimension of a linear pair —

- **column-parallel** first layer: weight ``[d_in, d_hidden/mp]`` per device,
  output stays sharded, the nonlinearity applies elementwise locally;
- **row-parallel** second layer: weight ``[d_hidden/mp, d_out]`` per device,
  partial products are summed with one ``lax.psum`` over ICI.

One all-reduce per pair, exactly the Megatron recipe, expressed as plain
functions to be called inside ``shard_map`` (composable with the pipeline's
``stage`` axis and the ``data`` axis).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.ops.layers import linear_init

MODEL_AXIS = "model"


def tp_pair_init(key: jax.Array, d_in: int, d_hidden: int, d_out: int,
                 n_shards: int) -> list[dict]:
    """Per-shard params for a column→row parallel linear pair.

    Returns a list of ``n_shards`` pytrees; shard i holds columns
    ``[i*h, (i+1)*h)`` of W1 (h = d_hidden/n_shards) and the matching rows of
    W2. Initialization matches the unsharded :func:`linear_init` layers, so a
    TP run is numerically identical to the dense run (see tests).
    """
    if d_hidden % n_shards:
        raise ValueError(f"d_hidden {d_hidden} not divisible by {n_shards}")
    k1, k2 = jax.random.split(key)
    w1 = linear_init(k1, d_in, d_hidden)
    w2 = linear_init(k2, d_hidden, d_out)
    h = d_hidden // n_shards
    shards = []
    for i in range(n_shards):
        shards.append({
            "w1": {"w": w1["w"][:, i * h:(i + 1) * h],
                   "b": w1["b"][i * h:(i + 1) * h]},
            "w2": {"w": w2["w"][i * h:(i + 1) * h, :],
                   # bias added once, on shard 0 only (it is not sharded)
                   "b": w2["b"] if i == 0 else jnp.zeros_like(w2["b"])},
        })
    return shards


def tp_pair_apply(params: dict, x: jax.Array, activation=jax.nn.relu,
                  axis: str = MODEL_AXIS) -> jax.Array:
    """Column→activation→row parallel pair. Call inside shard_map; ``params``
    is THIS device's shard. One psum over ``axis`` per call."""
    h = activation(x @ params["w1"]["w"] + params["w1"]["b"])
    partial_out = h @ params["w2"]["w"] + params["w2"]["b"]
    return lax.psum(partial_out, axis)


def stack_tp_shards(shards: list[dict]):
    """Stack per-shard pytrees along a leading axis for ``P('model')``
    placement: leaf i of the result has shape ``[n_shards, ...]``."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *shards)
