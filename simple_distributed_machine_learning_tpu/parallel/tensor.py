"""Tensor (model) parallelism: Megatron-style sharded linear pairs.

Not owed for reference parity (SURVEY §2.2: the reference has no TP), but a
first-class capability of this framework: a ``model`` mesh axis shards the
hidden dimension of a linear pair —

- **column-parallel** first layer: weight ``[d_in, d_hidden/mp]`` per device,
  output stays sharded, the nonlinearity applies elementwise locally;
- **row-parallel** second layer: weight ``[d_hidden/mp, d_out]`` per device,
  partial products are summed with one ``lax.psum`` over ICI.

One all-reduce per pair, exactly the Megatron recipe, expressed as plain
functions to be called inside ``shard_map`` (composable with the pipeline's
``stage`` axis and the ``data`` axis).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.ops.layers import linear_init
from simple_distributed_machine_learning_tpu.parallel.mesh import MODEL_AXIS


def tp_pair_init(key: jax.Array, d_in: int, d_hidden: int, d_out: int,
                 n_shards: int) -> list[dict]:
    """Per-shard params for a column→row parallel linear pair.

    Returns a list of ``n_shards`` pytrees; shard i holds columns
    ``[i*h, (i+1)*h)`` of W1 (h = d_hidden/n_shards) and the matching rows of
    W2. Initialization matches the unsharded :func:`linear_init` layers, so a
    TP run is numerically identical to the dense run (see tests).
    """
    if d_hidden % n_shards:
        raise ValueError(f"d_hidden {d_hidden} not divisible by {n_shards}")
    k1, k2 = jax.random.split(key)
    w1 = linear_init(k1, d_in, d_hidden)
    w2 = linear_init(k2, d_hidden, d_out)
    h = d_hidden // n_shards
    shards = []
    for i in range(n_shards):
        shards.append({
            "w1": {"w": w1["w"][:, i * h:(i + 1) * h],
                   "b": w1["b"][i * h:(i + 1) * h]},
            # w2's bias is REPLICATED on every shard and added after the
            # psum: each replica then receives the identical cotangent, so
            # SPMD updates keep the copies in sync and the effective bias
            # trains at exactly the dense rate (a shard-0-only bias added
            # pre-psum would train n_shards times too fast)
            "w2": {"w": w2["w"][i * h:(i + 1) * h, :], "b": w2["b"]},
        })
    return shards


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def grad_sync(x: jax.Array, axis: str, overlap: str = "none") -> jax.Array:
    """Identity forward; psum over ``axis`` backward.

    For params that are REPLICATED over a mesh axis inside ``shard_map`` but
    carried in per-device (axis-sharded) storage: when the loss is built from
    axis-replicated values, the transpose machinery splits the loss cotangent
    evenly across the axis (each replica sees 1/axis_size of it). Leaves whose
    forward path crosses a psum recover the full cotangent through the psum's
    transpose; leaves that stay replicated (e.g. a row-parallel pair's output
    bias, or a whole non-tensor-parallel stage on a model>1 mesh) do not —
    their grads come out 1/axis_size of the true value, and replicas would
    train too slowly. Wrapping such params in ``grad_sync`` restores the full
    gradient on every replica (and keeps replicas bit-identical, since each
    gets the same psum).

    ``overlap='ring'`` runs the backward all-reduce as the chunked ppermute
    ring of :func:`~.overlap.ring_psum` instead of one blocking ``lax.psum``,
    so the gradient sync of wide replicated leaves hides its ICI transfer
    under neighbouring backward compute (ring summation order: replicas stay
    bit-identical to each other, tolerance-equal to the monolithic psum).
    """
    return x


def _grad_sync_fwd(x, axis, overlap):
    return x, None


def _grad_sync_bwd(axis, overlap, _, ct):
    if overlap == "ring":
        from simple_distributed_machine_learning_tpu.parallel.overlap import (
            _bwd_perm,
            _ring_psum_impl,
        )
        return (_ring_psum_impl(ct, axis, perm_fn=_bwd_perm,
                                tag="grad_sync_ring"),)
    return (lax.psum(ct, axis),)


grad_sync.defvjp(_grad_sync_fwd, _grad_sync_bwd)


def tp_pair_apply(params: dict, x: jax.Array, activation=jax.nn.relu,
                  axis: str = MODEL_AXIS, overlap: str = "none") -> jax.Array:
    """Column→activation→row parallel pair. Call inside shard_map; ``params``
    is THIS device's shard. One all-reduce over ``axis`` per call; the output
    bias is replicated and added after the reduce (see :func:`tp_pair_init`),
    with :func:`grad_sync` restoring its full (unsplit) gradient.

    ``overlap='none'``: the Megatron monolithic ``lax.psum`` — the chip
    blocks for the full collective after the row matmul. ``overlap='ring'``:
    the chunked-psum collective matmul of :func:`~.overlap.ring_psum` — the
    partial products ring-shift chunk by chunk so each hop hides under
    another chunk's accumulate (forward AND backward; tolerance-equal, see
    overlap.py's numerics note).

    The ``pmean`` around the bias is the vma-checker's replication proof:
    the replicas are bit-identical (grad_sync keeps them in sync), so it is
    the identity value-wise, and its transpose (ct/n per replica) composes
    with grad_sync's psum to hand every replica the full cotangent — the
    same accounting the implicit replicated out_spec used to do. On the ring
    path the reduced value stays varying-typed (ppermutes carry no
    replication proof), so the bias term is pcast up to match."""
    h = activation(x @ params["w1"]["w"] + params["w1"]["b"])
    z = h @ params["w2"]["w"]
    bias = lax.pmean(grad_sync(params["w2"]["b"], axis, overlap), axis)
    if overlap == "ring":
        from simple_distributed_machine_learning_tpu.parallel.compat import (
            pvary_to,
        )
        from simple_distributed_machine_learning_tpu.parallel.overlap import (
            ring_psum,
        )
        return ring_psum(z, axis) + pvary_to(bias, (axis,))
    return lax.psum(z, axis) + bias


def stack_tp_shards(shards: list[dict]):
    """Stack per-shard pytrees along a leading axis for ``P('model')``
    placement: leaf i of the result has shape ``[n_shards, ...]``."""
    return jax.tree.map(lambda *ls: jnp.stack(ls), *shards)


def make_mlp_tp_stages(key: jax.Array, dims, n_stages: int, n_model: int,
                       overlap: str = "none"):
    """Tensor-parallel MLP pipeline stages: dp x pp x tp in one step.

    Like :func:`~..models.mlp.make_mlp_stages` but each stage is a
    column→row parallel linear *pair* sharded ``n_model`` ways over the
    ``model`` mesh axis, so ``dims`` must have ``2 * n_stages`` layers
    (length ``2 * n_stages + 1``) and every hidden width must divide by
    ``n_model``. Initialization splits the same dense init as the unsharded
    layers, so the TP pipeline matches a dense single-device run to float
    tolerance (tests/test_tp_pipeline.py).

    ``overlap``: the collective schedule of every pair's all-reduce —
    ``'none'`` (monolithic psum) or ``'ring'`` (latency-hiding chunked ring,
    ``overlap.ring_psum``; same losses to float tolerance).

    Returns ``(stages, wire_dim, out_dim)`` for :class:`~.pipeline.Pipeline`
    on a ``make_mesh(n_stages=..., n_model=...)`` mesh.
    """
    from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
    from simple_distributed_machine_learning_tpu.parallel.overlap import (
        check_overlap,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage

    check_overlap(overlap)
    dims = [int(d) for d in dims]
    if len(dims) != 2 * n_stages + 1:
        raise ValueError(
            f"TP stages hold one column->row pair each: need exactly "
            f"{2 * n_stages} layers for {n_stages} stages, got {len(dims) - 1}")
    keys = jax.random.split(key, n_stages)

    stages = []
    for s in range(n_stages):
        d_in, d_h, d_out = dims[2 * s], dims[2 * s + 1], dims[2 * s + 2]
        shards = tuple(tp_pair_init(keys[s], d_in, d_h, d_out, n_model))
        is_last = s == n_stages - 1

        def apply(params, x, key, deterministic, _last=is_last):
            y = tp_pair_apply(params, x, activation=jax.nn.relu,
                              overlap=overlap)
            return log_softmax(y) if _last else jax.nn.relu(y)

        stages.append(Stage(apply=apply, params=shards[0],
                            in_shape=(d_in,), shards=shards))
    # only stage inputs/outputs (even-index dims) cross the wire; hidden
    # widths live inside a stage and must not inflate the ppermute buffers
    return stages, max(dims[::2]), dims[-1]
