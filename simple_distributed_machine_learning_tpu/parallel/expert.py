"""Expert parallelism: mixture-of-experts FFN with all-to-all dispatch.

Not owed for reference parity (SURVEY §2.2: the reference has no MoE), but a
first-class parallelism strategy of this framework, alongside pipeline
(``pipeline.py``), tensor (``tensor.py``) and sequence (``sequence.py``)
parallelism.

TPU-first design (GShard/Switch recipe, not a torch translation):

- **routing** is a small matmul + top-k over experts; the dispatch and combine
  steps are expressed as one-hot einsums (``[T,E,C]`` dispatch tensor against
  ``[T,d]`` tokens), which XLA tiles onto the MXU — no gather/scatter with
  dynamic shapes, no data-dependent control flow, so the whole layer stays
  inside one compiled program;
- **capacity** is static (``capacity_factor * k * T / E`` slots per expert):
  tokens beyond an expert's capacity are dropped (their combine weight is 0 and
  the residual path carries them), which keeps every shape static for XLA;
- **expert parallelism** shards the expert axis over an ``"expert"`` mesh axis:
  each device holds ``E / D`` experts and a ``1/D`` shard of the tokens. One
  ``lax.all_to_all`` ships each expert's capacity buffer to its owner, the
  owner runs its experts' FFN on a ``[E/D, D·C, d]`` batch (one big MXU
  matmul), and a second ``all_to_all`` ships results back — the canonical
  2×all-to-all MoE schedule, riding ICI.

The dense path (:func:`moe_apply`) is the single-device ground truth; the EP
path (:func:`moe_apply_ep`, called inside ``shard_map``) computes exactly the
same function when the token shards match (parity-tested in
``tests/test_expert_parallel.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.parallel.compat import (
    axis_size as _axis_size,
)

from simple_distributed_machine_learning_tpu.ops.layers import linear_init

EXPERT_AXIS = "expert"


def moe_init(key: jax.Array, d_model: int, d_hidden: int, n_experts: int,
             dtype=jnp.float32) -> dict:
    """Params for a MoE FFN: router + ``n_experts`` two-layer MLPs.

    Expert weights are stacked on a leading ``[E, ...]`` axis so the expert
    axis can be sharded ``P('expert')`` and the per-expert matmul is a single
    batched einsum.
    """
    kr, *ke = jax.random.split(key, 1 + n_experts)
    experts = [
        {"in": linear_init(jax.random.fold_in(k, 0), d_model, d_hidden, dtype),
         "out": linear_init(jax.random.fold_in(k, 1), d_hidden, d_model, dtype)}
        for k in ke
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *experts)
    return {
        # router bias-free (Switch convention); small init keeps early routing
        # near-uniform
        "router": 0.02 * jax.random.normal(kr, (d_model, n_experts), dtype),
        "experts": stacked,
    }


def n_experts_of(params: dict) -> int:
    return params["router"].shape[-1]


def _route(params: dict, x: jax.Array, k: int, capacity: int):
    """Top-k routing → dispatch/combine tensors.

    x: [T, d] tokens. Returns ``(dispatch [T,E,C] one-hot, combine [T,E,C]
    gate-weighted, aux_loss scalar)``. Static shapes throughout; tokens past an
    expert's capacity get zero combine weight (dropped — the caller's residual
    connection carries them).
    """
    T, _ = x.shape
    E = n_experts_of(params)
    logits = x @ params["router"]                       # [T, E]
    gates = jax.nn.softmax(logits, axis=-1)

    # Switch-style load-balancing aux loss: E * sum_e f_e * p_e where f_e is
    # the fraction of tokens whose top-1 choice is e and p_e the mean gate.
    top1 = jnp.argmax(gates, axis=-1)
    f = jnp.mean(jax.nn.one_hot(top1, E), axis=0)
    p = jnp.mean(gates, axis=0)
    aux_loss = E * jnp.sum(f * p)

    _, topk_idx = lax.top_k(gates, k)                   # [T, k]
    # renormalize the selected gates so they sum to 1 per token
    topk_gate = jnp.take_along_axis(gates, topk_idx, axis=-1)
    topk_gate = topk_gate / jnp.maximum(
        jnp.sum(topk_gate, axis=-1, keepdims=True), 1e-9)

    # position of each (token, choice) in its expert's capacity buffer:
    # flatten choices in priority order (all rank-0 choices first, token order
    # within a rank) so earlier tokens win capacity slots deterministically.
    sel = jax.nn.one_hot(topk_idx, E, dtype=jnp.int32)  # [T, k, E]
    sel_flat = sel.transpose(1, 0, 2).reshape(k * T, E)  # [k*T, E] rank-major
    pos_flat = jnp.cumsum(sel_flat, axis=0) - sel_flat   # slot index per entry
    pos = pos_flat.reshape(k, T, E).transpose(1, 0, 2)   # [T, k, E]
    in_cap = (pos < capacity) & (sel > 0)

    # dispatch[t, e, c] = 1 iff token t occupies slot c of expert e.
    # Built one routing rank at a time: peak memory is one [T, E, C] tensor,
    # not [T, k, E, C] (C scales with T, so the k axis would square the cost).
    dispatch = jnp.zeros((T, E, capacity), x.dtype)
    combine = jnp.zeros((T, E, capacity), x.dtype)
    for j in range(k):
        oh = jnp.where(in_cap[:, j, :], 1.0, 0.0)[..., None] * jax.nn.one_hot(
            jnp.clip(pos[:, j, :], 0, capacity - 1), capacity)   # [T, E, C]
        dispatch = dispatch + oh
        combine = combine + oh * topk_gate[:, j, None, None]
    return dispatch, combine, aux_loss


def _expert_ffn(experts: dict, xs: jax.Array, activation=jax.nn.gelu
                ) -> jax.Array:
    """Batched per-expert MLP. xs: [E, C, d] -> [E, C, d]; one einsum per
    layer so the E·C token block hits the MXU as a single contraction."""
    h = jnp.einsum("ecd,edh->ech", xs, experts["in"]["w"])
    h = activation(h + experts["in"]["b"][:, None, :])
    y = jnp.einsum("ech,ehd->ecd", h, experts["out"]["w"])
    return y + experts["out"]["b"][:, None, :]


def default_capacity(n_tokens: int, n_experts: int, k: int,
                     capacity_factor: float = 1.25) -> int:
    return max(1, int(capacity_factor * k * n_tokens / n_experts))


def moe_apply(params: dict, x: jax.Array, k: int = 2,
              capacity: int | None = None) -> tuple[jax.Array, jax.Array]:
    """Dense (single-device) MoE FFN — the EP path's ground truth.

    x: [T, d] (flatten batch/sequence first). Returns ``(y [T, d], aux_loss)``.
    """
    T, _ = x.shape
    E = n_experts_of(params)
    capacity = default_capacity(T, E, k) if capacity is None else capacity
    dispatch, combine, aux = _route(params, x, k, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)   # [E, C, d]
    expert_out = _expert_ffn(params["experts"], expert_in)
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, aux


def moe_apply_ep(params: dict, x: jax.Array, k: int = 2,
                 capacity: int | None = None, axis: str = EXPERT_AXIS,
                 overlap: str = "none") -> tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE FFN — call inside ``shard_map`` over ``axis``.

    ``params['experts']`` is THIS device's ``[E/D, ...]`` expert shard; the
    router is replicated. ``x``: this device's ``[T_local, d]`` token shard.
    ``capacity`` is per (expert, source device) — each expert's total buffer is
    ``D * capacity``. Returns this shard's ``(y [T_local, d], aux_loss)``
    (aux is psum-averaged over the axis so every shard sees the global value).

    ``overlap='none'``: the canonical 2x ``all_to_all`` schedule — dispatch
    everything, run one batched FFN, ship everything back; the chip blocks
    for each full exchange. ``overlap='ring'``: the dispatch/combine exchange
    decomposes into ``D-1`` ppermute offset hops (``parallel/overlap.py``
    style): each remote shard's capacity buffer FFNs as it arrives while the
    next offset's buffer is in flight, and results stream back on the
    mirrored permute — same math per capacity slot, so parity with the
    all_to_all path is to float tolerance (the FFN matmul batches differ:
    ``[E/D, C, d]`` per chunk vs ``[E/D, D*C, d]`` in one piece).
    """
    from simple_distributed_machine_learning_tpu.parallel.overlap import (
        check_overlap,
    )
    from simple_distributed_machine_learning_tpu.utils.profiler import (
        annotate_scope,
    )

    check_overlap(overlap)
    D = _axis_size(axis)
    T, _ = x.shape
    E = n_experts_of(params)                             # global expert count
    capacity = default_capacity(T, E, k) if capacity is None else capacity
    dispatch, combine, aux = _route(params, x, k, capacity)
    aux = lax.pmean(aux, axis)

    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)   # [E, C, d] local contrib
    if overlap == "ring" and D > 1:
        e_loc = E // D
        i = lax.axis_index(axis)
        expert_out = jnp.zeros_like(expert_in)
        # own chunk first — no hop to hide it under
        with annotate_scope("moe_ep_ring/chunk0"):
            own = lax.dynamic_slice_in_dim(expert_in, i * e_loc, e_loc, 0)
            expert_out = lax.dynamic_update_slice_in_dim(
                expert_out, _expert_ffn(params["experts"], own), i * e_loc, 0)
        for s in range(1, D):
            # offset-s exchange: send the chunk destined for owner i+s, FFN
            # the chunk arriving from source i-s, return it on the mirrored
            # permute — XLA overlaps offset s+1's hop with offset s's FFN
            fwd = [(j, (j + s) % D) for j in range(D)]
            rev = [(j, (j - s) % D) for j in range(D)]
            dst = (i + s) % D
            with annotate_scope(f"moe_ep_ring/hop{s}"):
                send = lax.dynamic_slice_in_dim(expert_in, dst * e_loc,
                                                e_loc, 0)
                recv = lax.ppermute(send, axis, fwd)
            with annotate_scope(f"moe_ep_ring/chunk{s}"):
                y_chunk = _expert_ffn(params["experts"], recv)
            with annotate_scope(f"moe_ep_ring/return{s}"):
                back = lax.ppermute(y_chunk, axis, rev)
                expert_out = lax.dynamic_update_slice_in_dim(
                    expert_out, back, dst * e_loc, 0)
    else:
        # ship each expert's buffer to its owner: split the E axis D-ways,
        # concat the shards' contributions along capacity → [E/D, D*C, d]
        expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                                   concat_axis=1, tiled=True)
        expert_out = _expert_ffn(params["experts"], expert_in)
        # inverse exchange: send each source shard its slice back → [E, C, d]
        expert_out = lax.all_to_all(expert_out, axis, split_axis=1,
                                    concat_axis=0, tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, aux
