"""Profiling hooks (SURVEY §5.1: absent in the reference, cheap under JAX).

Wraps ``jax.profiler`` so any training window can be captured as an XProf /
TensorBoard trace — the tool for verifying the pipeline actually overlaps
ICI transfer with compute (the ≥10× claim's mechanism, SURVEY §3.3).
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/sdml_trace", enabled: bool = True):
    """``with trace('/tmp/tb'): step(...)`` → open in TensorBoard/XProf."""
    if not enabled:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the trace timeline.

    Host-side: annotates the wall-clock interval of the Python block (dispatch,
    blocking reads). For regions INSIDE a jitted program use
    :func:`annotate_scope` — a TraceAnnotation entered at trace time would
    label the tracing, not the execution.
    """
    return jax.profiler.TraceAnnotation(name)


def annotate_scope(name: str):
    """Named region for ops inside a compiled program.

    ``jax.named_scope`` prefixes the HLO metadata of every op traced under it,
    which XProf surfaces as a grouped region on the device timeline — the
    right tool for showing that e.g. each chunk of a ring collective matmul
    (``parallel/overlap.py``) has its compute overlapped with the next chunk's
    ICI transfer.
    """
    return jax.named_scope(name)
