"""Profiling hooks (SURVEY §5.1: absent in the reference, cheap under JAX).

Wraps ``jax.profiler`` so any training window can be captured as an XProf /
TensorBoard trace — the tool for verifying the pipeline actually overlaps
ICI transfer with compute (the ≥10× claim's mechanism, SURVEY §3.3).
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/sdml_trace", enabled: bool = True):
    """``with trace('/tmp/tb'): step(...)`` → open in TensorBoard/XProf."""
    if not enabled:
        yield
        return
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)
