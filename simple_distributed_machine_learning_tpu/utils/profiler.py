"""Profiling hooks (SURVEY §5.1: absent in the reference, cheap under JAX).

Wraps ``jax.profiler`` so any training window can be captured as an XProf /
TensorBoard trace — the tool for verifying the pipeline actually overlaps
ICI transfer with compute (the ≥10× claim's mechanism, SURVEY §3.3).
"""

from __future__ import annotations

import contextlib
import os
import sys

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/sdml_trace", enabled: bool = True):
    """``with trace('/tmp/tb') as d: step(...)`` → open ``d`` in
    TensorBoard/XProf.

    Yields the logdir (``None`` when no trace is being captured) so tooling
    can hand the path on. Hardened so the profiler can never take a run
    down or leak a started trace:

    - ``enabled=False`` touches nothing (no directory creation) and yields
      ``None``;
    - an uncreatable ``logdir`` degrades to disabled with a stderr note
      instead of raising — a full disk must not kill the training it was
      profiling;
    - stop is idempotent: it runs only if start actually succeeded, and a
      stop failure (e.g. the body already stopped the trace, or the first
      flush never completed before the body raised) is swallowed so the
      body's own exception — the one that matters — propagates.
    """
    if not enabled:
        yield None
        return
    try:
        os.makedirs(logdir, exist_ok=True)
    except OSError as e:
        print(f"profiler: cannot create trace dir {logdir!r} ({e}); "
              f"tracing disabled for this window", file=sys.stderr)
        yield None
        return
    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except RuntimeError as e:
        # another trace is already running (nested trace() windows): keep
        # the outer capture alive rather than crashing the run
        print(f"profiler: start_trace failed ({e}); continuing untraced",
              file=sys.stderr)
        yield None
        return
    try:
        yield logdir
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except RuntimeError:
                pass  # already stopped / never fully started: nothing leaks


def annotate(name: str):
    """Named region that shows up on the trace timeline.

    Host-side: annotates the wall-clock interval of the Python block (dispatch,
    blocking reads). For regions INSIDE a jitted program use
    :func:`annotate_scope` — a TraceAnnotation entered at trace time would
    label the tracing, not the execution.
    """
    return jax.profiler.TraceAnnotation(name)


def annotate_scope(name: str):
    """Named region for ops inside a compiled program.

    ``jax.named_scope`` prefixes the HLO metadata of every op traced under it,
    which XProf surfaces as a grouped region on the device timeline — the
    right tool for showing that e.g. each chunk of a ring collective matmul
    (``parallel/overlap.py``) has its compute overlapped with the next chunk's
    ICI transfer.
    """
    return jax.named_scope(name)
