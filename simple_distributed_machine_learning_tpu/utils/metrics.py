"""Step timing and throughput metering.

The reference never measures time or throughput (SURVEY §6 — its only output
is loss/accuracy prints); the driver's north-star metric is samples/sec/chip,
so the framework meters it natively.
"""

from __future__ import annotations

import time


class Throughput:
    """Tracks samples/sec over a window of steps (host-side wall clock)."""

    def __init__(self) -> None:
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._samples = 0
        self._steps = 0

    def update(self, n_samples: int) -> None:
        self._samples += n_samples
        self._steps += 1

    @property
    def samples_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._samples / dt if dt > 0 else 0.0

    @property
    def steps_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._steps / dt if dt > 0 else 0.0
