"""Utilities: metrics, profiling, failure detection.

Deliberately NO re-exports here: the heartbeat watchdog's monitor runs as a
stdlib-only subprocess via ``python -m ...utils.failure`` (see failure.py),
whose import chain passes through this ``__init__`` — any eager import of
``profiler`` (which imports jax) or siblings would break that isolation.
Import the submodules directly.
"""
