"""Utilities: metrics, timing, logging."""
