"""Failure detection: the dead-peer watchdog (SURVEY §5.3).

The reference sets ``rpc_timeout=0`` and hangs forever when a peer dies
(``/root/reference/simple_distributed.py:36,:167``). XLA collectives inside a
compiled step share that failure mode: a gloo/DCN send whose counterpart is
gone never completes, and the Python main thread is blocked inside the
runtime where no exception can reach it. The watchdog runs BESIDE the
training loop:

- rank 0 listens on a TCP port; every other rank connects and streams
  heartbeat bytes at ``interval``;
- a crash is detected two ways: the kernel closes a dead process's socket
  (EOF without the goodbye byte — immediate), or heartbeats go stale for
  ``timeout`` seconds (frozen process / severed network);
- on detection every surviving rank writes a diagnostic to stderr and
  hard-exits (``os._exit``) with :data:`EXIT_PEER_LOST` — the only reliable
  way out, since the main thread may be parked inside a collective that will
  never complete;
- clean shutdown is protocol-distinguished: :meth:`HeartbeatWatchdog.stop`
  sends a goodbye byte first, so a peer that finishes earlier never trips
  the others.

This turns the reference's infinite hang into a prompt, scriptable, nonzero
exit (tests/test_multiprocess.py::test_dead_peer_aborts_rank0).
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time

EXIT_PEER_LOST = 13
_HB = b"h"      # heartbeat byte
_BYE = b"b"     # clean-shutdown byte


class HeartbeatWatchdog:
    """Dead-peer detector over a star TCP topology (rank 0 at the center).

    ``start()`` after the collective rendezvous (all processes exist by
    then); ``stop()`` before process exit. All threads are daemons; a
    watchdog failure calls ``os._exit(EXIT_PEER_LOST)``.
    """

    def __init__(self, rank: int, world_size: int, master_addr: str,
                 port: int, interval: float = 1.0, timeout: float = 30.0):
        self.rank = rank
        self.world_size = world_size
        self.addr = master_addr
        self.port = int(port)
        self.interval = float(interval)
        self.timeout = float(timeout)
        self._stopping = False
        self._threads: list[threading.Thread] = []
        self._server: socket.socket | None = None
        self._client: socket.socket | None = None
        self._last_seen: dict[int, float] = {}
        self._said_bye: set[int] = set()
        self._master_bye = False
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "HeartbeatWatchdog":
        if self.world_size <= 1:
            return self
        if self.rank == 0:
            self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._server.bind((self.addr, self.port))
            self._server.listen(self.world_size)
            self._spawn(self._accept_loop)
            self._spawn(self._staleness_loop)
        else:
            self._spawn(self._client_loop)
        return self

    def stop(self) -> None:
        self._stopping = True
        try:
            if self._client is not None:
                self._client.sendall(_BYE)
                self._client.close()
        except OSError:
            pass
        # rank 0: tell every peer this is a clean exit before closing, so a
        # peer still mid-training doesn't read the EOF as a master crash
        for conn in self._conns:
            try:
                conn.sendall(_BYE)
                conn.close()
            except OSError:
                pass
        try:
            if self._server is not None:
                self._server.close()
        except OSError:
            pass

    # -- failure ----------------------------------------------------------

    def _fail(self, what: str) -> None:
        if self._stopping:
            return
        sys.stderr.write(
            f"[watchdog] rank {self.rank}: {what} — aborting run "
            f"(the reference would hang forever here; SURVEY §5.3)\n")
        sys.stderr.flush()
        os._exit(EXIT_PEER_LOST)

    # -- rank 0: server side ----------------------------------------------

    def _spawn(self, fn) -> None:
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept_loop(self) -> None:
        assert self._server is not None
        next_id = 0
        while not self._stopping and next_id < self.world_size - 1:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return      # server closed by stop()
            next_id += 1
            peer = next_id  # connection order stands in for rank identity
            with self._lock:
                self._last_seen[peer] = time.monotonic()
                self._conns.append(conn)
            self._spawn(lambda c=conn, p=peer: self._reader(c, p))

    def _reader(self, conn: socket.socket, peer: int) -> None:
        try:
            while True:
                data = conn.recv(64)
                if not data:
                    break               # EOF: peer's socket closed
                with self._lock:
                    self._last_seen[peer] = time.monotonic()
                    if _BYE in data:
                        self._said_bye.add(peer)
        except OSError:
            pass
        with self._lock:
            graceful = peer in self._said_bye
        if not graceful:
            self._fail(f"peer {peer} vanished (socket closed without "
                       f"goodbye — killed or crashed)")

    def _staleness_loop(self) -> None:
        deadline_first = time.monotonic() + self.timeout
        while not self._stopping:
            time.sleep(self.interval)
            now = time.monotonic()
            with self._lock:
                n_connected = len(self._last_seen)
                stale = [p for p, ts in self._last_seen.items()
                         if p not in self._said_bye
                         and now - ts > self.timeout]
            if stale:
                self._fail(f"peer(s) {stale} stopped heartbeating for "
                           f">{self.timeout:.0f}s (frozen or unreachable)")
            if (n_connected < self.world_size - 1
                    and now > deadline_first):
                self._fail(
                    f"only {n_connected}/{self.world_size - 1} peers "
                    f"connected their heartbeat within {self.timeout:.0f}s")

    # -- rank > 0: client side --------------------------------------------

    def _client_loop(self) -> None:
        deadline = time.monotonic() + self.timeout
        sock = None
        while not self._stopping:
            try:
                sock = socket.create_connection((self.addr, self.port),
                                                timeout=self.interval)
                break
            except OSError:
                if time.monotonic() > deadline:
                    self._fail(f"could not reach rank 0's heartbeat port "
                               f"{self.addr}:{self.port} within "
                               f"{self.timeout:.0f}s")
                    return
                time.sleep(0.2)
        if sock is None:
            return
        self._client = sock
        # rank 0 never writes; a recv returning EOF means its socket died.
        # Watch for that in a side thread while the main loop heartbeats.
        self._spawn(lambda: self._watch_master(sock))
        while not self._stopping:
            try:
                sock.sendall(_HB)
            except OSError:
                # a send failure AFTER rank 0's goodbye is just the socket
                # draining post-exit — not a peer loss
                if not self._master_bye:
                    self._fail("rank 0 unreachable (heartbeat send failed)")
                return
            time.sleep(self.interval)

    def _watch_master(self, sock: socket.socket) -> None:
        while True:
            try:
                data = sock.recv(64)
            except OSError:
                return
            if _BYE in data:
                self._master_bye = True   # clean exit: sends may now fail
                return
            if not data:
                if not self._stopping:
                    self._fail("rank 0 closed the heartbeat channel "
                               "without goodbye")
                return
