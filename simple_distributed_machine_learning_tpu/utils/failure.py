"""Failure detection: the dead-peer watchdog (SURVEY §5.3).

The reference sets ``rpc_timeout=0`` and hangs forever when a peer dies
(``/root/reference/simple_distributed.py:36,:167``). XLA collectives inside a
compiled step share that failure mode: a gloo/DCN send whose counterpart is
gone never completes, and the Python main thread is blocked inside the
runtime where no exception can reach it. The watchdog runs BESIDE the
training loop:

- rank 0 listens on a TCP port; every other rank connects and streams
  heartbeat bytes at ``interval``;
- a crash is detected two ways: the kernel closes a dead process's socket
  (EOF without the goodbye byte — immediate), or heartbeats go stale for
  ``timeout`` seconds (frozen process / severed network);
- on detection every surviving rank writes a diagnostic to stderr and
  hard-exits (``os._exit``) with :data:`EXIT_PEER_LOST` — the only reliable
  way out, since the main thread may be parked inside a collective that will
  never complete;
- clean shutdown is protocol-distinguished: :meth:`HeartbeatWatchdog.stop`
  sends a goodbye byte first, so a peer that finishes earlier never trips
  the others.

This turns the reference's infinite hang into a prompt, scriptable, nonzero
exit (tests/test_multiprocess.py::test_dead_peer_aborts_rank0).

**Why a subprocess** (:func:`spawn_watchdog`, what the CLI uses): a Python
thread only runs when it can take the GIL, and a rank whose main thread is
parked inside a native collective that blocks WITH the GIL held (observed
with gloo sends on the CPU backend) freezes every in-process thread — the
watchdog included. The spawned monitor is a separate stdlib-only process
(no jax import — its env disables the sitecustomize TPU plugin hook), so it
keeps running no matter what the trainer process is doing, and on failure it
SIGTERMs (then SIGKILLs) the trainer. In-process
:class:`HeartbeatWatchdog` remains the protocol engine and is what the
subprocess runs internally.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import threading
import time

# deterministic chaos hook (stdlib-only import — safe in the monitor
# subprocess): a scheduled frozen-peer fault at the "watchdog.heartbeat"
# site makes a rank stop heartbeating with its socket open, the frozen-
# process signature the staleness monitor must catch (resilience/faults.py)
from simple_distributed_machine_learning_tpu.resilience.faults import (
    check as _check_fault,
)

EXIT_PEER_LOST = 13
_HB = b"h"      # heartbeat byte
_BYE = b"b"     # clean-shutdown byte


def _abort_message(rank: int, what: str) -> str:
    """The one diagnostic format both the in-process and subprocess paths
    emit — tests/test_multiprocess.py greps for 'aborting run'."""
    return (f"[watchdog] rank {rank}: {what} — aborting run "
            f"(the reference would hang forever here; SURVEY §5.3)\n")


class HeartbeatWatchdog:
    """Dead-peer detector over a star TCP topology (rank 0 at the center).

    ``start()`` after the collective rendezvous (all processes exist by
    then); ``stop()`` before process exit. All threads are daemons; a
    watchdog failure calls ``os._exit(EXIT_PEER_LOST)``.
    """

    def __init__(self, rank: int, world_size: int, master_addr: str,
                 port: int, interval: float = 1.0, timeout: float = 30.0,
                 fail_handler=None):
        self.rank = rank
        self.world_size = world_size
        self.addr = master_addr
        self.port = int(port)
        self.interval = float(interval)
        self.timeout = float(timeout)
        # tests inject a recorder; production hard-exits (os._exit is the
        # only way out of a main thread parked inside a dead collective)
        self._fail_handler = fail_handler
        self._stopping = False
        self._threads: list[threading.Thread] = []
        self._server: socket.socket | None = None
        self._client: socket.socket | None = None
        self._last_seen: dict[int, float] = {}
        self._said_bye: set[int] = set()
        self._master_bye = False
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "HeartbeatWatchdog":
        if self.world_size <= 1:
            return self
        sys.stderr.write(f"[watchdog] rank {self.rank}: started "
                         f"({self.addr}:{self.port}, timeout "
                         f"{self.timeout:.0f}s)\n")
        sys.stderr.flush()
        if self.rank == 0:
            self._spawn(self._accept_loop)
            self._spawn(self._staleness_loop)
        else:
            self._spawn(self._client_loop)
        return self

    def stop(self, goodbye: bool = True) -> None:
        """``goodbye=False`` closes abruptly (no _BYE): used when the
        process being monitored CRASHED — peers must read the disconnect as
        a failure, not a clean exit."""
        self._stopping = True
        try:
            if self._client is not None:
                if goodbye:
                    self._client.sendall(_BYE)
                self._client.close()
        except OSError:
            pass
        # rank 0: tell every peer this is a clean exit before closing, so a
        # peer still mid-training doesn't read the EOF as a master crash
        for conn in self._conns:
            try:
                if goodbye:
                    conn.sendall(_BYE)
                conn.close()
            except OSError:
                pass
        try:
            if self._server is not None:
                self._server.close()
        except OSError:
            pass

    # -- failure ----------------------------------------------------------

    def _fail(self, what: str) -> None:
        if self._stopping:
            return
        if self._fail_handler is not None:
            self._fail_handler(what)
            return
        sys.stderr.write(_abort_message(self.rank, what))
        sys.stderr.flush()
        os._exit(EXIT_PEER_LOST)

    # -- rank 0: server side ----------------------------------------------

    def _spawn(self, fn) -> None:
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        self._threads.append(t)

    def _bind_server(self) -> bool:
        """Bind + listen with retry: a port still held by a previous run's
        dying watchdog (or an unrelated process) is retried until
        ``timeout`` — the port-collision fallback — then reported through
        ``_fail`` with a clear message instead of an unhandled thread
        OSError. SO_REUSEADDR already covers plain TIME_WAIT; the retry
        covers a LIVE holder that exits shortly."""
        deadline = time.monotonic() + self.timeout
        last_err: OSError | None = None
        while not self._stopping:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            try:
                srv.bind((self.addr, self.port))
                srv.listen(self.world_size)
                self._server = srv
                return True
            except OSError as e:
                srv.close()
                last_err = e
                if time.monotonic() > deadline:
                    break
                time.sleep(0.2)
        if not self._stopping:
            self._fail(
                f"could not bind heartbeat port {self.addr}:{self.port} "
                f"within {self.timeout:.0f}s ({last_err}) — is another "
                f"run's watchdog still holding it? (pass a different "
                f"--heartbeat-port)")
        return False

    def _accept_loop(self) -> None:
        if not self._bind_server():
            return
        next_id = 0
        while not self._stopping and next_id < self.world_size - 1:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return      # server closed by stop()
            next_id += 1
            peer = next_id  # connection order stands in for rank identity
            with self._lock:
                self._last_seen[peer] = time.monotonic()
                self._conns.append(conn)
            self._spawn(lambda c=conn, p=peer: self._reader(c, p))

    def _reader(self, conn: socket.socket, peer: int) -> None:
        try:
            while True:
                data = conn.recv(64)
                if not data:
                    break               # EOF: peer's socket closed
                with self._lock:
                    self._last_seen[peer] = time.monotonic()
                    if _BYE in data:
                        self._said_bye.add(peer)
        except OSError:
            pass
        with self._lock:
            graceful = peer in self._said_bye
        if not graceful:
            self._fail(f"peer {peer} vanished (socket closed without "
                       f"goodbye — killed or crashed)")

    def _staleness_loop(self) -> None:
        deadline_first = None
        while not self._stopping:
            time.sleep(self.interval)
            now = time.monotonic()
            if self._server is None:
                # bind still retrying (_bind_server owns that deadline):
                # clients cannot have connected yet, so the first-connect
                # clock starts only once the server is actually listening
                continue
            if deadline_first is None:
                deadline_first = now + self.timeout
            with self._lock:
                n_connected = len(self._last_seen)
                stale = [p for p, ts in self._last_seen.items()
                         if p not in self._said_bye
                         and now - ts > self.timeout]
            if stale:
                self._fail(f"peer(s) {stale} stopped heartbeating for "
                           f">{self.timeout:.0f}s (frozen or unreachable)")
            if (n_connected < self.world_size - 1
                    and now > deadline_first):
                self._fail(
                    f"only {n_connected}/{self.world_size - 1} peers "
                    f"connected their heartbeat within {self.timeout:.0f}s")

    # -- rank > 0: client side --------------------------------------------

    def _client_loop(self) -> None:
        deadline = time.monotonic() + self.timeout
        sock = None
        while not self._stopping:
            try:
                sock = socket.create_connection((self.addr, self.port),
                                                timeout=self.interval)
                break
            except OSError:
                if time.monotonic() > deadline:
                    self._fail(f"could not reach rank 0's heartbeat port "
                               f"{self.addr}:{self.port} within "
                               f"{self.timeout:.0f}s")
                    return
                time.sleep(0.2)
        if sock is None:
            return
        self._client = sock
        # rank 0 never writes; a recv returning EOF means its socket died.
        # Watch for that in a side thread while the main loop heartbeats.
        self._spawn(lambda: self._watch_master(sock))
        frozen = False
        while not self._stopping:
            # injected frozen-peer: stop heartbeating, keep the socket open
            # (exactly what a GIL-wedged or SIGSTOPped rank looks like from
            # the outside); rank 0's staleness monitor must trip
            if frozen or any(f.kind == "frozen-peer" for f in
                             _check_fault("watchdog.heartbeat",
                                          rank=self.rank)):
                frozen = True
                time.sleep(self.interval)
                continue
            try:
                sock.sendall(_HB)
            except OSError:
                # a send failure AFTER rank 0's goodbye is just the socket
                # draining post-exit — not a peer loss
                if not self._master_bye:
                    self._fail("rank 0 unreachable (heartbeat send failed)")
                return
            time.sleep(self.interval)

    def _watch_master(self, sock: socket.socket) -> None:
        while True:
            try:
                data = sock.recv(64)
            except OSError:
                return
            if _BYE in data:
                self._master_bye = True   # clean exit: sends may now fail
                return
            if not data:
                if not self._stopping:
                    self._fail("rank 0 closed the heartbeat channel "
                               "without goodbye")
                return


class _WatchdogHandle:
    """Parent-side handle for the spawned monitor; ``stop()`` on success,
    ``abort()`` on a crash path that still wants the monitor gone."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self._closing = False
        # visibility thread: a monitor that dies on its own (OOM-kill,
        # operator mistake) leaves this rank unprotected AND its abrupt
        # socket close makes the PEERS read this rank as crashed — log it
        # loudly so the resulting run teardown is attributable. (Best
        # effort: this thread needs the GIL; the monitor exists precisely
        # because the trainer may hold it. The log is diagnosis, not the
        # protection mechanism.)
        t = threading.Thread(target=self._watch_monitor, daemon=True)
        t.start()

    def _watch_monitor(self) -> None:
        while not self._closing:
            if self._proc.poll() is not None:
                if not self._closing:
                    sys.stderr.write(
                        f"[watchdog] monitor subprocess exited unexpectedly "
                        f"(rc={self._proc.returncode}): dead-peer protection "
                        f"is OFF for this rank, and peers may read this "
                        f"rank's heartbeat loss as a crash\n")
                    sys.stderr.flush()
                return
            time.sleep(2.0)

    def stop(self) -> None:
        self._closing = True
        try:
            # the explicit quit byte marks a CLEAN stop; a bare EOF (this
            # process dying with the pipe open) reads as a crash
            self._proc.stdin.write(b"q")
            self._proc.stdin.flush()
            self._proc.stdin.close()
        except (OSError, ValueError):
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()          # reap: no zombie in long-lived hosts

    def abort(self) -> None:
        """Kill the monitor WITHOUT the goodbye protocol: its abrupt socket
        close tells the peers this rank failed (crash semantics preserved),
        and the host process is released from the armed kill_parent."""
        self._closing = True
        try:
            self._proc.kill()
            self._proc.wait()
        except OSError:
            pass


def spawn_watchdog(rank: int, world_size: int, master_addr: str, port: int,
                   interval: float = 1.0, timeout: float = 30.0
                   ) -> _WatchdogHandle:
    """Launch the dead-peer monitor as a GIL-independent subprocess.

    The child runs :class:`HeartbeatWatchdog` with a fail handler that
    SIGTERMs (grace 5 s, then SIGKILLs) this process, so a vanished peer
    turns into a prompt nonzero exit even while the trainer's main thread is
    wedged inside a native collective holding the GIL. The child exits on
    its own when this process dies or closes the handle's stdin pipe.
    """
    env = dict(os.environ)
    # keep the child OUT of the TPU/jax world: the container's sitecustomize
    # registers a PJRT plugin in every python process when these are set
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env.pop("PYTHONPATH", None)
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "simple_distributed_machine_learning_tpu.utils.failure",
         "--rank", str(rank), "--world-size", str(world_size),
         "--addr", master_addr, "--port", str(port),
         "--interval", str(interval), "--timeout", str(timeout),
         "--parent-pid", str(os.getpid())],
        stdin=subprocess.PIPE, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))))
    return _WatchdogHandle(proc)


def _monitor_main(argv=None) -> None:
    """Child-process entry: run the watchdog protocol, kill the parent on
    peer loss, exit quietly when the parent stops or disappears."""
    import argparse
    import signal

    from simple_distributed_machine_learning_tpu.resilience.faults import (
        install_from_env,
    )
    install_from_env()      # SDML_CHAOS reaches the monitor subprocess too

    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--world-size", type=int, required=True)
    ap.add_argument("--addr", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--parent-pid", type=int, required=True)
    args = ap.parse_args(argv)

    # pidfd (Linux): an unforgeable handle to THIS parent — immune to pid
    # recycling between the SIGTERM grace and the SIGKILL
    try:
        parent_fd = os.pidfd_open(args.parent_pid)
    except (AttributeError, OSError):
        parent_fd = None

    def _signal_parent(sig) -> bool:
        try:
            if parent_fd is not None:
                signal.pidfd_send_signal(parent_fd, sig)
            else:
                os.kill(args.parent_pid, sig)
            return True
        except (ProcessLookupError, OSError):
            return False

    def kill_parent(what: str) -> None:
        sys.stderr.write(_abort_message(args.rank, what))
        sys.stderr.flush()
        if _signal_parent(signal.SIGTERM):
            # grace: poll for exit rather than one blind sleep, so SIGKILL
            # is only sent while the (pidfd-pinned) parent still runs
            for _ in range(50):
                time.sleep(0.1)
                if not _parent_alive():
                    break
            else:
                _signal_parent(signal.SIGKILL)
        os._exit(EXIT_PEER_LOST)

    def _parent_alive() -> bool:
        try:
            if parent_fd is not None:
                # a pidfd polls readable once the process exits
                import select as _select
                r, _, _ = _select.select([parent_fd], [], [], 0)
                return not r
            os.kill(args.parent_pid, 0)
            return True
        except (ProcessLookupError, OSError):
            return False

    def _parent_state() -> str:
        """One-char /proc state of the trainer ('T' stopped, 'Z' zombie,
        '?' unknown/non-Linux)."""
        try:
            with open(f"/proc/{args.parent_pid}/stat", "rb") as f:
                # field 3, after the parenthesised comm (which may contain
                # spaces): split on the LAST ')'
                return f.read().rsplit(b")", 1)[1].split()[0].decode()
        except (OSError, IndexError):
            return "?"

    wd = HeartbeatWatchdog(args.rank, args.world_size, args.addr, args.port,
                           interval=args.interval, timeout=args.timeout,
                           fail_handler=kill_parent)
    wd.start()
    # clean-shutdown signal: parent writes 'q' then closes our stdin; a bare
    # EOF or a vanished parent pid means the parent CRASHED — close without
    # goodbye so the peers abort instead of treating it as a clean exit.
    # A trainer stuck in 'T' (SIGSTOPped) or 'Z' for > timeout counts as
    # frozen: this monitor stays healthy and keeps heartbeating on the
    # trainer's behalf, so ONLY this check preserves the frozen-peer
    # abort the in-process design had (a GIL-wedged-but-running trainer is
    # indistinguishable from a long native block and is left to the jax
    # coordination service's own heartbeat).
    import select
    clean = False
    stopped_since = None
    while True:
        r, _, _ = select.select([sys.stdin], [], [], args.interval)
        if r:
            data = os.read(sys.stdin.fileno(), 64)
            if b"q" in data:
                clean = True
            if not data or b"q" in data:
                break
        if not _parent_alive():
            break                       # parent already gone (crash path)
        state = _parent_state()
        if state in ("T", "Z"):
            now = time.monotonic()
            stopped_since = stopped_since or now
            if now - stopped_since > args.timeout:
                sys.stderr.write(_abort_message(
                    args.rank, f"trainer pid {args.parent_pid} has been in "
                               f"state '{state}' for >{args.timeout:.0f}s"))
                sys.stderr.flush()
                _signal_parent(signal.SIGKILL)
                wd.stop(goodbye=False)  # peers must see this as a failure
                os._exit(EXIT_PEER_LOST)
        else:
            stopped_since = None
    wd.stop(goodbye=clean)


if __name__ == "__main__":
    _monitor_main()
