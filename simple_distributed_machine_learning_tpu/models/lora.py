"""Low-rank adapters (LoRA) over the GPT stage weights — multi-tenant
serving's per-tenant model deltas (ISSUE 20).

One *adapter* is a per-layer pair of low-rank factors for each adapted
projection: the served model computes ``q = hn @ wq + (hn @ aq) @ bq``
(same for ``v``) — the base weights are NEVER mutated, and the delta's
rank ``r`` is tiny next to ``d_model``, so hundreds of tenant fine-tunes
share one resident copy of the base model. The adapted projections are
the classic LoRA targets, attention's query and value (``wq``/``wv``);
``B`` initializes to zero so a fresh adapter IS the base model.

Serving applies adapters *merge-free and batched*: the engine stacks the
resident adapters into per-matrix BANKS with a leading adapter-row axis
(:func:`stack_adapters`), and every decode-path program gathers each
slot's A/B rows by a per-slot adapter index — the same discipline as the
per-slot traced sampling params, so ONE compiled program serves any
adapter mix per tick and a hot-swap (bank row rewrite) never retraces.
Row 0 is the all-zero BASE row: a request without an adapter gathers
exact zero deltas, and ``x + 0.0`` keeps its token stream identical to
an engine with no adapter subsystem at all.

The correctness anchor is the MERGED form: :func:`merge_adapter` bakes
``W + A @ B`` densely into a copy of the stage weights, and a solo
engine on those merged weights must emit the tenant's exact token stream
(tests/test_adapters.py pins it across mixed-adapter ticks, hot-swap,
preemption and crash recovery).

:func:`bank_bytes` is the ONE adapter HBM formula — the AdapterStore's
``serve_adapter_resident_bytes`` gauge and the analyzer's
``predict_adapter_bytes`` both call it, which is what makes the
live-gauge parity pin exact by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: the adapted projections (classic LoRA: attention query + value); the
#: bank carries one (A, B) pair per target per layer
LORA_TARGETS = ("wq", "wv")

#: bank keys in gather order: A then B for each target
BANK_KEYS = ("aq", "bq", "av", "bv")


def _check_rank(d_model: int, rank: int) -> None:
    if not 1 <= rank <= d_model:
        raise ValueError(
            f"adapter rank {rank} outside [1, d_model={d_model}] — a rank "
            f"above d_model is no longer LOW-rank (and wastes the bank)")


def init_lora_adapter(key: jax.Array, cfg, rank: int,
                      a_std: float = 0.02) -> dict:
    """One adapter's weights: ``{"aq": [L, d, r], "bq": [L, r, d],
    "av": [L, d, r], "bv": [L, r, d]}`` (f32, L = ``cfg.n_layers``).

    Standard LoRA init: A gaussian (``a_std``), B zero — the fresh
    adapter's delta is exactly 0, i.e. the base model. Train or perturb B
    to make the adapter DO something (the tests use small random B)."""
    _check_rank(cfg.d_model, rank)
    ka, kv = jax.random.split(key)
    L, d = cfg.n_layers, cfg.d_model
    return {
        "aq": a_std * jax.random.normal(ka, (L, d, rank), jnp.float32),
        "bq": jnp.zeros((L, rank, d), jnp.float32),
        "av": a_std * jax.random.normal(kv, (L, d, rank), jnp.float32),
        "bv": jnp.zeros((L, rank, d), jnp.float32),
    }


def zero_adapter(cfg, rank: int) -> dict:
    """The all-zero adapter — bank row 0, the base model's identity
    delta. Kept as a function (not a constant) so shape always matches
    the deployment's (n_layers, d_model, rank)."""
    _check_rank(cfg.d_model, rank)
    L, d = cfg.n_layers, cfg.d_model
    return {"aq": jnp.zeros((L, d, rank), jnp.float32),
            "bq": jnp.zeros((L, rank, d), jnp.float32),
            "av": jnp.zeros((L, d, rank), jnp.float32),
            "bv": jnp.zeros((L, rank, d), jnp.float32)}


def check_adapter_shapes(adapter: dict, cfg, rank: int) -> None:
    """Validate one adapter tree against a deployment's (L, d, r) —
    loud host-side rejection instead of a shape error mid-upload."""
    L, d = cfg.n_layers, cfg.d_model
    want = {"aq": (L, d, rank), "bq": (L, rank, d),
            "av": (L, d, rank), "bv": (L, rank, d)}
    for k in BANK_KEYS:
        if k not in adapter:
            raise ValueError(f"adapter tree missing key {k!r} "
                             f"(want keys {BANK_KEYS})")
        got = tuple(adapter[k].shape)
        if got != want[k]:
            raise ValueError(
                f"adapter[{k!r}] shape {got} != {want[k]} for "
                f"n_layers={L}, d_model={d}, rank={rank}")


def stack_adapters(adapters: list) -> dict:
    """Stack adapter trees into the device BANK the decode programs
    gather from: leaf ``[N, L, ...]`` where row i is ``adapters[i]``.
    Row 0 should be :func:`zero_adapter` (the AdapterStore enforces
    it)."""
    if not adapters:
        raise ValueError("stack_adapters needs at least the base row")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *adapters)


def lora_delta(hn: jax.Array, a: jax.Array, b: jax.Array) -> jax.Array:
    """The merge-free low-rank apply: ``(hn @ a) @ b``.

    ONE expression for both serving shapes — ``jnp.matmul`` broadcasting
    covers the unbatched prefill case (``hn [1, T, d]``, ``a [d, r]``,
    one request's adapter) and the batched decode case (``hn [S, K, d]``,
    ``a [S, d, r]``, each slot's own gathered adapter) — so the prefill
    and tick programs can never drift apart on the delta math."""
    return jnp.matmul(jnp.matmul(hn, a), b)


def merge_adapter(params_list: list, adapter: dict) -> list:
    """The MERGED-DENSE twin: stage param trees with ``W + A @ B`` baked
    into every block's ``wq``/``wv`` — what a dedicated single-tenant
    engine would serve. The bit-exactness anchor: a tenant's token
    stream through the batched adapter path must equal a solo engine on
    these merged weights. Layer index runs GLOBALLY across the stage
    split (block ``li`` pairs with ``adapter[...][li]``), matching the
    bank's layer axis. Non-mutating: returns new trees, shares
    everything but the adapted matrices."""
    li = 0
    out = []
    for p in params_list:
        np_ = dict(p)
        blocks = []
        for bp in p["blocks"]:
            nb = dict(bp)
            attn = dict(bp["attn"])
            attn["wq"] = (attn["wq"]
                          + adapter["aq"][li] @ adapter["bq"][li])
            attn["wv"] = (attn["wv"]
                          + adapter["av"][li] @ adapter["bv"][li])
            nb["attn"] = attn
            blocks.append(nb)
            li += 1
        np_["blocks"] = blocks
        out.append(np_)
    return out


def bank_bytes(n_rows: int, n_layers: int, d_model: int, rank: int) -> int:
    """HBM bytes one resident adapter bank pins: ``n_rows`` adapters x
    ``n_layers`` x (aq + bq + av + bv = 4 * d * r f32 values). The ONE
    formula — the AdapterStore's ``serve_adapter_resident_bytes`` gauge
    and ``analysis/programs.py::predict_adapter_bytes`` both call it, so
    the analyzer-vs-live parity pin is exact by construction."""
    return int(n_rows) * int(n_layers) * 4 * int(d_model) * int(rank) * 4
