"""Tiny GPT as pipeline stages (BASELINE.json config 5).

A decoder-only transformer LM — token+position embeddings, pre-LN blocks
(causal MHA + GELU MLP), final LN + untied head + log_softmax — expressed in
the same :class:`~..parallel.pipeline.Stage` form as MLP/LeNet, so the exact
GPipe/ppermute machinery that runs the reference's conv↔fc split also runs a
transformer with per-token next-token loss.

The reference has no attention or sequence models at all (SURVEY §5.7); this
is pure capability extension mandated by the driver's config 5 ("2-layer
tiny-GPT d=128, 2-stage pipeline with GPipe microbatching").

Wire notes: stage 0 consumes tokens (cast to float on the wire, exact for any
realistic vocab), emits the [T, d] hidden state; the last stage emits [T, V]
log-probs. The engine's per-token loss path (``Pipeline(out_dim=(T, V))``)
averages NLL over batch and sequence.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp

from simple_distributed_machine_learning_tpu.ops.attention import (
    _merge_heads,
    _split_heads,
    causal_attention,
    causal_attention_core,
    mha_init,
)
from simple_distributed_machine_learning_tpu.ops.layers import (
    dropout,
    embedding_init,
    embedding_lookup,
    layer_norm,
    layer_norm_init,
    linear,
    linear_init,
)
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab: int = 128
    seq_len: int = 64
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    mlp_ratio: int = 4
    dropout_rate: float = 0.0   # tiny-GPT default: no dropout
    # attention implementation:
    #   "dense"   — plain causal MHA (single-device math)
    #   "flash"   — Pallas fused kernel (ops/flash_attention.py)
    #   "ring"    — ring attention over the mesh's seq axis: K/V blocks
    #               rotate via ppermute (ops/attention.py); requires n_seq > 1
    #               to actually shard (falls back to dense math at n_seq=1)
    #   "ulysses" — DeepSpeed-Ulysses all-to-all head/sequence re-sharding
    #               (parallel/sequence.py); n_heads must divide by n_seq
    attn_impl: str = "dense"
    # Pallas flash kernel block sizes (attn_impl="flash" only): the tuned
    # values from benchmarks/flash_tune.py go here — bigger block_q cuts K/V
    # HBM passes, bigger block_k cuts grid steps (VMEM bounds both)
    flash_block_q: int = 128
    flash_block_k: int = 128
    # sequence parallelism: n_seq > 1 shards the token axis over the mesh's
    # "seq" axis — stage in_shapes, the wire, and all block compute are then
    # per-shard (seq_len / n_seq tokens); cross-token mixing happens only in
    # the attention collective chosen above.
    n_seq: int = 1
    # MoE: n_experts > 0 replaces each block's MLP with a mixture-of-experts
    # FFN (top-k routed, see parallel/expert.py). The Switch load-balancing
    # aux loss (scaled by moe_aux_weight) is returned alongside the stage
    # output and threaded into the pipeline objective by the engine.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    # expert parallelism: n_expert_parallel > 1 shards each block's expert
    # weights over the mesh's "expert" axis (E / n_ep experts per device) and
    # splits the microbatch's sequences across it — each device routes its
    # own sequences, the 2x all-to-all inside moe_apply_ep ships capacity
    # buffers to the expert owners, and an all_gather reassembles the batch.
    # Routing groups (one sequence each) are identical to the dense path, so
    # EP is numerically exact vs n_expert_parallel=1.
    n_expert_parallel: int = 1
    # tensor (Megatron) parallelism: n_tensor_parallel > 1 shards every
    # block's QKV/O projections (by head) and MLP hidden width over the
    # mesh's "model" axis. Init slices the same dense init, so a TP run
    # matches the dense run to float tolerance. Dense attention + dense MLP
    # blocks only (no MoE/seq-parallel/flash composition).
    n_tensor_parallel: int = 1
    # collective schedule for the TP all-reduces (and the EP dispatch):
    #   "none" — monolithic lax.psum / all_to_all: the chip blocks for the
    #            whole collective after the widest matmuls
    #   "ring" — ppermute-chunked latency-hiding collective matmuls
    #            (parallel/overlap.py): allgather_matmul + reduce-scatter
    #            ring through each block's MLP, chunked-psum ring on the
    #            attention output projection; same losses to float tolerance
    overlap: str = "none"

    def __post_init__(self):
        if self.attn_impl not in ("dense", "flash", "ring", "ulysses"):
            raise ValueError(
                f"attn_impl must be one of dense/flash/ring/ulysses, got "
                f"{self.attn_impl!r}")
        if self.flash_block_q < 1 or self.flash_block_k < 1:
            raise ValueError(
                f"flash blocks must be positive, got "
                f"{self.flash_block_q}/{self.flash_block_k}")
        if self.n_seq < 1 or self.seq_len % self.n_seq:
            raise ValueError(
                f"seq_len {self.seq_len} not divisible by n_seq {self.n_seq}")
        if self.n_seq > 1 and self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"n_seq={self.n_seq} needs a sequence-parallel attention "
                f"(ring or ulysses), got {self.attn_impl!r}")
        if (self.attn_impl == "ulysses" and self.n_seq > 1
                and self.n_heads % self.n_seq):
            raise ValueError(
                f"ulysses needs n_heads ({self.n_heads}) divisible by "
                f"n_seq ({self.n_seq})")
        if self.n_experts < 0 or (self.n_experts > 0 and not
                                  1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"invalid MoE config: n_experts={self.n_experts}, "
                f"top_k={self.moe_top_k}")
        if self.n_expert_parallel < 1 or (
                self.n_expert_parallel > 1
                and (self.n_experts == 0
                     or self.n_experts % self.n_expert_parallel)):
            raise ValueError(
                f"n_expert_parallel={self.n_expert_parallel} needs "
                f"n_experts ({self.n_experts}) > 0 and divisible by it")
        if self.overlap not in ("none", "ring"):
            raise ValueError(
                f"overlap must be 'none' or 'ring', got {self.overlap!r}")
        ntp = self.n_tensor_parallel
        if ntp < 1:
            raise ValueError(f"n_tensor_parallel must be >= 1, got {ntp}")
        if ntp > 1:
            if self.n_heads % ntp:
                raise ValueError(
                    f"n_tensor_parallel={ntp} needs n_heads "
                    f"({self.n_heads}) divisible by it")
            if (self.mlp_ratio * self.d_model) % ntp:
                raise ValueError(
                    f"n_tensor_parallel={ntp} needs the MLP hidden width "
                    f"({self.mlp_ratio * self.d_model}) divisible by it")
            if self.attn_impl != "dense":
                raise ValueError(
                    f"tensor parallelism shards attention by head and "
                    f"computes dense math on the local heads; "
                    f"attn_impl={self.attn_impl!r} is not composable with it")
            if self.n_experts > 0 or self.n_expert_parallel > 1:
                raise ValueError(
                    "a stage cannot be both tensor- and expert-sharded "
                    "(Stage.shards vs expert_shards): use n_tensor_parallel "
                    "with dense-MLP blocks only")
            if self.n_seq > 1:
                raise ValueError(
                    "n_tensor_parallel > 1 with n_seq > 1 is not supported "
                    "(the wire's token sharding and the TP row scatter "
                    "would both claim the token axis)")


def _block_init(key: jax.Array, cfg: GPTConfig) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    d, dh = cfg.d_model, cfg.mlp_ratio * cfg.d_model
    p = {
        "ln1": layer_norm_init(d),
        "attn": mha_init(k1, d, cfg.n_heads),
        "ln2": layer_norm_init(d),
    }
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            moe_init,
        )
        p["moe"] = moe_init(k2, d, dh, cfg.n_experts)
    else:
        p["mlp_in"] = linear_init(k2, d, dh)
        p["mlp_out"] = linear_init(k3, dh, d)
    return p


def _block_apply(params: dict, h: jax.Array, cfg: GPTConfig, key: jax.Array,
                 deterministic: bool) -> tuple[jax.Array, jax.Array]:
    """One transformer block. Returns ``(h, aux)`` — aux is the block's MoE
    load-balancing loss (0 for a dense MLP block)."""
    k1, k2 = jax.random.split(key)
    hn1 = layer_norm(params["ln1"], h)
    if cfg.attn_impl == "flash":
        from simple_distributed_machine_learning_tpu.ops.flash_attention import (
            flash_mha,
        )
        a = flash_mha(params["attn"], hn1, cfg.n_heads,
                      block_q=cfg.flash_block_q, block_k=cfg.flash_block_k)
    elif cfg.attn_impl == "ring" and cfg.n_seq > 1:
        from simple_distributed_machine_learning_tpu.ops.attention import (
            SEQ_AXIS,
            ring_attention,
        )
        a = ring_attention(params["attn"], hn1, cfg.n_heads, axis=SEQ_AXIS)
    elif cfg.attn_impl == "ulysses" and cfg.n_seq > 1:
        from simple_distributed_machine_learning_tpu.parallel.sequence import (
            ulysses_attention,
        )
        a = ulysses_attention(params["attn"], hn1, cfg.n_heads)
    else:
        # dense — also the n_seq == 1 degenerate case of ring/ulysses
        # (identical math on the whole sequence)
        a = causal_attention(params["attn"], hn1, cfg.n_heads)
    a = dropout(k1, a, cfg.dropout_rate, deterministic)
    h = h + a
    hn = layer_norm(params["ln2"], h)
    aux = jnp.float32(0.0)
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            EXPERT_AXIS,
            default_capacity,
            moe_apply,
            moe_apply_ep,
        )
        # route per sequence (vmap over batch): keeps the [T, E, C] dispatch
        # tensors at seq_len scale instead of batch*seq_len (C grows with the
        # routed group size, so global routing would cost O((B*T)^2/E))
        cap = default_capacity(hn.shape[1], cfg.n_experts, cfg.moe_top_k,
                               cfg.moe_capacity_factor)
        if cfg.n_expert_parallel > 1:
            # expert-parallel: each expert-axis device takes its slice of the
            # microbatch's SEQUENCES (routing groups identical to dense),
            # runs the 2x-all-to-all EP FFN on its E/D expert shard, and the
            # all_gather reassembles the batch (replicated again)
            D = cfg.n_expert_parallel
            b = hn.shape[0]
            if b % D:
                raise ValueError(
                    f"microbatch of {b} sequences not divisible by "
                    f"n_expert_parallel={D}")
            nb = b // D
            i = jax.lax.axis_index(EXPERT_AXIS)
            hn_loc = jax.lax.dynamic_slice_in_dim(hn, i * nb, nb, 0)
            m_loc, aux_v = jax.vmap(
                lambda t: moe_apply_ep(params["moe"], t, k=cfg.moe_top_k,
                                       capacity=cap,
                                       overlap=cfg.overlap))(hn_loc)
            aux = jnp.mean(aux_v)   # already pmean'd over the expert axis
            m = jax.lax.all_gather(m_loc, EXPERT_AXIS, axis=0, tiled=True)
        else:
            m, aux_v = jax.vmap(
                lambda t: moe_apply(params["moe"], t, k=cfg.moe_top_k,
                                    capacity=cap))(hn)
            aux = jnp.mean(aux_v)
    else:
        m = linear(params["mlp_out"], jax.nn.gelu(linear(params["mlp_in"], hn)))
    m = dropout(k2, m, cfg.dropout_rate, deterministic)
    return h + m, aux


def _slice_tp_block(bp: dict, m: int, mp: int) -> dict:
    """Model-shard ``m``'s slice of one dense block's params (Megatron):
    QKV columns / O rows by head, MLP hidden width column→row; norms and the
    MLP output bias replicated. Slicing the SAME dense init keeps a TP run
    numerically identical to the dense run (tests/test_overlap.py)."""
    d = bp["attn"]["wq"].shape[0]
    dc = d // mp                      # head-aligned qkv column chunk
    hc = bp["mlp_in"]["w"].shape[1] // mp
    return {
        "ln1": bp["ln1"],
        "attn": {"wq": bp["attn"]["wq"][:, m * dc:(m + 1) * dc],
                 "wk": bp["attn"]["wk"][:, m * dc:(m + 1) * dc],
                 "wv": bp["attn"]["wv"][:, m * dc:(m + 1) * dc],
                 "wo": bp["attn"]["wo"][m * dc:(m + 1) * dc, :]},
        "ln2": bp["ln2"],
        "mlp_in": {"w": bp["mlp_in"]["w"][:, m * hc:(m + 1) * hc],
                   "b": bp["mlp_in"]["b"][m * hc:(m + 1) * hc]},
        "mlp_out": {"w": bp["mlp_out"]["w"][m * hc:(m + 1) * hc, :],
                    "b": bp["mlp_out"]["b"]},
    }


def _slice_tp_stage(params: dict, m: int, mp: int) -> dict:
    """Model-shard ``m``'s stage tree: blocks sliced, embed/head replicated
    (stored per-shard like the MLP TP pair's output bias — grad_sync'd)."""
    out = {"blocks": [_slice_tp_block(bp, m, mp) for bp in params["blocks"]]}
    for k in ("embed", "head"):
        if k in params:
            out[k] = params[k]
    return out


def _is_tp_sharded_leaf(path) -> bool:
    """True for leaves genuinely split across the model axis — their grads
    arrive through the TP collectives' transposes; everything else (norms,
    the MLP output bias, embed, head) is replicated-in-sharded-storage and
    needs grad_sync over the model axis."""
    keys = [getattr(p, "key", None) for p in path]
    if "attn" in keys or "mlp_in" in keys:
        return True
    return "mlp_out" in keys and keys[-1] == "w"


def _grad_sync_non_tp(params: dict, overlap: str) -> dict:
    import jax.tree_util as jtu

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        grad_sync,
    )
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf if _is_tp_sharded_leaf(path)
                            else grad_sync(leaf, MODEL_AXIS, overlap)),
        params)


def _block_apply_tp(params: dict, h: jax.Array, cfg: GPTConfig,
                    key: jax.Array, deterministic: bool) -> jax.Array:
    """One transformer block, tensor-parallel over the model axis — call
    inside ``shard_map``. ``params`` is THIS shard's slice
    (:func:`_slice_tp_block`); ``h`` is replicated and the return is too.

    Attention: QKV project onto the local ``H/mp`` heads (column shards are
    head-aligned), dense causal math runs on them, and the output projection
    is row-parallel — closed by ``lax.psum`` (``overlap='none'``) or the
    chunked-psum ring of :func:`~..parallel.overlap.ring_psum`.

    MLP with ``overlap='ring'`` runs the full scattered collective-matmul
    pair: each device takes its ``1/mp`` row slice of the (replicated)
    tokens, :func:`~..parallel.overlap.allgather_matmul` re-gathers them
    chunk-by-chunk under the column matmul,
    :func:`~..parallel.overlap.matmul_reducescatter` ring-accumulates the
    row matmul's partial products, and a ring all-gather restores
    replication — every hop hidden under a chunk's compute, forward and
    backward (the custom_vjp mirrors). Falls back to the chunked-psum form
    when the token count does not divide by ``mp``. ``overlap='none'`` is
    the monolithic Megatron schedule (one blocking psum).
    """
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        pvary_to,
        vma_of,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.overlap import (
        allgather_matmul,
        matmul_reducescatter,
        ring_all_gather,
        ring_psum,
    )

    mp = cfg.n_tensor_parallel
    ring = cfg.overlap == "ring"
    axis = MODEL_AXIS

    def reduce_full(z):
        # replicated all-reduce of a row-parallel product, typed to match
        # the (varying) residual stream for the vma checker
        red = ring_psum(z, axis) if ring else lax.psum(z, axis)
        return pvary_to(red, tuple(vma_of(h)))

    k1, k2 = jax.random.split(key)
    hn = layer_norm(params["ln1"], h)
    h_loc = cfg.n_heads // mp
    q = _split_heads(hn @ params["attn"]["wq"], h_loc)
    k_ = _split_heads(hn @ params["attn"]["wk"], h_loc)
    v = _split_heads(hn @ params["attn"]["wv"], h_loc)
    a = _merge_heads(causal_attention_core(q, k_, v))      # [B, T, d/mp]
    a = reduce_full(a @ params["attn"]["wo"])
    h = h + dropout(k1, a, cfg.dropout_rate, deterministic)

    hn2 = layer_norm(params["ln2"], h)
    b, t, d = hn2.shape
    rows = hn2.reshape(b * t, d)
    if ring and (b * t) % mp == 0:
        n_loc = (b * t) // mp
        i = lax.axis_index(axis)
        x_shard = lax.dynamic_slice_in_dim(rows, i * n_loc, n_loc, 0)
        mid = jax.nn.gelu(
            allgather_matmul(x_shard, params["mlp_in"]["w"], axis)
            + params["mlp_in"]["b"])
        y_shard = matmul_reducescatter(mid, params["mlp_out"]["w"], axis)
        m = (ring_all_gather(y_shard, axis).reshape(b, t, d)
             + params["mlp_out"]["b"])
        m = pvary_to(m, tuple(vma_of(h)))
    else:
        mid = jax.nn.gelu(rows @ params["mlp_in"]["w"]
                          + params["mlp_in"]["b"])
        m = reduce_full((mid @ params["mlp_out"]["w"]).reshape(b, t, d))
        m = m + params["mlp_out"]["b"]
    return h + dropout(k2, m, cfg.dropout_rate, deterministic)


def make_gpt_stages(key: jax.Array, cfg: GPTConfig = GPTConfig(),
                    n_stages: int = 2) -> tuple[list[Stage], int, tuple[int, int]]:
    """Build the GPT as ``n_stages`` pipeline stages.

    Blocks are split contiguously; stage 0 additionally owns the embeddings,
    the last stage owns the final LN + head. Returns
    ``(stages, wire_dim, (seq_len, vocab))`` — pass the tuple as the
    Pipeline's ``out_dim`` for the per-token loss.

    With ``cfg.n_seq > 1`` the stages are sequence-parallel: in_shapes and
    ``wire_dim`` are per-seq-shard sizes (``seq_len / n_seq`` tokens), the
    embedding stage offsets its positional slice by the shard's global
    position, and attention runs as the configured seq collective. Build the
    Pipeline on a ``make_mesh(..., n_seq=cfg.n_seq)`` mesh; the returned
    out_dim stays GLOBAL — the engine reassembles the token axis.

    With ``cfg.n_tensor_parallel > 1`` the stages are tensor-parallel
    (Megatron): every block's QKV/O projections shard by head and the MLP
    hidden width column→row over the mesh's ``model`` axis
    (``Stage.shards``), with ``cfg.overlap`` choosing the collective
    schedule (monolithic psum vs the latency-hiding ppermute rings of
    ``parallel/overlap.py``). Build on a ``make_mesh(...,
    n_model=cfg.n_tensor_parallel)`` mesh. Single-device decode helpers
    (``generate``/``make_decoder``/``fused_reference``) need an unsharded
    build of the same weights — the same restriction as ``n_seq > 1``.
    """
    if cfg.n_layers < n_stages and not (n_stages == 1 and cfg.n_layers == 0):
        raise ValueError(
            f"{cfg.n_layers} layers cannot fill {n_stages} stages")
    ke, kp, kh, *kb = jax.random.split(key, 3 + cfg.n_layers)
    embed = {"tok": embedding_init(ke, cfg.vocab, cfg.d_model),
             "pos": 0.02 * jax.random.normal(kp, (cfg.seq_len, cfg.d_model))}
    blocks = [_block_init(kb[i], cfg) for i in range(cfg.n_layers)]
    head = {"ln_f": layer_norm_init(cfg.d_model),
            "out": linear_init(kh, cfg.d_model, cfg.vocab)}

    from simple_distributed_machine_learning_tpu.parallel.staging import (
        contiguous_split,
    )
    block_split = (contiguous_split(blocks, n_stages) if blocks
                   else [[] for _ in range(n_stages)])
    t_loc = cfg.seq_len // cfg.n_seq        # tokens per seq shard

    stages: list[Stage] = []
    for s in range(n_stages):
        stage_blocks = block_split[s]
        first, last = s == 0, s == n_stages - 1
        params: dict = {"blocks": stage_blocks}
        if first:
            params["embed"] = embed
        if last:
            params["head"] = head

        def apply(params, x, key, deterministic,
                  _first=first, _last=last, _n=len(stage_blocks)):
            if cfg.n_expert_parallel > 1:
                # this stage's storage row is expert-sharded: expert weights
                # are genuinely per-device, everything else (router, attn,
                # norms, embed/head) is replicated-in-sharded-storage and
                # needs grad_sync over the expert axis to receive its full
                # gradient on every replica
                params = _grad_sync_non_expert(params)
            if cfg.n_tensor_parallel > 1:
                # likewise for a tensor-sharded row: QKV/O and MLP weights
                # are genuinely per-device (their grads arrive through the
                # TP collectives' transposes); norms, the MLP output bias,
                # embed and head are replicated-in-sharded-storage
                params = _grad_sync_non_tp(params, cfg.overlap)
            if _first:
                ids = x.astype(jnp.int32)                     # tokens on the wire
                pos = params["embed"]["pos"]
                if cfg.n_seq > 1:
                    # this shard holds global positions [i*t_loc, (i+1)*t_loc)
                    from simple_distributed_machine_learning_tpu.ops.attention import (
                        SEQ_AXIS,
                    )
                    off = jax.lax.axis_index(SEQ_AXIS) * t_loc
                    pos = jax.lax.dynamic_slice_in_dim(pos, off, t_loc, 0)
                h = embedding_lookup(params["embed"]["tok"], ids) + pos
            else:
                h = x                                         # [B, T_loc, d]
            aux = jnp.float32(0.0)
            for i in range(_n):
                if cfg.n_tensor_parallel > 1:
                    h = _block_apply_tp(params["blocks"][i], h, cfg,
                                        jax.random.fold_in(key, i),
                                        deterministic)
                else:
                    h, a = _block_apply(params["blocks"][i], h, cfg,
                                        jax.random.fold_in(key, i),
                                        deterministic)
                    aux = aux + a
            if _last:
                h = layer_norm(params["head"]["ln_f"], h)
                h = log_softmax(linear(params["head"]["out"], h))
            if cfg.n_experts > 0:
                return h, cfg.moe_aux_weight * aux
            return h

        in_shape = (t_loc,) if first else (t_loc, cfg.d_model)
        if cfg.n_expert_parallel > 1:
            shards = tuple(_slice_expert_shard(params, e, cfg)
                           for e in range(cfg.n_expert_parallel))
            stages.append(Stage(apply=apply, params=shards[0],
                                in_shape=in_shape, expert_shards=shards))
        elif cfg.n_tensor_parallel > 1:
            # slice the SAME dense init per model shard (Megatron layout):
            # the TP pipeline matches the dense build to float tolerance
            shards = tuple(_slice_tp_stage(params, m, cfg.n_tensor_parallel)
                           for m in range(cfg.n_tensor_parallel))
            stages.append(Stage(apply=apply, params=shards[0],
                                in_shape=in_shape, shards=shards))
        else:
            stages.append(Stage(apply=apply, params=params, in_shape=in_shape))

    # the wire carries only INTER-stage activations ([t_loc, d_model] blocks
    # and the stage-0 token ids); the last stage's [t_loc, vocab] log-probs
    # are consumed locally by the engine's loss and never ride the ppermute
    # ring, so vocab never widens the wire
    wire_dim = t_loc * cfg.d_model
    return stages, wire_dim, (cfg.seq_len, cfg.vocab)


def _is_expert_leaf(path) -> bool:
    return any(getattr(p, "key", None) == "experts" for p in path)


def _slice_expert_shard(params: dict, e: int, cfg: GPTConfig) -> dict:
    """Expert-device ``e``'s param tree: blocks' ``experts`` leaves sliced
    ``[e*E/D, (e+1)*E/D)`` on their leading expert axis, all else shared."""
    import jax.tree_util as jtu

    per = cfg.n_experts // cfg.n_expert_parallel
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf[e * per:(e + 1) * per]
                            if _is_expert_leaf(path) else leaf),
        params)


def _grad_sync_non_expert(params: dict) -> dict:
    """grad_sync every leaf EXCEPT the expert weights over the expert axis
    (expert weights are genuinely sharded; their grads arrive through the
    all-to-all transposes)."""
    import jax.tree_util as jtu

    from simple_distributed_machine_learning_tpu.parallel.expert import (
        EXPERT_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        grad_sync,
    )
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf if _is_expert_leaf(path)
                            else grad_sync(leaf, EXPERT_AXIS)),
        params)


def _filter_top(scaled: jax.Array, top_k: int | None,
                top_p: float | None) -> jax.Array:
    """Top-k / nucleus filtering on temperature-scaled log-probs [B, V].

    Masked tokens get -inf (zero probability under categorical). Applied
    after temperature scaling, top-k before top-p — the standard sampling
    pipeline. The top-1 token is always kept (top_p exclusive-cumsum rule),
    so the distribution can never become empty.
    """
    if top_k is not None and top_k > scaled.shape[-1]:
        raise ValueError(
            f"top_k={top_k} exceeds the row width {scaled.shape[-1]} "
            f"(the model's vocab)")
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]       # [B, 1]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None:
        srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)    # descending
        p = jax.nn.softmax(srt, axis=-1)
        exclusive = jnp.cumsum(p, axis=-1) - p
        keep = exclusive < top_p                               # top-1 always
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        scaled = jnp.where(scaled < thresh, -jnp.inf, scaled)
    return scaled


def _dense_qkv(bp, h, n_heads):
    """ln1 + QKV projections of one dense block — the ONE copy shared by the
    cached and pipeline-parallel decoders (prefill and step), so their math
    can never drift apart."""
    hn = layer_norm(bp["ln1"], h)
    return (_split_heads(hn @ bp["attn"]["wq"], n_heads),
            _split_heads(hn @ bp["attn"]["wk"], n_heads),
            _split_heads(hn @ bp["attn"]["wv"], n_heads))


def _dense_attn_tail(bp, h, a):
    """wo merge + residual + ln2 + MLP + residual (the dense block tail)."""
    h = h + _merge_heads(a) @ bp["attn"]["wo"]
    hn2 = layer_norm(bp["ln2"], h)
    return h + linear(bp["mlp_out"], jax.nn.gelu(linear(bp["mlp_in"], hn2)))


def _cache_dtype(cache_dtype):
    """K/V cache storage dtype (None = f32). bf16 HALVES decode memory — the
    cache is the dominant inference allocation at L x B x H x total x dh x 2
    buffers — at ~1e-3 relative logit error (attention math still
    accumulates in f32 via einsum promotion). The one copy of the rule for
    every decoder (cached, beam, pipeline-parallel)."""
    return jnp.float32 if cache_dtype is None else jnp.dtype(cache_dtype)


# Built decode-path programs, keyed by their STATIC config. Every function
# cached here closes over shape scalars only — params (and therefore the
# stages' weights and layer count) arrive as traced ARGUMENTS — so two
# builds with the same key return one shared jitted callable and its
# compiled executables. Build-time validation still runs per call (it
# checks the CALLER's stages); only the trace/compile work is shared.
# This is what keeps a fleet of serving engines (and a test suite full of
# them) from recompiling identical programs per instance.
_DECODE_BUILD_CACHE: dict = {}


def _memo_build(key: tuple, build):
    fn = _DECODE_BUILD_CACHE.get(key)
    if fn is None:
        fn = _DECODE_BUILD_CACHE[key] = build()
    return fn


def _dense_block_prefill(bp, h, li, kc, vc, prompt_len, n_heads):
    """One block over the whole prompt [b, T0, d], recording cache row
    ``li`` for positions [0, prompt_len). K/V are cast to the cache's dtype
    (a bf16 cache halves decode memory; reads promote back in the einsum)."""
    q, k, v = _dense_qkv(bp, h, n_heads)
    kc = kc.at[li, :, :, :prompt_len].set(k.astype(kc.dtype))
    vc = vc.at[li, :, :, :prompt_len].set(v.astype(vc.dtype))
    return _dense_attn_tail(bp, h, causal_attention_core(q, k, v)), kc, vc


def _dense_block_step(bp, h, li, kc, vc, i, total, n_heads):
    """One block on ONE token [b, 1, d] against cache row ``li``; writes K/V
    at position ``i`` (cast to the cache's dtype). Same scale expression as
    causal_attention_core (divide by sqrt(dh)) so prefill and step compile
    to identical math."""
    dh = h.shape[-1] // n_heads
    q, knew, vnew = _dense_qkv(bp, h, n_heads)          # [B,H,1,dh] each
    kc = jax.lax.dynamic_update_slice(kc, knew[None].astype(kc.dtype),
                                      (li, 0, 0, i, 0))
    vc = jax.lax.dynamic_update_slice(vc, vnew[None].astype(vc.dtype),
                                      (li, 0, 0, i, 0))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kc[li]) / math.sqrt(dh)
    live = (jnp.arange(total) <= i)[None, None, None, :]
    scores = jnp.where(live, scores, -jnp.inf)
    a = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(scores, axis=-1), vc[li])
    return _dense_attn_tail(bp, h, a), kc, vc


def _validate_decode_build(stages, cfg, prompt_len, n_new, caller):
    """Shared decoder-build validation (cached + pipeline-parallel): dense
    blocks only, sane lengths, and cfg matching the stages' ACTUAL build
    shapes (a mismatched cfg would otherwise silently clamp pos-table
    slices past the real seq_len instead of raising)."""
    if cfg.n_experts > 0:
        raise ValueError(
            f"{caller} supports dense-MLP blocks only — MoE capacity is a "
            f"full-sequence quantity, so per-token cached routing would "
            f"change overflow behavior; use make_decoder")
    if prompt_len < 1:
        raise ValueError(
            f"{caller} needs a non-empty prompt (t0 >= 1): the first "
            f"decoded token is conditioned on the prompt's last position")
    if n_new < 1:
        raise ValueError(f"{caller} needs n_new >= 1 (there is nothing to "
                         f"cache for a pure-prefill call)")
    total = prompt_len + n_new
    if total > cfg.seq_len:
        raise ValueError(
            f"prompt {prompt_len} + n_new {n_new} exceeds the model's "
            f"sequence length {cfg.seq_len}")
    _check_embed_matches(stages, cfg)
    return total


def _check_embed_matches(stages, cfg: GPTConfig) -> None:
    """The one copy of the cfg-vs-build shape check every decoder-style
    builder runs (cached/beam via :func:`_validate_decode_build`, the
    serving slot ops via :func:`_validate_slot_build`): a mismatched cfg
    would otherwise silently clamp pos-table slices past the real seq_len
    instead of raising."""
    embed = next((s.params.get("embed") for s in stages
                  if isinstance(s.params, dict) and "embed" in s.params),
                 None)
    if embed is None or embed["pos"].shape != (cfg.seq_len, cfg.d_model):
        got = None if embed is None else embed["pos"].shape
        raise ValueError(
            f"cfg (seq_len={cfg.seq_len}, d_model={cfg.d_model}) does not "
            f"match the stages' embedding table {got} — pass the GPTConfig "
            f"the stages were built with")


def _merged_stage_trees(params_list):
    """Re-join per-stage param trees into ``(embed, blocks, head)`` — the
    one copy shared by every single-device decoder (cached, beam)."""
    embed = head = None
    blocks = []
    for p in params_list:
        blocks.extend(p["blocks"])
        embed = p.get("embed", embed)
        head = p.get("head", head)
    return embed, blocks, head


def _head_logprobs(head, h_last):
    """[B, d] final hidden -> [B, V] log-probs (ln_f + untied head)."""
    return log_softmax(linear(head["out"], layer_norm(head["ln_f"], h_last)))


def _sample_from(row, ks, temperature, top_k, top_p):
    """Scale/filter/categorical core on a PRE-SPLIT subkey ``ks`` (argmax
    when temperature == 0) — the ONE copy of the sampling math, shared by
    every decoder (cached, recompute, pipeline-parallel)."""
    if temperature > 0.0:
        return jax.random.categorical(
            ks, _filter_top(row / temperature, top_k, top_p), axis=-1)
    return jnp.argmax(row, axis=-1)


def _sample_row(row, k, temperature, top_k, top_p):
    """One decode step on [B, V] log-probs -> ``(tokens, next_key)``.

    The ONE copy of the split discipline (exactly one split per sampled
    token) over :func:`_sample_from` — the single-device decoders call it,
    which is what keeps their key streams (and therefore their sampled
    tokens) exactly identical; the pipeline decoder performs the same split
    itself (uniformly on every device) and calls :func:`_sample_from`."""
    if temperature > 0.0:
        k, ks = jax.random.split(k)
        return _sample_from(row, ks, temperature, top_k, top_p), k
    return jnp.argmax(row, axis=-1), k


def _filter_top_dyn(scaled: jax.Array, top_k: jax.Array,
                    top_p: jax.Array) -> jax.Array:
    """Traced-argument counterpart of :func:`_filter_top` on ONE row [V] —
    the serving engine's decode tick samples every slot in a single compiled
    program, so each request's top-k/top-p knobs arrive as device scalars.
    ``top_k == 0`` disables top-k; ``top_p > 1`` disables top-p. When a
    filter IS enabled the math mirrors the static version step for step
    (same k-th-largest threshold, same exclusive-cumsum rule, top-k before
    top-p with the second sort on the top-k-filtered row), so a served
    request's filtered distribution matches its solo decode bit for bit."""
    V = scaled.shape[-1]
    srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)        # descending
    kth = jnp.take(srt, jnp.clip(top_k, 1, V) - 1, axis=-1)
    scaled = jnp.where((top_k >= 1) & (scaled < kth), -jnp.inf, scaled)
    srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)        # post-top-k
    p = jax.nn.softmax(srt, axis=-1)
    exclusive = jnp.cumsum(p, axis=-1) - p
    keep = exclusive < top_p                                  # top-1 always
    thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
    return jnp.where((top_p <= 1.0) & (scaled < thresh), -jnp.inf, scaled)


def _sample_dyn(row: jax.Array, key_data: jax.Array, temperature: jax.Array,
                top_k: jax.Array, top_p: jax.Array
                ) -> tuple[jax.Array, jax.Array]:
    """One decode step on ONE row [V] with TRACED sampling params ->
    ``(token, next_key_data)``. Mirrors :func:`_sample_row`'s key-split
    discipline exactly — greedy (``temperature == 0``) consumes no
    randomness, sampling splits once per token — so a served request's key
    stream (and therefore its tokens) match its solo decode bit for bit.
    Keys travel as raw uint32 key data so per-slot selection can use
    ``jnp.where`` (typed key arrays reject it); ``vmap`` over slots is the
    loop semantics, so per-slot draws equal the unbatched calls."""
    k = jax.random.wrap_key_data(key_data)
    nk, ks = jax.random.split(k)
    safe_t = jnp.where(temperature > 0, temperature, jnp.float32(1.0))
    filtered = _filter_top_dyn(row / safe_t, top_k, top_p)
    samp = jax.random.categorical(ks, filtered, axis=-1)
    tok = jnp.where(temperature > 0, samp, jnp.argmax(row, axis=-1))
    kd = jnp.where(temperature > 0, jax.random.key_data(nk), key_data)
    return tok.astype(jnp.int32), kd


def _check_sampling_args(temperature, top_k, top_p, vocab=None):
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError("top_k/top_p filtering needs temperature > 0 "
                         "(greedy decoding ignores the filtered tail)")
    if top_k is not None and (top_k < 1 or
                              (vocab is not None and top_k > vocab)):
        raise ValueError(f"top_k={top_k} out of range [1, vocab={vocab}]")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} out of range (0, 1]")


def generate(stages, prompt: jax.Array, n_new: int,
             key: jax.Array | None = None,
             temperature: float = 0.0,
             cfg: GPTConfig | None = None,
             top_k: int | None = None,
             top_p: float | None = None) -> jax.Array:
    """Autoregressive decoding from the (single-device) stage composition.

    ``prompt``: [B, T0] int tokens; returns [B, T0 + n_new]. The whole decode
    is ONE ``lax.scan`` over a fixed-length token buffer — static shapes, no
    per-step Python dispatch (the TPU-idiomatic decode shape). Each step
    recomputes the full prefix forward; causal masking makes the
    not-yet-written zero padding at positions > current length invisible to
    the prediction read at the current position. Full-prefix recompute is
    O(T²) per sequence — right for reference-scale models; a KV-cache decode
    path is the standard next optimization.

    ``temperature=0`` → greedy argmax; ``> 0`` → softmax sampling with
    ``key`` (required); ``top_k``/``top_p`` filter the sampling
    distribution. One-shot convenience: retraces per call — build the
    decoder once with :func:`make_decoder` / :func:`make_cached_decoder`
    for repeated generation.

    ``cfg``: pass the stages' build config to decode through the O(T)
    KV-cache path (:func:`make_cached_decoder`) instead of the O(T²)
    full-prefix recompute — same tokens, faster; dense-MLP single-device
    builds only (the cached path's restrictions apply).

    The reference has no inference path at all (eval only,
    ``/root/reference/simple_distributed.py:119-132``); this is a capability
    extension.
    """
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    key = key if key is not None else jax.random.key(0)
    if cfg is not None:
        dec = make_cached_decoder(stages, cfg, int(prompt.shape[1]), n_new,
                                  temperature=temperature, top_k=top_k,
                                  top_p=top_p)
    else:
        dec = make_decoder(stages, int(prompt.shape[1]), n_new,
                           temperature=temperature, top_k=top_k, top_p=top_p)
    return dec([s.params for s in stages], prompt, key)


def make_cached_decoder(stages, cfg: GPTConfig, prompt_len: int, n_new: int,
                        temperature: float = 0.0, top_k: int | None = None,
                        top_p: float | None = None, cache_dtype=None):
    """KV-cache decode: ``decode(params, prompt, key) -> [B, prompt_len+n_new]``.

    Same contract as :func:`make_decoder` but O(T) per generated token instead
    of O(T²): a one-shot prefill runs the prompt through every block once,
    recording each layer's K/V projections into static ``[L, B, H, total, dh]``
    cache buffers, and the decode ``lax.scan`` then pushes ONE token per step —
    the new K/V row lands in the cache via ``lax.dynamic_update_slice`` and
    attention is a single [1, total] masked row against the cache. Static
    shapes throughout (the TPU decode idiom: no growing buffers, no retraces).

    For ``attn_impl="dense"`` builds greedy tokens match :func:`make_decoder`
    exactly (same math, different association; see
    tests/test_gpt.py::test_cached_decoder_matches_recompute). The cached path
    always computes DENSE attention math on the weights — an
    ``attn_impl="flash"`` build decodes fine here (flash is the same math),
    but ``make_decoder`` would run the Pallas kernel, whose different
    accumulation order can flip a near-tie argmax; cross-decoder token
    equality is only to float tolerance in that case.

    Single-device dense-MLP composition only: MoE routing capacity is defined
    per full sequence (``default_capacity(T, ...)``), so per-token routing
    would silently change which tokens overflow — decode MoE models with
    :func:`make_decoder`. Sequence-parallel builds (``cfg.n_seq > 1``) use mesh
    collectives in their applies and cannot run here either (same restriction
    as :func:`make_decoder`).

    The reference has no inference path at all (eval only,
    ``/root/reference/simple_distributed.py:119-132``).

    Builds are memoized on their static config (``_DECODE_BUILD_CACHE``):
    the program traces everything model-shaped from ``params``, so two
    calls with the same (cfg, lengths, sampling, cache dtype) share one
    jitted callable — and its compiled executables — even across stages
    builds.
    """
    if cfg.n_seq > 1:
        raise ValueError(
            "cached decode is single-device; rebuild the stages with n_seq=1 "
            "(same weights) as make_decoder requires too")
    _check_sampling_args(temperature, top_k, top_p, cfg.vocab)
    total = _validate_decode_build(stages, cfg, prompt_len, n_new,
                                   "make_cached_decoder")
    H, d = cfg.n_heads, cfg.d_model
    dh = d // H
    cd = _cache_dtype(cache_dtype)
    key_ = ("cached_decoder", cfg, prompt_len, n_new, temperature, top_k,
            top_p, jnp.dtype(cd).name)
    return _memo_build(key_, lambda: _build_cached_decoder(
        total, prompt_len, n_new, H, dh, cd, temperature, top_k, top_p))


def _build_cached_decoder(total, prompt_len, n_new, H, dh, cd,
                          temperature, top_k, top_p):
    from jax import lax

    _merged = _merged_stage_trees
    _head_row = _head_logprobs

    def _pick(row, k):
        return _sample_row(row, k, temperature, top_k, top_p)

    @jax.jit
    def decode(params, prompt, key):
        embed, blocks, head = _merged(params)
        b = prompt.shape[0]
        L = len(blocks)
        kc = jnp.zeros((L, b, H, total, dh), cd)
        vc = jnp.zeros((L, b, H, total, dh), cd)

        # --- prefill: one dense causal pass over the whole prompt, recording
        # every layer's K/V rows for positions [0, prompt_len)
        ids = prompt.astype(jnp.int32)
        h = embedding_lookup(embed["tok"], ids) + embed["pos"][:prompt_len]
        for li, bp in enumerate(blocks):
            h, kc, vc = _dense_block_prefill(bp, h, li, kc, vc, prompt_len, H)
        row = _head_row(head, h[:, -1])
        tok, key = _pick(row, key)          # token for position prompt_len

        # --- decode: one token per step; the input token sits at position i,
        # its K/V row lands at cache index i, and the masked attention row
        # covers positions [0, i]
        def step(carry, i):
            kc, vc, tok, k = carry
            pos = lax.dynamic_slice_in_dim(embed["pos"], i, 1, 0)
            h = embedding_lookup(embed["tok"], tok[:, None]) + pos   # [B,1,d]
            for li, bp in enumerate(blocks):
                h, kc, vc = _dense_block_step(bp, h, li, kc, vc, i, total, H)
            row = _head_row(head, h[:, 0])
            nxt, k = _pick(row, k)
            return (kc, vc, nxt, k), tok

        # steps i = prompt_len .. total-2 each CONSUME the carried token at
        # position i and emit it, producing the next; the final carried token
        # (position total-1) is appended after the scan
        (_, _, last, _), toks = lax.scan(
            step, (kc, vc, tok, key), prompt_len + jnp.arange(n_new - 1))
        out = jnp.concatenate(
            [prompt.astype(jnp.int32),
             jnp.moveaxis(toks, 0, 1),
             last[:, None]], axis=1)
        return out

    return decode


def _validate_slot_build(stages, cfg: GPTConfig, max_len: int,
                         caller: str) -> None:
    """Shared validation for the serving slot ops: single-device dense-MLP
    builds only (the :func:`make_cached_decoder` restrictions — MoE routing
    capacity is a full-sequence quantity; sharded stage trees are per-shard
    slices, not the whole model), and ``max_len`` within the position
    table."""
    if cfg.n_experts > 0:
        raise ValueError(
            f"{caller} supports dense-MLP blocks only — MoE capacity is a "
            f"full-sequence quantity (make_cached_decoder's restriction)")
    if cfg.n_seq > 1:
        raise ValueError(
            f"{caller} is single-device; rebuild the stages with n_seq=1")
    if any(getattr(s, "shards", None) is not None
           or getattr(s, "expert_shards", None) is not None for s in stages):
        raise ValueError(
            f"{caller} needs unsharded stage params — gather tensor/expert "
            f"shards into a dense build first")
    if not 2 <= max_len <= cfg.seq_len:
        raise ValueError(
            f"slot max_len={max_len} outside [2, seq_len={cfg.seq_len}] "
            f"(the position table bounds every slot's sequence budget)")
    _check_embed_matches(stages, cfg)


def make_slot_prefill(stages, cfg: GPTConfig, max_len: int,
                      cache_dtype=None):
    """Serving prefill-into-slot: ``prefill(params, kc, vc, prompt [1, T0],
    slot, key_data, temperature, top_k, top_p) -> (kc, vc, token,
    key_data)``.

    Runs ONE request's prompt through every block (batch 1, exactly the
    solo decoder's prefill shapes and math — shared :func:`_dense_qkv` /
    ``causal_attention_core`` / :func:`_dense_attn_tail`), writes each
    layer's K/V rows into pool row ``slot`` at positions ``[0, T0)``, and
    samples the first output token with the request's own params and key
    stream (:func:`_sample_dyn`'s sentinels: ``top_k=0`` / ``top_p=2.0``
    disable). Retraces per distinct prompt length (the prompt shape is
    static — real serving buckets prompt lengths the same way); the decode
    tick stays one program regardless.

    ``kc``/``vc``: the pool buffers, ``[L, n_slots, H, max_len, dh]`` in
    the :func:`_cache_dtype` storage dtype (bf16 halves pool memory). They
    are DONATED — the engine always threads the returned buffers back into
    the pool, and donation lets XLA update the slot row in place instead of
    copying the whole pool per call.
    """
    _validate_slot_build(stages, cfg, max_len, "make_slot_prefill")
    H = cfg.n_heads

    def build():
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(params, kc, vc, prompt, slot, key_data, temperature,
                    top_k, top_p):
            embed, blocks, head = _merged_stage_trees(params)
            t0 = prompt.shape[1]
            ids = prompt.astype(jnp.int32)
            h = embedding_lookup(embed["tok"], ids) + embed["pos"][:t0]
            for li, bp in enumerate(blocks):
                q, k_, v = _dense_qkv(bp, h, H)           # [1, H, T0, dh]
                kc = jax.lax.dynamic_update_slice(
                    kc, k_.astype(kc.dtype)[None], (li, slot, 0, 0, 0))
                vc = jax.lax.dynamic_update_slice(
                    vc, v.astype(vc.dtype)[None], (li, slot, 0, 0, 0))
                h = _dense_attn_tail(bp, h, causal_attention_core(q, k_, v))
            row = _head_logprobs(head, h[:, -1])[0]       # [V]
            tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
            return kc, vc, tok, kd

        return prefill

    return _memo_build(("slot_prefill", cfg, max_len), build)


def _dense_block_step_slots(bp, h, li, kc, vc, pos, n_heads):
    """One block on one token per SLOT (``h``: [S, 1, d]) against pool
    cache row ``li``; each slot writes its new K/V at its OWN position
    (``pos``: [S]) and attends ``[0, pos]``. Per-slot math is exactly
    :func:`_dense_block_step`'s (same scale expression, same einsums, same
    masked-row softmax), and every slot's output depends only on its own
    cache row — the bit-exactness anchor continuous batching rests on."""
    dh = h.shape[-1] // n_heads
    q, knew, vnew = _dense_qkv(bp, h, n_heads)            # [S, H, 1, dh]

    def upd(cache, new, p):
        return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

    kci = jax.vmap(upd)(kc[li], knew.astype(kc.dtype), pos)
    vci = jax.vmap(upd)(vc[li], vnew.astype(vc.dtype), pos)
    kc = kc.at[li].set(kci)
    vc = vc.at[li].set(vci)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kci) / math.sqrt(dh)
    live = (jnp.arange(kci.shape[-2])[None, None, None, :]
            <= pos[:, None, None, None])
    scores = jnp.where(live, scores, -jnp.inf)
    a = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(scores, axis=-1), vci)
    return _dense_attn_tail(bp, h, a), kc, vc


def make_slot_decode_step(stages, cfg: GPTConfig, max_len: int,
                          cache_dtype=None):
    """Serving decode tick: ``step(params, kc, vc, toks [S], pos [S],
    key_data [S, 2], temps [S], top_ks [S], top_ps [S]) -> (kc, vc,
    next_toks [S], next_key_data [S, 2])``.

    ONE batched token step over ALL ``n_slots`` slots — static shapes, so a
    single compiled program serves every tick regardless of occupancy.
    Each slot consumes its carried token at its own position, lands its K/V
    row via a per-slot scatter, attends its masked cache row, and samples
    with its own params and key stream (``vmap`` of :func:`_sample_dyn` —
    loop semantics, per-slot draws equal the unbatched calls). Inactive
    slots compute garbage that the engine discards host-side; their stale
    cache writes are invisible by construction (see ``serve/slots.py``).
    ``kc``/``vc`` are donated (same contract as :func:`make_slot_prefill`):
    one in-place pool update per tick, not a pool-sized copy per token.
    """
    _validate_slot_build(stages, cfg, max_len, "make_slot_decode_step")
    H = cfg.n_heads

    def build():
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, kc, vc, toks, pos, key_data, temps, top_ks,
                 top_ps):
            embed, blocks, head = _merged_stage_trees(params)
            pe = jnp.take(embed["pos"], pos, axis=0)[:, None]  # [S, 1, d]
            h = embedding_lookup(embed["tok"], toks[:, None]) + pe
            for li, bp in enumerate(blocks):
                h, kc, vc = _dense_block_step_slots(bp, h, li, kc, vc,
                                                    pos, H)
            rows = _head_logprobs(head, h[:, 0])               # [S, V]
            toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                               top_ks, top_ps)
            return kc, vc, toks2, kd2

        return step

    return _memo_build(("slot_decode", cfg, max_len), build)


def _validate_paged_build(stages, cfg: GPTConfig, max_len: int,
                          block_size: int, caller: str) -> None:
    """Paged-op validation: the slot-op restrictions plus a sane block."""
    _validate_slot_build(stages, cfg, max_len, caller)
    if block_size < 1:
        raise ValueError(f"{caller} needs block_size >= 1, got {block_size}")


def _gather_paged_rows(cache_l: jax.Array, table: jax.Array) -> jax.Array:
    """Assemble a sequence's contiguous K or V row from the paged pool.

    ``cache_l``: one layer's blocks ``[n_blocks, H, bs, dh]``; ``table``:
    logical->physical block ids, ``[NB]`` (one sequence) or ``[S, NB]``
    (one per slot). Returns ``[..., H, NB*bs, dh]`` with position ``p``
    of the sequence at flattened row index ``p`` — EXACTLY the dense
    layout's row order, so the attention math downstream is unchanged and
    the trailing garbage rows (trash-block entries past the allocated
    span) are removed by the same position mask that already hides
    not-yet-written dense rows."""
    rows = cache_l[table]                     # [..., NB, H, bs, dh]
    rows = jnp.moveaxis(rows, -4, -3)         # [..., H, NB, bs, dh]
    return rows.reshape(*rows.shape[:-3],
                        rows.shape[-3] * rows.shape[-2], rows.shape[-1])


def make_paged_prefill_chunk(stages, cfg: GPTConfig, max_len: int,
                             block_size: int, cache_dtype=None):
    """Chunked serving prefill into paged blocks: ``chunk(params, kc, vc,
    tokens [1, c], p0, table [NB], key_data, temperature, top_k, top_p) ->
    (kc, vc, token, key_data)``.

    Runs ONE request's prompt positions ``[p0, p0+c)`` through every block
    (batch 1, the solo decoder's math via the shared :func:`_dense_qkv` /
    :func:`_dense_attn_tail`), scattering each position's K/V into its
    physical block (``table[p // bs]``, offset ``p % bs``) and attending
    the gathered block row masked to ``<= position`` — which covers both
    earlier chunks (already in the cache, including SHARED prefix blocks
    another request prefilled) and the chunk's own freshly written rows.
    The engine interleaves these chunks with decode ticks so a long prompt
    never stalls in-flight requests; the last chunk's final position feeds
    the head and samples the request's first token (:func:`_sample_dyn` —
    the engine discards the sampled token and key for non-final chunks, so
    the request's key stream advances exactly once, at the same point as
    its solo decode).

    Retraces per distinct chunk length (like :func:`make_slot_prefill`
    retraces per prompt length). Bit-exactness vs the solo
    ``make_cached_decoder`` holds for f32 caches: the chunk reads earlier
    K/V back out of the cache, so a bf16 cache rounds where the solo
    monolithic prefill attends fresh f32 K/V — the one place the paged
    path's parity is dtype-conditional (the decode tick round-trips the
    cache in BOTH paths, so it is exempt).

    ``kc``/``vc`` (``[L, n_blocks+1, H, block_size, dh]``) are donated —
    the engine always threads the returned buffers back into the pool.
    """
    _validate_paged_build(stages, cfg, max_len, block_size,
                          "make_paged_prefill_chunk")
    H, bs = cfg.n_heads, block_size
    dh = cfg.d_model // H
    return _memo_build(("paged_chunk", cfg, max_len, block_size),
                       lambda: _build_paged_prefill_chunk(H, bs, dh))


def _build_paged_prefill_chunk(H, bs, dh):
    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def chunk(params, kc, vc, tokens, p0, table, key_data, temperature,
              top_k, top_p):
        embed, blocks, head = _merged_stage_trees(params)
        c = tokens.shape[1]
        ids = tokens.astype(jnp.int32)
        pos_emb = jax.lax.dynamic_slice_in_dim(embed["pos"], p0, c, 0)
        h = embedding_lookup(embed["tok"], ids) + pos_emb
        idx = p0 + jnp.arange(c)
        phys = table[idx // bs]                       # [c]
        off = idx % bs
        span = table.shape[0] * bs
        live = (jnp.arange(span)[None, :] <= idx[:, None])[None, None]
        for li, bp in enumerate(blocks):
            q, k_, v = _dense_qkv(bp, h, H)           # [1, H, c, dh]
            kc = kc.at[li, phys, :, off, :].set(
                k_[0].swapaxes(0, 1).astype(kc.dtype))
            vc = vc.at[li, phys, :, off, :].set(
                v[0].swapaxes(0, 1).astype(vc.dtype))
            krow = _gather_paged_rows(kc[li], table)  # [H, span, dh]
            vrow = _gather_paged_rows(vc[li], table)
            scores = jnp.einsum("bhqd,hkd->bhqk", q, krow) / math.sqrt(dh)
            scores = jnp.where(live, scores, -jnp.inf)
            a = jnp.einsum("bhqk,hkd->bhqd",
                           jax.nn.softmax(scores, axis=-1), vrow)
            h = _dense_attn_tail(bp, h, a)
        row = _head_logprobs(head, h[:, -1])[0]       # [V]
        tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
        return kc, vc, tok, kd

    return chunk


def make_paged_decode_step(stages, cfg: GPTConfig, max_len: int,
                           block_size: int, cache_dtype=None):
    """Paged serving decode tick: ``step(params, kc, vc, toks [S], pos [S],
    tables [S, NB], key_data [S, 2], temps [S], top_ks [S], top_ps [S]) ->
    (kc, vc, next_toks [S], next_key_data [S, 2])``.

    The block-gather twin of :func:`make_slot_decode_step`: ONE batched
    token step over all slots, but each slot's K/V row is assembled from
    its block table (:func:`_gather_paged_rows`) instead of a dense pool
    row, and its new K/V lands via a per-slot scatter into physical block
    ``tables[s, pos // bs]`` at offset ``pos % bs``. Values for live
    positions are bit-identical to the dense layout's (same numbers,
    different storage), the mask removes everything else, so the PR-5
    bit-exactness anchor carries over unchanged.

    The dense pool's stale-write safety argument does NOT carry over: a
    non-decoding slot's table entries may alias blocks reused by a live
    request, so the ENGINE routes those slots' tick inputs to the trash
    block (``pos = 0``, all-trash table) — their garbage K/V lands where
    no real table points. ``kc``/``vc`` are donated (one in-place pool
    update per tick).
    """
    _validate_paged_build(stages, cfg, max_len, block_size,
                          "make_paged_decode_step")
    H, bs = cfg.n_heads, block_size
    dh = cfg.d_model // H
    return _memo_build(("paged_decode", cfg, max_len, block_size),
                       lambda: _build_paged_decode_step(H, bs, dh))


def _build_paged_decode_step(H, bs, dh):
    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, kc, vc, toks, pos, tables, key_data, temps, top_ks,
             top_ps):
        embed, blocks, head = _merged_stage_trees(params)
        pe = jnp.take(embed["pos"], pos, axis=0)[:, None]     # [S, 1, d]
        h = embedding_lookup(embed["tok"], toks[:, None]) + pe
        phys = jnp.take_along_axis(tables, (pos // bs)[:, None],
                                   axis=1)[:, 0]              # [S]
        off = pos % bs
        span = tables.shape[1] * bs
        live = (jnp.arange(span)[None, None, None, :]
                <= pos[:, None, None, None])
        for li, bp in enumerate(blocks):
            q, knew, vnew = _dense_qkv(bp, h, H)              # [S, H, 1, dh]
            kc = kc.at[li, phys, :, off, :].set(
                knew[:, :, 0, :].astype(kc.dtype))
            vc = vc.at[li, phys, :, off, :].set(
                vnew[:, :, 0, :].astype(vc.dtype))
            krow = _gather_paged_rows(kc[li], tables)         # [S,H,span,dh]
            vrow = _gather_paged_rows(vc[li], tables)
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, krow) / math.sqrt(dh)
            scores = jnp.where(live, scores, -jnp.inf)
            a = jnp.einsum("bhqk,bhkd->bhqd",
                           jax.nn.softmax(scores, axis=-1), vrow)
            h = _dense_attn_tail(bp, h, a)
        rows = _head_logprobs(head, h[:, 0])                  # [S, V]
        toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                           top_ks, top_ps)
        return kc, vc, toks2, kd2

    return step


def make_paged_block_copy():
    """The copy-on-write device op: ``copy(kc, vc, dst, src) -> (kc, vc)``
    duplicates one physical block's rows across every layer before a
    divergent write. Buffers are donated so XLA updates the pool in place
    instead of materializing a second pool; ``dst``/``src`` are traced
    scalars so one compiled program serves every copy."""
    def build():
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def copy(kc, vc, dst, src):
            ks = jax.lax.dynamic_slice_in_dim(kc, src, 1, 1)
            vs = jax.lax.dynamic_slice_in_dim(vc, src, 1, 1)
            kc = jax.lax.dynamic_update_slice_in_dim(kc, ks, dst, 1)
            vc = jax.lax.dynamic_update_slice_in_dim(vc, vs, dst, 1)
            return kc, vc

        return copy

    return _memo_build(("paged_block_copy",), build)


# The memoized decode-path builders, by name — the single list the
# analyzer's program registry and host-side AST lint key off
# (analysis/programs.py enumerates these as compiled entry points;
# analysis/hostlint.py checks each definition routes through _memo_build
# and that no call site bypasses it).
DECODE_BUILDERS = {
    "make_cached_decoder": make_cached_decoder,
    "make_slot_prefill": make_slot_prefill,
    "make_slot_decode_step": make_slot_decode_step,
    "make_paged_prefill_chunk": make_paged_prefill_chunk,
    "make_paged_decode_step": make_paged_decode_step,
    "make_paged_block_copy": make_paged_block_copy,
}


def decoder_from_pipeline(pipe, cfg: GPTConfig, prompt_len: int, n_new: int,
                          temperature: float = 0.0, top_k: int | None = None,
                          top_p: float | None = None, cache_dtype=None):
    """Cached decode bound to a training :class:`~..parallel.pipeline.Pipeline`:
    returns ``decode(buf, prompt, key)`` taking the LIVE packed param buffer.

    The bridge from training to inference: no manual unpacking, no separate
    weight copy — checkpoint-restore or train, then decode from the same
    buffer. The buffer is gathered to host and re-split into stage trees per
    call (``Pipeline.unpack``), then the single-device KV-cache decoder runs
    on them; for a training run that decodes once per eval epoch this
    host-side gather is noise. Tensor-/expert-sharded stages are rejected
    (their trees are per-shard slices, not the whole model).
    """
    if any(s.shards is not None or s.expert_shards is not None
           for s in pipe.stages):
        raise ValueError(
            "decoder_from_pipeline needs unsharded stage params — gather "
            "tensor/expert shards into a dense build first")
    dec = make_cached_decoder(pipe.stages, cfg, prompt_len, n_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, cache_dtype=cache_dtype)

    def decode(buf, prompt, key):
        return dec(pipe.unpack(buf), prompt, key)

    return decode


def make_decoder(stages, prompt_len: int, n_new: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None):
    """Build the jitted decode fn: ``decode(params, prompt, key) ->
    [B, prompt_len + n_new]`` tokens.

    Like the ``make_train_step`` pattern: build ONCE and reuse across calls
    to amortize the trace/compile (``generate`` is the one-shot convenience
    wrapper and rebuilds per call). Single-device composition only: stages
    from a ``cfg.n_seq > 1`` build use mesh collectives in their applies and
    cannot run here — decode with an ``n_seq=1`` build of the same weights.
    """
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    if prompt_len < 1:
        raise ValueError(
            "generate needs a non-empty prompt (t0 >= 1): the first decoded "
            "token is conditioned on the prompt's last position")
    # vocab-bound validation of top_k happens at trace time in _filter_top
    # against the actual row width — no reach into the param layout here
    _check_sampling_args(temperature, top_k, top_p)
    # the stages are traced at a fixed sequence length (stage 0's in_shape);
    # decode inside that static buffer
    seq_len = int(stages[0].in_shape[0])
    if prompt_len + n_new > seq_len:
        raise ValueError(
            f"prompt {prompt_len} + n_new {n_new} exceeds the model's "
            f"sequence length {seq_len}")
    fused = fused_reference(stages)

    @jax.jit
    def decode(params, prompt, key):
        b = prompt.shape[0]
        buf = jnp.zeros((b, seq_len), jnp.int32)
        buf = lax.dynamic_update_slice_in_dim(
            buf, prompt.astype(jnp.int32), 0, 1)

        def step(carry, i):
            buf, k = carry
            logp = fused(params, buf.astype(jnp.float32), k, True)
            # prediction for position i comes from the read at i-1
            row = lax.dynamic_index_in_dim(logp, i - 1, 1, keepdims=False)
            tok, k = _sample_row(row, k, temperature, top_k, top_p)
            buf = lax.dynamic_update_slice_in_dim(
                buf, tok[:, None].astype(jnp.int32), i, 1)
            return (buf, k), None

        (buf, _), _ = lax.scan(step, (buf, key),
                               prompt_len + jnp.arange(n_new))
        return buf[:, :prompt_len + n_new]

    return decode
