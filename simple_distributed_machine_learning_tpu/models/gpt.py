"""Tiny GPT as pipeline stages (BASELINE.json config 5).

A decoder-only transformer LM — token+position embeddings, pre-LN blocks
(causal MHA + GELU MLP), final LN + untied head + log_softmax — expressed in
the same :class:`~..parallel.pipeline.Stage` form as MLP/LeNet, so the exact
GPipe/ppermute machinery that runs the reference's conv↔fc split also runs a
transformer with per-token next-token loss.

The reference has no attention or sequence models at all (SURVEY §5.7); this
is pure capability extension mandated by the driver's config 5 ("2-layer
tiny-GPT d=128, 2-stage pipeline with GPipe microbatching").

Wire notes: stage 0 consumes tokens (cast to float on the wire, exact for any
realistic vocab), emits the [T, d] hidden state; the last stage emits [T, V]
log-probs. The engine's per-token loss path (``Pipeline(out_dim=(T, V))``)
averages NLL over batch and sequence.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from simple_distributed_machine_learning_tpu.ops.attention import (
    _merge_heads,
    _split_heads,
    causal_attention,
    causal_attention_core,
    mha_init,
)
from simple_distributed_machine_learning_tpu.ops.layers import (
    dropout,
    embedding_init,
    embedding_lookup,
    layer_norm,
    layer_norm_init,
    linear,
    linear_init,
)
from simple_distributed_machine_learning_tpu.models.lora import lora_delta
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab: int = 128
    seq_len: int = 64
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    mlp_ratio: int = 4
    dropout_rate: float = 0.0   # tiny-GPT default: no dropout
    # attention implementation:
    #   "dense"   — plain causal MHA (single-device math)
    #   "flash"   — Pallas fused kernel (ops/flash_attention.py)
    #   "ring"    — ring attention over the mesh's seq axis: K/V blocks
    #               rotate via ppermute (ops/attention.py); requires n_seq > 1
    #               to actually shard (falls back to dense math at n_seq=1)
    #   "ulysses" — DeepSpeed-Ulysses all-to-all head/sequence re-sharding
    #               (parallel/sequence.py); n_heads must divide by n_seq
    attn_impl: str = "dense"
    # Pallas flash kernel block sizes (attn_impl="flash" only): the tuned
    # values from benchmarks/flash_tune.py go here — bigger block_q cuts K/V
    # HBM passes, bigger block_k cuts grid steps (VMEM bounds both)
    flash_block_q: int = 128
    flash_block_k: int = 128
    # sequence parallelism: n_seq > 1 shards the token axis over the mesh's
    # "seq" axis — stage in_shapes, the wire, and all block compute are then
    # per-shard (seq_len / n_seq tokens); cross-token mixing happens only in
    # the attention collective chosen above.
    n_seq: int = 1
    # MoE: n_experts > 0 replaces each block's MLP with a mixture-of-experts
    # FFN (top-k routed, see parallel/expert.py). The Switch load-balancing
    # aux loss (scaled by moe_aux_weight) is returned alongside the stage
    # output and threaded into the pipeline objective by the engine.
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01
    # expert parallelism: n_expert_parallel > 1 shards each block's expert
    # weights over the mesh's "expert" axis (E / n_ep experts per device) and
    # splits the microbatch's sequences across it — each device routes its
    # own sequences, the 2x all-to-all inside moe_apply_ep ships capacity
    # buffers to the expert owners, and an all_gather reassembles the batch.
    # Routing groups (one sequence each) are identical to the dense path, so
    # EP is numerically exact vs n_expert_parallel=1.
    n_expert_parallel: int = 1
    # tensor (Megatron) parallelism: n_tensor_parallel > 1 shards every
    # block's QKV/O projections (by head) and MLP hidden width over the
    # mesh's "model" axis. Init slices the same dense init, so a TP run
    # matches the dense run to float tolerance. Dense attention + dense MLP
    # blocks only (no MoE/seq-parallel/flash composition).
    n_tensor_parallel: int = 1
    # collective schedule for the TP all-reduces (and the EP dispatch):
    #   "none" — monolithic lax.psum / all_to_all: the chip blocks for the
    #            whole collective after the widest matmuls
    #   "ring" — ppermute-chunked latency-hiding collective matmuls
    #            (parallel/overlap.py): allgather_matmul + reduce-scatter
    #            ring through each block's MLP, chunked-psum ring on the
    #            attention output projection; same losses to float tolerance
    overlap: str = "none"

    def __post_init__(self):
        if self.attn_impl not in ("dense", "flash", "ring", "ulysses"):
            raise ValueError(
                f"attn_impl must be one of dense/flash/ring/ulysses, got "
                f"{self.attn_impl!r}")
        if self.flash_block_q < 1 or self.flash_block_k < 1:
            raise ValueError(
                f"flash blocks must be positive, got "
                f"{self.flash_block_q}/{self.flash_block_k}")
        if self.n_seq < 1 or self.seq_len % self.n_seq:
            raise ValueError(
                f"seq_len {self.seq_len} not divisible by n_seq {self.n_seq}")
        if self.n_seq > 1 and self.attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"n_seq={self.n_seq} needs a sequence-parallel attention "
                f"(ring or ulysses), got {self.attn_impl!r}")
        if (self.attn_impl == "ulysses" and self.n_seq > 1
                and self.n_heads % self.n_seq):
            raise ValueError(
                f"ulysses needs n_heads ({self.n_heads}) divisible by "
                f"n_seq ({self.n_seq})")
        if self.n_experts < 0 or (self.n_experts > 0 and not
                                  1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"invalid MoE config: n_experts={self.n_experts}, "
                f"top_k={self.moe_top_k}")
        if self.n_expert_parallel < 1 or (
                self.n_expert_parallel > 1
                and (self.n_experts == 0
                     or self.n_experts % self.n_expert_parallel)):
            raise ValueError(
                f"n_expert_parallel={self.n_expert_parallel} needs "
                f"n_experts ({self.n_experts}) > 0 and divisible by it")
        if self.overlap not in ("none", "ring"):
            raise ValueError(
                f"overlap must be 'none' or 'ring', got {self.overlap!r}")
        ntp = self.n_tensor_parallel
        if ntp < 1:
            raise ValueError(f"n_tensor_parallel must be >= 1, got {ntp}")
        if ntp > 1:
            if self.n_heads % ntp:
                raise ValueError(
                    f"n_tensor_parallel={ntp} needs n_heads "
                    f"({self.n_heads}) divisible by it")
            if (self.mlp_ratio * self.d_model) % ntp:
                raise ValueError(
                    f"n_tensor_parallel={ntp} needs the MLP hidden width "
                    f"({self.mlp_ratio * self.d_model}) divisible by it")
            if self.attn_impl != "dense":
                raise ValueError(
                    f"tensor parallelism shards attention by head and "
                    f"computes dense math on the local heads; "
                    f"attn_impl={self.attn_impl!r} is not composable with it")
            if self.n_experts > 0 or self.n_expert_parallel > 1:
                raise ValueError(
                    "a stage cannot be both tensor- and expert-sharded "
                    "(Stage.shards vs expert_shards): use n_tensor_parallel "
                    "with dense-MLP blocks only")
            if self.n_seq > 1:
                raise ValueError(
                    "n_tensor_parallel > 1 with n_seq > 1 is not supported "
                    "(the wire's token sharding and the TP row scatter "
                    "would both claim the token axis)")


def _block_init(key: jax.Array, cfg: GPTConfig) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    d, dh = cfg.d_model, cfg.mlp_ratio * cfg.d_model
    p = {
        "ln1": layer_norm_init(d),
        "attn": mha_init(k1, d, cfg.n_heads),
        "ln2": layer_norm_init(d),
    }
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            moe_init,
        )
        p["moe"] = moe_init(k2, d, dh, cfg.n_experts)
    else:
        p["mlp_in"] = linear_init(k2, d, dh)
        p["mlp_out"] = linear_init(k3, dh, d)
    return p


def _block_apply(params: dict, h: jax.Array, cfg: GPTConfig, key: jax.Array,
                 deterministic: bool) -> tuple[jax.Array, jax.Array]:
    """One transformer block. Returns ``(h, aux)`` — aux is the block's MoE
    load-balancing loss (0 for a dense MLP block)."""
    k1, k2 = jax.random.split(key)
    hn1 = layer_norm(params["ln1"], h)
    if cfg.attn_impl == "flash":
        from simple_distributed_machine_learning_tpu.ops.flash_attention import (
            flash_mha,
        )
        a = flash_mha(params["attn"], hn1, cfg.n_heads,
                      block_q=cfg.flash_block_q, block_k=cfg.flash_block_k)
    elif cfg.attn_impl == "ring" and cfg.n_seq > 1:
        from simple_distributed_machine_learning_tpu.ops.attention import (
            SEQ_AXIS,
            ring_attention,
        )
        a = ring_attention(params["attn"], hn1, cfg.n_heads, axis=SEQ_AXIS)
    elif cfg.attn_impl == "ulysses" and cfg.n_seq > 1:
        from simple_distributed_machine_learning_tpu.parallel.sequence import (
            ulysses_attention,
        )
        a = ulysses_attention(params["attn"], hn1, cfg.n_heads)
    else:
        # dense — also the n_seq == 1 degenerate case of ring/ulysses
        # (identical math on the whole sequence)
        a = causal_attention(params["attn"], hn1, cfg.n_heads)
    a = dropout(k1, a, cfg.dropout_rate, deterministic)
    h = h + a
    hn = layer_norm(params["ln2"], h)
    aux = jnp.float32(0.0)
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            EXPERT_AXIS,
            default_capacity,
            moe_apply,
            moe_apply_ep,
        )
        # route per sequence (vmap over batch): keeps the [T, E, C] dispatch
        # tensors at seq_len scale instead of batch*seq_len (C grows with the
        # routed group size, so global routing would cost O((B*T)^2/E))
        cap = default_capacity(hn.shape[1], cfg.n_experts, cfg.moe_top_k,
                               cfg.moe_capacity_factor)
        if cfg.n_expert_parallel > 1:
            # expert-parallel: each expert-axis device takes its slice of the
            # microbatch's SEQUENCES (routing groups identical to dense),
            # runs the 2x-all-to-all EP FFN on its E/D expert shard, and the
            # all_gather reassembles the batch (replicated again)
            D = cfg.n_expert_parallel
            b = hn.shape[0]
            if b % D:
                raise ValueError(
                    f"microbatch of {b} sequences not divisible by "
                    f"n_expert_parallel={D}")
            nb = b // D
            i = jax.lax.axis_index(EXPERT_AXIS)
            hn_loc = jax.lax.dynamic_slice_in_dim(hn, i * nb, nb, 0)
            m_loc, aux_v = jax.vmap(
                lambda t: moe_apply_ep(params["moe"], t, k=cfg.moe_top_k,
                                       capacity=cap,
                                       overlap=cfg.overlap))(hn_loc)
            aux = jnp.mean(aux_v)   # already pmean'd over the expert axis
            m = jax.lax.all_gather(m_loc, EXPERT_AXIS, axis=0, tiled=True)
        else:
            m, aux_v = jax.vmap(
                lambda t: moe_apply(params["moe"], t, k=cfg.moe_top_k,
                                    capacity=cap))(hn)
            aux = jnp.mean(aux_v)
    else:
        m = linear(params["mlp_out"], jax.nn.gelu(linear(params["mlp_in"], hn)))
    m = dropout(k2, m, cfg.dropout_rate, deterministic)
    return h + m, aux


def _slice_tp_block(bp: dict, m: int, mp: int) -> dict:
    """Model-shard ``m``'s slice of one dense block's params (Megatron):
    QKV columns / O rows by head, MLP hidden width column→row; norms and the
    MLP output bias replicated. Slicing the SAME dense init keeps a TP run
    numerically identical to the dense run (tests/test_overlap.py)."""
    d = bp["attn"]["wq"].shape[0]
    dc = d // mp                      # head-aligned qkv column chunk
    hc = bp["mlp_in"]["w"].shape[1] // mp
    return {
        "ln1": bp["ln1"],
        "attn": {"wq": bp["attn"]["wq"][:, m * dc:(m + 1) * dc],
                 "wk": bp["attn"]["wk"][:, m * dc:(m + 1) * dc],
                 "wv": bp["attn"]["wv"][:, m * dc:(m + 1) * dc],
                 "wo": bp["attn"]["wo"][m * dc:(m + 1) * dc, :]},
        "ln2": bp["ln2"],
        "mlp_in": {"w": bp["mlp_in"]["w"][:, m * hc:(m + 1) * hc],
                   "b": bp["mlp_in"]["b"][m * hc:(m + 1) * hc]},
        "mlp_out": {"w": bp["mlp_out"]["w"][m * hc:(m + 1) * hc, :],
                    "b": bp["mlp_out"]["b"]},
    }


def _slice_tp_stage(params: dict, m: int, mp: int) -> dict:
    """Model-shard ``m``'s stage tree: blocks sliced, embed/head replicated
    (stored per-shard like the MLP TP pair's output bias — grad_sync'd)."""
    out = {"blocks": [_slice_tp_block(bp, m, mp) for bp in params["blocks"]]}
    for k in ("embed", "head"):
        if k in params:
            out[k] = params[k]
    return out


def _is_tp_sharded_leaf(path) -> bool:
    """True for leaves genuinely split across the model axis — their grads
    arrive through the TP collectives' transposes; everything else (norms,
    the MLP output bias, embed, head) is replicated-in-sharded-storage and
    needs grad_sync over the model axis."""
    keys = [getattr(p, "key", None) for p in path]
    if "attn" in keys or "mlp_in" in keys:
        return True
    return "mlp_out" in keys and keys[-1] == "w"


def _grad_sync_non_tp(params: dict, overlap: str) -> dict:
    import jax.tree_util as jtu

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        grad_sync,
    )
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf if _is_tp_sharded_leaf(path)
                            else grad_sync(leaf, MODEL_AXIS, overlap)),
        params)


def _block_apply_tp(params: dict, h: jax.Array, cfg: GPTConfig,
                    key: jax.Array, deterministic: bool) -> jax.Array:
    """One transformer block, tensor-parallel over the model axis — call
    inside ``shard_map``. ``params`` is THIS shard's slice
    (:func:`_slice_tp_block`); ``h`` is replicated and the return is too.

    Attention: QKV project onto the local ``H/mp`` heads (column shards are
    head-aligned), dense causal math runs on them, and the output projection
    is row-parallel — closed by ``lax.psum`` (``overlap='none'``) or the
    chunked-psum ring of :func:`~..parallel.overlap.ring_psum`.

    MLP with ``overlap='ring'`` runs the full scattered collective-matmul
    pair: each device takes its ``1/mp`` row slice of the (replicated)
    tokens, :func:`~..parallel.overlap.allgather_matmul` re-gathers them
    chunk-by-chunk under the column matmul,
    :func:`~..parallel.overlap.matmul_reducescatter` ring-accumulates the
    row matmul's partial products, and a ring all-gather restores
    replication — every hop hidden under a chunk's compute, forward and
    backward (the custom_vjp mirrors). Falls back to the chunked-psum form
    when the token count does not divide by ``mp``. ``overlap='none'`` is
    the monolithic Megatron schedule (one blocking psum).
    """
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        pvary_to,
        vma_of,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.overlap import (
        allgather_matmul,
        matmul_reducescatter,
        ring_all_gather,
        ring_psum,
    )

    mp = cfg.n_tensor_parallel
    ring = cfg.overlap == "ring"
    axis = MODEL_AXIS

    def reduce_full(z):
        # replicated all-reduce of a row-parallel product, typed to match
        # the (varying) residual stream for the vma checker
        red = ring_psum(z, axis) if ring else lax.psum(z, axis)
        return pvary_to(red, tuple(vma_of(h)))

    k1, k2 = jax.random.split(key)
    hn = layer_norm(params["ln1"], h)
    h_loc = cfg.n_heads // mp
    q = _split_heads(hn @ params["attn"]["wq"], h_loc)
    k_ = _split_heads(hn @ params["attn"]["wk"], h_loc)
    v = _split_heads(hn @ params["attn"]["wv"], h_loc)
    a = _merge_heads(causal_attention_core(q, k_, v))      # [B, T, d/mp]
    a = reduce_full(a @ params["attn"]["wo"])
    h = h + dropout(k1, a, cfg.dropout_rate, deterministic)

    hn2 = layer_norm(params["ln2"], h)
    b, t, d = hn2.shape
    rows = hn2.reshape(b * t, d)
    if ring and (b * t) % mp == 0:
        n_loc = (b * t) // mp
        i = lax.axis_index(axis)
        x_shard = lax.dynamic_slice_in_dim(rows, i * n_loc, n_loc, 0)
        mid = jax.nn.gelu(
            allgather_matmul(x_shard, params["mlp_in"]["w"], axis)
            + params["mlp_in"]["b"])
        y_shard = matmul_reducescatter(mid, params["mlp_out"]["w"], axis)
        m = (ring_all_gather(y_shard, axis).reshape(b, t, d)
             + params["mlp_out"]["b"])
        m = pvary_to(m, tuple(vma_of(h)))
    else:
        mid = jax.nn.gelu(rows @ params["mlp_in"]["w"]
                          + params["mlp_in"]["b"])
        m = reduce_full((mid @ params["mlp_out"]["w"]).reshape(b, t, d))
        m = m + params["mlp_out"]["b"]
    return h + dropout(k2, m, cfg.dropout_rate, deterministic)


def make_gpt_stages(key: jax.Array, cfg: GPTConfig = GPTConfig(),
                    n_stages: int = 2) -> tuple[list[Stage], int, tuple[int, int]]:
    """Build the GPT as ``n_stages`` pipeline stages.

    Blocks are split contiguously; stage 0 additionally owns the embeddings,
    the last stage owns the final LN + head. Returns
    ``(stages, wire_dim, (seq_len, vocab))`` — pass the tuple as the
    Pipeline's ``out_dim`` for the per-token loss.

    With ``cfg.n_seq > 1`` the stages are sequence-parallel: in_shapes and
    ``wire_dim`` are per-seq-shard sizes (``seq_len / n_seq`` tokens), the
    embedding stage offsets its positional slice by the shard's global
    position, and attention runs as the configured seq collective. Build the
    Pipeline on a ``make_mesh(..., n_seq=cfg.n_seq)`` mesh; the returned
    out_dim stays GLOBAL — the engine reassembles the token axis.

    With ``cfg.n_tensor_parallel > 1`` the stages are tensor-parallel
    (Megatron): every block's QKV/O projections shard by head and the MLP
    hidden width column→row over the mesh's ``model`` axis
    (``Stage.shards``), with ``cfg.overlap`` choosing the collective
    schedule (monolithic psum vs the latency-hiding ppermute rings of
    ``parallel/overlap.py``). Build on a ``make_mesh(...,
    n_model=cfg.n_tensor_parallel)`` mesh. Single-device decode helpers
    (``generate``/``make_decoder``/``fused_reference``) need an unsharded
    build of the same weights — the same restriction as ``n_seq > 1``.
    """
    if cfg.n_layers < n_stages and not (n_stages == 1 and cfg.n_layers == 0):
        raise ValueError(
            f"{cfg.n_layers} layers cannot fill {n_stages} stages")
    ke, kp, kh, *kb = jax.random.split(key, 3 + cfg.n_layers)
    embed = {"tok": embedding_init(ke, cfg.vocab, cfg.d_model),
             "pos": 0.02 * jax.random.normal(kp, (cfg.seq_len, cfg.d_model))}
    blocks = [_block_init(kb[i], cfg) for i in range(cfg.n_layers)]
    head = {"ln_f": layer_norm_init(cfg.d_model),
            "out": linear_init(kh, cfg.d_model, cfg.vocab)}

    from simple_distributed_machine_learning_tpu.parallel.staging import (
        contiguous_split,
    )
    block_split = (contiguous_split(blocks, n_stages) if blocks
                   else [[] for _ in range(n_stages)])
    t_loc = cfg.seq_len // cfg.n_seq        # tokens per seq shard

    stages: list[Stage] = []
    for s in range(n_stages):
        stage_blocks = block_split[s]
        first, last = s == 0, s == n_stages - 1
        params: dict = {"blocks": stage_blocks}
        if first:
            params["embed"] = embed
        if last:
            params["head"] = head

        def apply(params, x, key, deterministic,
                  _first=first, _last=last, _n=len(stage_blocks)):
            if cfg.n_expert_parallel > 1:
                # this stage's storage row is expert-sharded: expert weights
                # are genuinely per-device, everything else (router, attn,
                # norms, embed/head) is replicated-in-sharded-storage and
                # needs grad_sync over the expert axis to receive its full
                # gradient on every replica
                params = _grad_sync_non_expert(params)
            if cfg.n_tensor_parallel > 1:
                # likewise for a tensor-sharded row: QKV/O and MLP weights
                # are genuinely per-device (their grads arrive through the
                # TP collectives' transposes); norms, the MLP output bias,
                # embed and head are replicated-in-sharded-storage
                params = _grad_sync_non_tp(params, cfg.overlap)
            if _first:
                ids = x.astype(jnp.int32)                     # tokens on the wire
                pos = params["embed"]["pos"]
                if cfg.n_seq > 1:
                    # this shard holds global positions [i*t_loc, (i+1)*t_loc)
                    from simple_distributed_machine_learning_tpu.ops.attention import (
                        SEQ_AXIS,
                    )
                    off = jax.lax.axis_index(SEQ_AXIS) * t_loc
                    pos = jax.lax.dynamic_slice_in_dim(pos, off, t_loc, 0)
                h = embedding_lookup(params["embed"]["tok"], ids) + pos
            else:
                h = x                                         # [B, T_loc, d]
            aux = jnp.float32(0.0)
            for i in range(_n):
                if cfg.n_tensor_parallel > 1:
                    h = _block_apply_tp(params["blocks"][i], h, cfg,
                                        jax.random.fold_in(key, i),
                                        deterministic)
                else:
                    h, a = _block_apply(params["blocks"][i], h, cfg,
                                        jax.random.fold_in(key, i),
                                        deterministic)
                    aux = aux + a
            if _last:
                h = layer_norm(params["head"]["ln_f"], h)
                h = log_softmax(linear(params["head"]["out"], h))
            if cfg.n_experts > 0:
                return h, cfg.moe_aux_weight * aux
            return h

        in_shape = (t_loc,) if first else (t_loc, cfg.d_model)
        if cfg.n_expert_parallel > 1:
            shards = tuple(_slice_expert_shard(params, e, cfg)
                           for e in range(cfg.n_expert_parallel))
            stages.append(Stage(apply=apply, params=shards[0],
                                in_shape=in_shape, expert_shards=shards))
        elif cfg.n_tensor_parallel > 1:
            # slice the SAME dense init per model shard (Megatron layout):
            # the TP pipeline matches the dense build to float tolerance
            shards = tuple(_slice_tp_stage(params, m, cfg.n_tensor_parallel)
                           for m in range(cfg.n_tensor_parallel))
            stages.append(Stage(apply=apply, params=shards[0],
                                in_shape=in_shape, shards=shards))
        else:
            stages.append(Stage(apply=apply, params=params, in_shape=in_shape))

    # the wire carries only INTER-stage activations ([t_loc, d_model] blocks
    # and the stage-0 token ids); the last stage's [t_loc, vocab] log-probs
    # are consumed locally by the engine's loss and never ride the ppermute
    # ring, so vocab never widens the wire
    wire_dim = t_loc * cfg.d_model
    return stages, wire_dim, (cfg.seq_len, cfg.vocab)


def _is_expert_leaf(path) -> bool:
    return any(getattr(p, "key", None) == "experts" for p in path)


def _slice_expert_shard(params: dict, e: int, cfg: GPTConfig) -> dict:
    """Expert-device ``e``'s param tree: blocks' ``experts`` leaves sliced
    ``[e*E/D, (e+1)*E/D)`` on their leading expert axis, all else shared."""
    import jax.tree_util as jtu

    per = cfg.n_experts // cfg.n_expert_parallel
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf[e * per:(e + 1) * per]
                            if _is_expert_leaf(path) else leaf),
        params)


def _grad_sync_non_expert(params: dict) -> dict:
    """grad_sync every leaf EXCEPT the expert weights over the expert axis
    (expert weights are genuinely sharded; their grads arrive through the
    all-to-all transposes)."""
    import jax.tree_util as jtu

    from simple_distributed_machine_learning_tpu.parallel.expert import (
        EXPERT_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        grad_sync,
    )
    return jtu.tree_map_with_path(
        lambda path, leaf: (leaf if _is_expert_leaf(path)
                            else grad_sync(leaf, EXPERT_AXIS)),
        params)


def _filter_top(scaled: jax.Array, top_k: int | None,
                top_p: float | None) -> jax.Array:
    """Top-k / nucleus filtering on temperature-scaled log-probs [B, V].

    Masked tokens get -inf (zero probability under categorical). Applied
    after temperature scaling, top-k before top-p — the standard sampling
    pipeline. The top-1 token is always kept (top_p exclusive-cumsum rule),
    so the distribution can never become empty.
    """
    if top_k is not None and top_k > scaled.shape[-1]:
        raise ValueError(
            f"top_k={top_k} exceeds the row width {scaled.shape[-1]} "
            f"(the model's vocab)")
    if top_k is not None:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]       # [B, 1]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    if top_p is not None:
        srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)    # descending
        p = jax.nn.softmax(srt, axis=-1)
        exclusive = jnp.cumsum(p, axis=-1) - p
        keep = exclusive < top_p                               # top-1 always
        thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                         keepdims=True)
        scaled = jnp.where(scaled < thresh, -jnp.inf, scaled)
    return scaled


def _dense_qkv(bp, h, n_heads, ab=None):
    """ln1 + QKV projections of one dense block — the ONE copy shared by the
    cached and pipeline-parallel decoders (prefill and step), so their math
    can never drift apart.

    ``ab`` (optional): this layer's LoRA factors ``(aq, bq, av, bv)`` — the
    multi-tenant serving path's merge-free per-request delta,
    ``q += (hn @ aq) @ bq`` (same for v, k unadapted; classic LoRA
    targets). Factors are unbatched ``[d, r]`` in prefill (one request) or
    leading-``[S]``-batched in the ticks (each slot's own gathered
    adapter) — :func:`~.lora.lora_delta`'s matmul broadcasting covers
    both. The all-zero base row contributes an exact 0 delta, so base
    requests keep the adapter-free token stream."""
    hn = layer_norm(bp["ln1"], h)
    q = hn @ bp["attn"]["wq"]
    v = hn @ bp["attn"]["wv"]
    if ab is not None:
        aq, bq, av, bv = ab
        q = q + lora_delta(hn, aq, bq)
        v = v + lora_delta(hn, av, bv)
    return (_split_heads(q, n_heads),
            _split_heads(hn @ bp["attn"]["wk"], n_heads),
            _split_heads(v, n_heads))


def _adapter_layers(bank, aid):
    """Per-request adapter slices for the decode-path programs: gather
    row(s) ``aid`` (a traced scalar for one-request prefill, ``[S]`` for
    the batched ticks) from the stacked bank
    (``{"aq": [N, L, d, r], "bq": [N, L, r, d], "av": ..., "bv": ...}``)
    and return a per-layer lookup ``at(li) -> (aq, bq, av, bv)`` feeding
    :func:`_dense_qkv`. The gather is data — one compiled program serves
    any adapter mix per tick, and a bank-row hot-swap never retraces."""
    sel = {k: bank[k][aid] for k in ("aq", "bq", "av", "bv")}

    def at(li):
        return tuple(sel[k][..., li, :, :]
                     for k in ("aq", "bq", "av", "bv"))

    return at


def _dense_attn_tail(bp, h, a):
    """wo merge + residual + ln2 + MLP + residual (the dense block tail)."""
    h = h + _merge_heads(a) @ bp["attn"]["wo"]
    hn2 = layer_norm(bp["ln2"], h)
    return h + linear(bp["mlp_out"], jax.nn.gelu(linear(bp["mlp_in"], hn2)))


def _cache_dtype(cache_dtype):
    """K/V cache storage dtype (None = f32). bf16 HALVES decode memory — the
    cache is the dominant inference allocation at L x B x H x total x dh x 2
    buffers — at ~1e-3 relative logit error (attention math still
    accumulates in f32 via einsum promotion). The one copy of the rule for
    every decoder (cached, beam, pipeline-parallel).

    QUANTIZED storage (``int8``, and the fp8 formats where the jnp build
    has them) quarters/halves-again the paged pool's block bytes: blocks
    store narrow-dtype rows plus one f32 scale per (position, head) row —
    a :class:`QuantKV` pytree instead of a bare array — with quantize
    fused into every scatter and dequantize into every gather/kernel
    (:func:`_quantize_rows` / :func:`_paged_gather`). Quantization is a
    PAGED-pool feature: dense slot pools and the solo cached decoder are
    the parity anchors and reject it (:func:`_check_cache_quantization`)."""
    return jnp.float32 if cache_dtype is None else jnp.dtype(cache_dtype)


# fp8 availability is build-dependent on the 0.4.x line; int8 always exists
_QUANT_QMAX = {"int8": 127.0}
for _fp8_name, _fp8_qmax in (("float8_e4m3fn", 448.0),
                             ("float8_e5m2", 57344.0)):
    if hasattr(jnp, _fp8_name):
        _QUANT_QMAX[_fp8_name] = _fp8_qmax


def _is_quantized_dtype(cache_dtype) -> bool:
    """Whether ``cache_dtype`` selects the quantized (data + scales) K/V
    block format — the one predicate pool construction, byte accounting
    and program tracing all branch on."""
    return (cache_dtype is not None
            and jnp.dtype(cache_dtype).name in _QUANT_QMAX)


class QuantKV(NamedTuple):
    """One quantized K or V pool buffer: narrow-dtype block ``data``
    (``[L, n_blocks+1, H, bs, dh]``) plus the per-row f32 dequant
    ``scale`` plane (``[L, n_blocks+1, H, bs]`` — one scale per written
    position per head, so incremental decode writes never re-quantize a
    block's existing rows). A NamedTuple so jax treats the pair as ONE
    pytree buffer: jit donation, device_put sharding and tree_map'd block
    copies all flow through unchanged engine/pool code."""
    data: jax.Array
    scale: jax.Array

    @property
    def dtype(self):
        """The storage dtype — what ``engine_spec``/``ServeSpec`` record
        as the deployment's cache_dtype."""
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.scale.nbytes

    @property
    def shape(self):
        return self.data.shape


def _quantize_rows(rows: jax.Array, dtype) -> tuple[jax.Array, jax.Array]:
    """Quantize K/V rows ``[..., dh]`` to ``dtype`` with one f32 scale per
    row: ``scale = amax(|row|) / qmax`` (floored so all-zero rows stay
    finite), data = ``round(row / scale)`` for int8, the plain cast for
    fp8 (whose format rounds itself). Dequantization is exactly
    ``data * scale`` — the round trip's relative error is bounded by
    ~``1/(2*qmax)`` per element (tests/test_paged_attention.py pins it)."""
    dtype = jnp.dtype(dtype)
    qmax = _QUANT_QMAX[dtype.name]
    rows = rows.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(rows), axis=-1) / qmax, 1e-8)
    q = rows / scale[..., None]
    if dtype.name == "int8":
        q = jnp.clip(jnp.round(q), -127.0, 127.0)
    return q.astype(dtype), scale.astype(jnp.float32)


def _check_cache_quantization(cache_dtype, caller: str,
                              paged: bool) -> None:
    """Quantized caches are paged-pool-only (the dense layouts are the
    bit-exactness anchors the quantized pool's pinned tolerance is judged
    against); unknown narrow dtypes fail loudly here instead of as a
    shape error mid-trace."""
    if cache_dtype is None:
        return
    name = jnp.dtype(cache_dtype).name
    if name in ("float8_e4m3fn", "float8_e5m2") and name not in _QUANT_QMAX:
        raise ValueError(
            f"{caller}: cache_dtype={name} is not available in this jnp "
            f"build — use int8 (always available) or a wider dtype")
    if _is_quantized_dtype(cache_dtype) and not paged:
        raise ValueError(
            f"{caller}: quantized cache_dtype={name} is a paged-pool "
            f"feature (per-block scales live beside physical blocks); "
            f"dense slot layouts are the parity anchors — use f32/bf16")


# -- tensor-parallel serving ------------------------------------------------
#
# The serving builders below accept a GPTConfig with n_tensor_parallel > 1:
# the same program math then runs inside shard_map over the mesh's "model"
# axis with the training path's Megatron layout — QKV/O head-sharded
# (_slice_tp_block slices the SAME dense weights, so a TP engine serves the
# identical model the dense build trains and solo-decodes), the MLP as the
# column→row collective pair of tensor.tp_pair_apply (overlap='ring'|'none'
# knob included), and the K/V pool sharded over its HEAD axis so per-chip
# cache bytes drop by tp. Stages stay the UNSHARDED dense build — the
# serving layer slices per shard itself (pack_tp_serve_params), which keeps
# checkpoint restore and the solo-decode parity anchor on one weight set.


def pack_tp_serve_params(params_list, tp: int):
    """Slice dense per-stage trees into the TP serving layout:
    ``([stacked per-layer block trees], {"embed": ..., "head": ...})`` —
    leaf i of a stacked block tree is shard i's Megatron slice (leading
    axis ``tp``, placed ``P('model')`` by the engine); embed and head are
    replicated. The slices are exactly :func:`_slice_tp_block`'s, so a TP
    engine serves the identical model."""
    embed, blocks, head = _merged_stage_trees(params_list)
    stacked = [jax.tree.map(lambda *ls: jnp.stack(ls),
                            *[_slice_tp_block(bp, m, tp) for m in range(tp)])
               for bp in blocks]
    return stacked, {"embed": embed, "head": head}


def _tp_local_trees(params):
    """Inside the serving shard_map: this shard's block slices (the stacked
    leading axis arrives split to size 1 by the ``P('model')`` in_spec) and
    the replicated embed/head."""
    stacked, rep = params
    blocks = [jax.tree.map(lambda leaf: leaf[0], bp) for bp in stacked]
    return blocks, rep["embed"], rep["head"]


def _tp_attn_tail(bp, h, a, overlap="none"):
    """TP twin of :func:`_dense_attn_tail` — call inside ``shard_map`` with
    shard-sliced block params (``a`` holds the local ``H/tp`` heads). The
    attention output projection is row-parallel (``wo`` rows are
    head-aligned), closed by one ``lax.psum`` (``overlap='none'``) or the
    chunked-psum ring of ``overlap.ring_psum``; the MLP is the training
    path's column→row collective pair (``tensor.tp_pair_apply``, gelu).
    Same numbers as the dense tail up to the all-reduce's summation split
    (token-level parity is pinned in tests/test_serve.py)."""
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        pvary_to,
        vma_of,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        tp_pair_apply,
    )

    z = _merge_heads(a) @ bp["attn"]["wo"]
    if overlap == "ring":
        from simple_distributed_machine_learning_tpu.parallel.overlap import (
            ring_psum,
        )
        red = ring_psum(z, MODEL_AXIS)
    else:
        red = lax.psum(z, MODEL_AXIS)
    h = pvary_to(h, tuple(vma_of(red))) + red
    hn2 = layer_norm(bp["ln2"], h)
    return h + tp_pair_apply({"w1": bp["mlp_in"], "w2": bp["mlp_out"]}, hn2,
                             activation=jax.nn.gelu, overlap=overlap)


def _close_rows(rows):
    """Re-replicate the sampling rows across the model axis before any
    token is drawn. With ``overlap='none'`` the replicas are already
    bit-identical (psum is symmetric) and the pmean is the exact identity
    for power-of-two tp (``(x * tp) / tp`` is exact in binary floating
    point); with the ring schedule each shard's accumulation ORDER differs
    by a ulp, and sampling on per-shard rows could argmax-diverge — the
    pmean makes every shard sample the same row bits."""
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    return lax.pmean(rows, MODEL_AXIS)


def _tp_adapter_layers(bank, aid, tp):
    """TP twin of :func:`_adapter_layers` — call inside ``shard_map``. The
    bank arrives replicated (it is tiny next to the weights); each shard
    slices its LOCAL output columns of the B factors — ``bq``/``bv``
    columns are head-aligned exactly like ``wq``/``wv``'s Megatron column
    shards, and column slicing commutes with the matmul — so the local
    delta lands on the same columns the local base projection produces,
    bit-identically to the dense build's slice."""
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    at_full = _adapter_layers(bank, aid)
    m = lax.axis_index(MODEL_AXIS)

    def at(li):
        aq, bq, av, bv = at_full(li)
        dc = bq.shape[-1] // tp
        bq = lax.dynamic_slice_in_dim(bq, m * dc, dc, bq.ndim - 1)
        bv = lax.dynamic_slice_in_dim(bv, m * dc, dc, bv.ndim - 1)
        return aq, bq, av, bv

    return at


def _tp_jit(body, mesh, n_buf_in, n_rest_in, n_buf_out, n_rest_out,
            donate=(1, 2)):
    """``jit(shard_map(body))`` with the serving specs: params as the
    ``(stacked blocks, replicated embed/head)`` pair, ``n_buf_in`` K/V pool
    buffers sharded on their HEAD axis (dim 2 in both layouts), everything
    else replicated. The pool buffers are donated exactly as in the
    single-device builders."""
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map as _shard_map,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    cache = P(None, None, MODEL_AXIS)
    in_specs = (((P(MODEL_AXIS), P()),) + (cache,) * n_buf_in
                + (P(),) * n_rest_in)
    out_specs = (cache,) * n_buf_out + (P(),) * n_rest_out
    fn = _shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return functools.partial(jax.jit, donate_argnums=donate)(fn)


def _validate_tp_serve(cfg: GPTConfig, mesh, caller: str):
    """Serving-op TP validation: ``n_tensor_parallel > 1`` needs a mesh
    whose ``model`` axis is exactly that size (the shard_map programs bind
    it); tp == 1 normalizes mesh to None so memo keys stay shared."""
    tp = cfg.n_tensor_parallel
    if tp == 1:
        return None
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        MODEL_AXIS,
    )
    if mesh is None or dict(mesh.shape).get(MODEL_AXIS, 1) != tp:
        got = None if mesh is None else dict(mesh.shape)
        raise ValueError(
            f"{caller}: cfg.n_tensor_parallel={tp} needs a mesh with a "
            f"'{MODEL_AXIS}' axis of that size, got {got}")
    return mesh


# Built decode-path programs, keyed by their STATIC config. Every function
# cached here closes over shape scalars only — params (and therefore the
# stages' weights and layer count) arrive as traced ARGUMENTS — so two
# builds with the same key return one shared jitted callable and its
# compiled executables. Build-time validation still runs per call (it
# checks the CALLER's stages); only the trace/compile work is shared.
# This is what keeps a fleet of serving engines (and a test suite full of
# them) from recompiling identical programs per instance.
_DECODE_BUILD_CACHE: dict = {}


def _memo_build(key: tuple, build):
    fn = _DECODE_BUILD_CACHE.get(key)
    if fn is None:
        fn = _DECODE_BUILD_CACHE[key] = build()
    return fn


def _dense_block_prefill(bp, h, li, kc, vc, prompt_len, n_heads):
    """One block over the whole prompt [b, T0, d], recording cache row
    ``li`` for positions [0, prompt_len). K/V are cast to the cache's dtype
    (a bf16 cache halves decode memory; reads promote back in the einsum)."""
    q, k, v = _dense_qkv(bp, h, n_heads)
    kc = kc.at[li, :, :, :prompt_len].set(k.astype(kc.dtype))
    vc = vc.at[li, :, :, :prompt_len].set(v.astype(vc.dtype))
    return _dense_attn_tail(bp, h, causal_attention_core(q, k, v)), kc, vc


def _dense_block_step(bp, h, li, kc, vc, i, total, n_heads):
    """One block on ONE token [b, 1, d] against cache row ``li``; writes K/V
    at position ``i`` (cast to the cache's dtype). Same scale expression as
    causal_attention_core (divide by sqrt(dh)) so prefill and step compile
    to identical math."""
    dh = h.shape[-1] // n_heads
    q, knew, vnew = _dense_qkv(bp, h, n_heads)          # [B,H,1,dh] each
    kc = jax.lax.dynamic_update_slice(kc, knew[None].astype(kc.dtype),
                                      (li, 0, 0, i, 0))
    vc = jax.lax.dynamic_update_slice(vc, vnew[None].astype(vc.dtype),
                                      (li, 0, 0, i, 0))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kc[li]) / math.sqrt(dh)
    live = (jnp.arange(total) <= i)[None, None, None, :]
    scores = jnp.where(live, scores, -jnp.inf)
    a = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(scores, axis=-1), vc[li])
    return _dense_attn_tail(bp, h, a), kc, vc


def _validate_decode_build(stages, cfg, prompt_len, n_new, caller):
    """Shared decoder-build validation (cached + pipeline-parallel): dense
    blocks only, sane lengths, and cfg matching the stages' ACTUAL build
    shapes (a mismatched cfg would otherwise silently clamp pos-table
    slices past the real seq_len instead of raising)."""
    if cfg.n_experts > 0:
        raise ValueError(
            f"{caller} supports dense-MLP blocks only — MoE capacity is a "
            f"full-sequence quantity, so per-token cached routing would "
            f"change overflow behavior; use make_decoder")
    if prompt_len < 1:
        raise ValueError(
            f"{caller} needs a non-empty prompt (t0 >= 1): the first "
            f"decoded token is conditioned on the prompt's last position")
    if n_new < 1:
        raise ValueError(f"{caller} needs n_new >= 1 (there is nothing to "
                         f"cache for a pure-prefill call)")
    total = prompt_len + n_new
    if total > cfg.seq_len:
        raise ValueError(
            f"prompt {prompt_len} + n_new {n_new} exceeds the model's "
            f"sequence length {cfg.seq_len}")
    _check_embed_matches(stages, cfg)
    return total


def _check_embed_matches(stages, cfg: GPTConfig) -> None:
    """The one copy of the cfg-vs-build shape check every decoder-style
    builder runs (cached/beam via :func:`_validate_decode_build`, the
    serving slot ops via :func:`_validate_slot_build`): a mismatched cfg
    would otherwise silently clamp pos-table slices past the real seq_len
    instead of raising."""
    embed = next((s.params.get("embed") for s in stages
                  if isinstance(s.params, dict) and "embed" in s.params),
                 None)
    if embed is None or embed["pos"].shape != (cfg.seq_len, cfg.d_model):
        got = None if embed is None else embed["pos"].shape
        raise ValueError(
            f"cfg (seq_len={cfg.seq_len}, d_model={cfg.d_model}) does not "
            f"match the stages' embedding table {got} — pass the GPTConfig "
            f"the stages were built with")


def _merged_stage_trees(params_list):
    """Re-join per-stage param trees into ``(embed, blocks, head)`` — the
    one copy shared by every single-device decoder (cached, beam)."""
    embed = head = None
    blocks = []
    for p in params_list:
        blocks.extend(p["blocks"])
        embed = p.get("embed", embed)
        head = p.get("head", head)
    return embed, blocks, head


def _head_logprobs(head, h_last):
    """[B, d] final hidden -> [B, V] log-probs (ln_f + untied head)."""
    return log_softmax(linear(head["out"], layer_norm(head["ln_f"], h_last)))


def _sample_from(row, ks, temperature, top_k, top_p):
    """Scale/filter/categorical core on a PRE-SPLIT subkey ``ks`` (argmax
    when temperature == 0) — the ONE copy of the sampling math, shared by
    every decoder (cached, recompute, pipeline-parallel)."""
    if temperature > 0.0:
        return jax.random.categorical(
            ks, _filter_top(row / temperature, top_k, top_p), axis=-1)
    return jnp.argmax(row, axis=-1)


def _sample_row(row, k, temperature, top_k, top_p):
    """One decode step on [B, V] log-probs -> ``(tokens, next_key)``.

    The ONE copy of the split discipline (exactly one split per sampled
    token) over :func:`_sample_from` — the single-device decoders call it,
    which is what keeps their key streams (and therefore their sampled
    tokens) exactly identical; the pipeline decoder performs the same split
    itself (uniformly on every device) and calls :func:`_sample_from`."""
    if temperature > 0.0:
        k, ks = jax.random.split(k)
        return _sample_from(row, ks, temperature, top_k, top_p), k
    return jnp.argmax(row, axis=-1), k


def _filter_top_dyn(scaled: jax.Array, top_k: jax.Array,
                    top_p: jax.Array) -> jax.Array:
    """Traced-argument counterpart of :func:`_filter_top` on ONE row [V] —
    the serving engine's decode tick samples every slot in a single compiled
    program, so each request's top-k/top-p knobs arrive as device scalars.
    ``top_k == 0`` disables top-k; ``top_p > 1`` disables top-p. When a
    filter IS enabled the math mirrors the static version step for step
    (same k-th-largest threshold, same exclusive-cumsum rule, top-k before
    top-p with the second sort on the top-k-filtered row), so a served
    request's filtered distribution matches its solo decode bit for bit."""
    V = scaled.shape[-1]
    srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)        # descending
    kth = jnp.take(srt, jnp.clip(top_k, 1, V) - 1, axis=-1)
    scaled = jnp.where((top_k >= 1) & (scaled < kth), -jnp.inf, scaled)
    srt = jnp.flip(jnp.sort(scaled, axis=-1), axis=-1)        # post-top-k
    p = jax.nn.softmax(srt, axis=-1)
    exclusive = jnp.cumsum(p, axis=-1) - p
    keep = exclusive < top_p                                  # top-1 always
    thresh = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)
    return jnp.where((top_p <= 1.0) & (scaled < thresh), -jnp.inf, scaled)


def _sample_dyn(row: jax.Array, key_data: jax.Array, temperature: jax.Array,
                top_k: jax.Array, top_p: jax.Array
                ) -> tuple[jax.Array, jax.Array]:
    """One decode step on ONE row [V] with TRACED sampling params ->
    ``(token, next_key_data)``. Mirrors :func:`_sample_row`'s key-split
    discipline exactly — greedy (``temperature == 0``) consumes no
    randomness, sampling splits once per token — so a served request's key
    stream (and therefore its tokens) match its solo decode bit for bit.
    Keys travel as raw uint32 key data so per-slot selection can use
    ``jnp.where`` (typed key arrays reject it); ``vmap`` over slots is the
    loop semantics, so per-slot draws equal the unbatched calls."""
    k = jax.random.wrap_key_data(key_data)
    nk, ks = jax.random.split(k)
    safe_t = jnp.where(temperature > 0, temperature, jnp.float32(1.0))
    filtered = _filter_top_dyn(row / safe_t, top_k, top_p)
    samp = jax.random.categorical(ks, filtered, axis=-1)
    tok = jnp.where(temperature > 0, samp, jnp.argmax(row, axis=-1))
    kd = jnp.where(temperature > 0, jax.random.key_data(nk), key_data)
    return tok.astype(jnp.int32), kd


def _check_sampling_args(temperature, top_k, top_p, vocab=None):
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError("top_k/top_p filtering needs temperature > 0 "
                         "(greedy decoding ignores the filtered tail)")
    if top_k is not None and (top_k < 1 or
                              (vocab is not None and top_k > vocab)):
        raise ValueError(f"top_k={top_k} out of range [1, vocab={vocab}]")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} out of range (0, 1]")


def generate(stages, prompt: jax.Array, n_new: int,
             key: jax.Array | None = None,
             temperature: float = 0.0,
             cfg: GPTConfig | None = None,
             top_k: int | None = None,
             top_p: float | None = None) -> jax.Array:
    """Autoregressive decoding from the (single-device) stage composition.

    ``prompt``: [B, T0] int tokens; returns [B, T0 + n_new]. The whole decode
    is ONE ``lax.scan`` over a fixed-length token buffer — static shapes, no
    per-step Python dispatch (the TPU-idiomatic decode shape). Each step
    recomputes the full prefix forward; causal masking makes the
    not-yet-written zero padding at positions > current length invisible to
    the prediction read at the current position. Full-prefix recompute is
    O(T²) per sequence — right for reference-scale models; a KV-cache decode
    path is the standard next optimization.

    ``temperature=0`` → greedy argmax; ``> 0`` → softmax sampling with
    ``key`` (required); ``top_k``/``top_p`` filter the sampling
    distribution. One-shot convenience: retraces per call — build the
    decoder once with :func:`make_decoder` / :func:`make_cached_decoder`
    for repeated generation.

    ``cfg``: pass the stages' build config to decode through the O(T)
    KV-cache path (:func:`make_cached_decoder`) instead of the O(T²)
    full-prefix recompute — same tokens, faster; dense-MLP single-device
    builds only (the cached path's restrictions apply).

    The reference has no inference path at all (eval only,
    ``/root/reference/simple_distributed.py:119-132``); this is a capability
    extension.
    """
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    key = key if key is not None else jax.random.key(0)
    if cfg is not None:
        dec = make_cached_decoder(stages, cfg, int(prompt.shape[1]), n_new,
                                  temperature=temperature, top_k=top_k,
                                  top_p=top_p)
    else:
        dec = make_decoder(stages, int(prompt.shape[1]), n_new,
                           temperature=temperature, top_k=top_k, top_p=top_p)
    return dec([s.params for s in stages], prompt, key)


def make_cached_decoder(stages, cfg: GPTConfig, prompt_len: int, n_new: int,
                        temperature: float = 0.0, top_k: int | None = None,
                        top_p: float | None = None, cache_dtype=None):
    """KV-cache decode: ``decode(params, prompt, key) -> [B, prompt_len+n_new]``.

    Same contract as :func:`make_decoder` but O(T) per generated token instead
    of O(T²): a one-shot prefill runs the prompt through every block once,
    recording each layer's K/V projections into static ``[L, B, H, total, dh]``
    cache buffers, and the decode ``lax.scan`` then pushes ONE token per step —
    the new K/V row lands in the cache via ``lax.dynamic_update_slice`` and
    attention is a single [1, total] masked row against the cache. Static
    shapes throughout (the TPU decode idiom: no growing buffers, no retraces).

    For ``attn_impl="dense"`` builds greedy tokens match :func:`make_decoder`
    exactly (same math, different association; see
    tests/test_gpt.py::test_cached_decoder_matches_recompute). The cached path
    always computes DENSE attention math on the weights — an
    ``attn_impl="flash"`` build decodes fine here (flash is the same math),
    but ``make_decoder`` would run the Pallas kernel, whose different
    accumulation order can flip a near-tie argmax; cross-decoder token
    equality is only to float tolerance in that case.

    Single-device dense-MLP composition only: MoE routing capacity is defined
    per full sequence (``default_capacity(T, ...)``), so per-token routing
    would silently change which tokens overflow — decode MoE models with
    :func:`make_decoder`. Sequence-parallel builds (``cfg.n_seq > 1``) use mesh
    collectives in their applies and cannot run here either (same restriction
    as :func:`make_decoder`).

    The reference has no inference path at all (eval only,
    ``/root/reference/simple_distributed.py:119-132``).

    Builds are memoized on their static config (``_DECODE_BUILD_CACHE``):
    the program traces everything model-shaped from ``params``, so two
    calls with the same (cfg, lengths, sampling, cache dtype) share one
    jitted callable — and its compiled executables — even across stages
    builds.
    """
    if cfg.n_seq > 1:
        raise ValueError(
            "cached decode is single-device; rebuild the stages with n_seq=1 "
            "(same weights) as make_decoder requires too")
    _check_sampling_args(temperature, top_k, top_p, cfg.vocab)
    _check_cache_quantization(cache_dtype, "make_cached_decoder",
                              paged=False)
    total = _validate_decode_build(stages, cfg, prompt_len, n_new,
                                   "make_cached_decoder")
    H, d = cfg.n_heads, cfg.d_model
    dh = d // H
    cd = _cache_dtype(cache_dtype)
    key_ = ("cached_decoder", cfg, prompt_len, n_new, temperature, top_k,
            top_p, jnp.dtype(cd).name)
    return _memo_build(key_, lambda: _build_cached_decoder(
        total, prompt_len, n_new, H, dh, cd, temperature, top_k, top_p))


def _build_cached_decoder(total, prompt_len, n_new, H, dh, cd,
                          temperature, top_k, top_p):
    from jax import lax

    _merged = _merged_stage_trees
    _head_row = _head_logprobs

    def _pick(row, k):
        return _sample_row(row, k, temperature, top_k, top_p)

    @jax.jit
    def decode(params, prompt, key):
        embed, blocks, head = _merged(params)
        b = prompt.shape[0]
        L = len(blocks)
        kc = jnp.zeros((L, b, H, total, dh), cd)
        vc = jnp.zeros((L, b, H, total, dh), cd)

        # --- prefill: one dense causal pass over the whole prompt, recording
        # every layer's K/V rows for positions [0, prompt_len)
        ids = prompt.astype(jnp.int32)
        h = embedding_lookup(embed["tok"], ids) + embed["pos"][:prompt_len]
        for li, bp in enumerate(blocks):
            h, kc, vc = _dense_block_prefill(bp, h, li, kc, vc, prompt_len, H)
        row = _head_row(head, h[:, -1])
        tok, key = _pick(row, key)          # token for position prompt_len

        # --- decode: one token per step; the input token sits at position i,
        # its K/V row lands at cache index i, and the masked attention row
        # covers positions [0, i]
        def step(carry, i):
            kc, vc, tok, k = carry
            pos = lax.dynamic_slice_in_dim(embed["pos"], i, 1, 0)
            h = embedding_lookup(embed["tok"], tok[:, None]) + pos   # [B,1,d]
            for li, bp in enumerate(blocks):
                h, kc, vc = _dense_block_step(bp, h, li, kc, vc, i, total, H)
            row = _head_row(head, h[:, 0])
            nxt, k = _pick(row, k)
            return (kc, vc, nxt, k), tok

        # steps i = prompt_len .. total-2 each CONSUME the carried token at
        # position i and emit it, producing the next; the final carried token
        # (position total-1) is appended after the scan
        (_, _, last, _), toks = lax.scan(
            step, (kc, vc, tok, key), prompt_len + jnp.arange(n_new - 1))
        out = jnp.concatenate(
            [prompt.astype(jnp.int32),
             jnp.moveaxis(toks, 0, 1),
             last[:, None]], axis=1)
        return out

    return decode


def _validate_slot_build(stages, cfg: GPTConfig, max_len: int,
                         caller: str, cache_dtype=None) -> None:
    """Shared validation for the serving slot ops: single-device dense-MLP
    builds only (the :func:`make_cached_decoder` restrictions — MoE routing
    capacity is a full-sequence quantity; sharded stage trees are per-shard
    slices, not the whole model), ``max_len`` within the position
    table, and no quantized cache dtype (dense slot rows are the parity
    anchors; the paged validator re-allows quantization)."""
    _check_cache_quantization(cache_dtype, caller, paged=False)
    if cfg.n_experts > 0:
        raise ValueError(
            f"{caller} supports dense-MLP blocks only — MoE capacity is a "
            f"full-sequence quantity (make_cached_decoder's restriction)")
    if cfg.n_seq > 1:
        raise ValueError(
            f"{caller} is single-device; rebuild the stages with n_seq=1")
    if any(getattr(s, "shards", None) is not None
           or getattr(s, "expert_shards", None) is not None for s in stages):
        raise ValueError(
            f"{caller} needs unsharded stage params — gather tensor/expert "
            f"shards into a dense build first")
    if not 2 <= max_len <= cfg.seq_len:
        raise ValueError(
            f"slot max_len={max_len} outside [2, seq_len={cfg.seq_len}] "
            f"(the position table bounds every slot's sequence budget)")
    _check_embed_matches(stages, cfg)


def make_slot_prefill(stages, cfg: GPTConfig, max_len: int,
                      cache_dtype=None, mesh=None,
                      adapters: bool = False):
    """Serving prefill-into-slot: ``prefill(params, kc, vc, prompt [1, T0],
    slot, key_data, temperature, top_k, top_p) -> (kc, vc, token,
    key_data)``.

    ``adapters=True`` builds the multi-tenant variant: two TRACED args
    append to the signature — the stacked adapter ``bank`` pytree and the
    request's bank-row index ``aid`` — and every block's q/v projection
    adds the gathered low-rank delta (:func:`_dense_qkv`). One static
    BOOL in the memo key: bank contents, row count and rank are all data,
    so adapter registration/hot-swap never retraces and any adapter mix
    shares this one program.

    Runs ONE request's prompt through every block (batch 1, exactly the
    solo decoder's prefill shapes and math — shared :func:`_dense_qkv` /
    ``causal_attention_core`` / :func:`_dense_attn_tail`), writes each
    layer's K/V rows into pool row ``slot`` at positions ``[0, T0)``, and
    samples the first output token with the request's own params and key
    stream (:func:`_sample_dyn`'s sentinels: ``top_k=0`` / ``top_p=2.0``
    disable). Retraces per distinct prompt length (the prompt shape is
    static — real serving buckets prompt lengths the same way); the decode
    tick stays one program regardless.

    ``kc``/``vc``: the pool buffers, ``[L, n_slots, H, max_len, dh]`` in
    the :func:`_cache_dtype` storage dtype (bf16 halves pool memory). They
    are DONATED — the engine always threads the returned buffers back into
    the pool, and donation lets XLA update the slot row in place instead of
    copying the whole pool per call.

    With ``cfg.n_tensor_parallel > 1`` (pass the ``mesh``): the same math
    inside ``shard_map`` — QKV on the local ``H/tp`` heads, K/V landing in
    this shard's slice of the head-sharded pool, the attention/MLP reduces
    of :func:`_tp_attn_tail` — with ``params`` in the
    :func:`pack_tp_serve_params` layout.
    """
    _validate_slot_build(stages, cfg, max_len, "make_slot_prefill",
                         cache_dtype)
    mesh = _validate_tp_serve(cfg, mesh, "make_slot_prefill")
    H = cfg.n_heads
    key_ = ("slot_prefill", cfg, max_len, mesh, adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_slot_prefill_tp(cfg, mesh,
                                                                adapters))
    return _memo_build(key_, lambda: _build_slot_prefill(H, adapters))


def _slot_prefill_fwd(blocks, embed, head, kc, vc, prompt, slot, H, tail,
                      ab_at=None):
    """One request's whole-prompt prefill into pool row ``slot`` — the one
    copy of the math, shared by the single-device and TP builds (``H`` is
    the LOCAL head count; ``tail`` closes each block; ``ab_at`` is the
    optional per-layer adapter lookup of :func:`_adapter_layers`)."""
    t0 = prompt.shape[1]
    ids = prompt.astype(jnp.int32)
    h = embedding_lookup(embed["tok"], ids) + embed["pos"][:t0]
    for li, bp in enumerate(blocks):
        q, k_, v = _dense_qkv(bp, h, H,               # [1, H, T0, dh]
                              None if ab_at is None else ab_at(li))
        kc = jax.lax.dynamic_update_slice(
            kc, k_.astype(kc.dtype)[None], (li, slot, 0, 0, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, v.astype(vc.dtype)[None], (li, slot, 0, 0, 0))
        h = tail(bp, h, causal_attention_core(q, k_, v))
    return kc, vc, _head_logprobs(head, h[:, -1])[0]  # row: [V]


def _build_slot_prefill(H, adapters=False):
    def run(params, kc, vc, prompt, slot, key_data, temperature, top_k,
            top_p, ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        kc, vc, row = _slot_prefill_fwd(blocks, embed, head, kc, vc,
                                        prompt, slot, H, _dense_attn_tail,
                                        ab_at)
        tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
        return kc, vc, tok, kd

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def prefill(params, kc, vc, prompt, slot, key_data, temperature,
                    top_k, top_p, bank, aid):
            return run(params, kc, vc, prompt, slot, key_data,
                       temperature, top_k, top_p,
                       _adapter_layers(bank, aid))

        return prefill

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def prefill(params, kc, vc, prompt, slot, key_data, temperature,
                top_k, top_p):
        return run(params, kc, vc, prompt, slot, key_data, temperature,
                   top_k, top_p)

    return prefill


def _build_slot_prefill_tp(cfg, mesh, adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, prompt, slot, key_data, temperature,
            top_k, top_p, ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        kc, vc, row = _slot_prefill_fwd(blocks, embed, head, kc, vc,
                                        prompt, slot, H_loc, tail, ab_at)
        row = _close_rows(row)
        tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
        return kc, vc, tok, kd

    if adapters:
        def body(params, kc, vc, prompt, slot, key_data, temperature,
                 top_k, top_p, bank, aid):
            return run(params, kc, vc, prompt, slot, key_data,
                       temperature, top_k, top_p,
                       _tp_adapter_layers(bank, aid, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=8, n_buf_out=2,
                       n_rest_out=2)

    def body(params, kc, vc, prompt, slot, key_data, temperature,
             top_k, top_p):
        return run(params, kc, vc, prompt, slot, key_data, temperature,
                   top_k, top_p)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=6, n_buf_out=2,
                   n_rest_out=2)


def _dense_block_step_slots(bp, h, li, kc, vc, pos, n_heads,
                            tail=_dense_attn_tail, ab=None):
    """One block on one token per SLOT (``h``: [S, 1, d]) against pool
    cache row ``li``; each slot writes its new K/V at its OWN position
    (``pos``: [S]) and attends ``[0, pos]``. Per-slot math is exactly
    :func:`_dense_block_step`'s (same scale expression, same einsums, same
    masked-row softmax), and every slot's output depends only on its own
    cache row — the bit-exactness anchor continuous batching rests on.
    ``n_heads`` is the LOCAL head count and ``tail`` closes the block (the
    TP build passes ``H/tp`` and :func:`_tp_attn_tail`); ``ab`` is this
    layer's optional batched adapter factors (:func:`_dense_qkv`)."""
    q, knew, vnew = _dense_qkv(bp, h, n_heads, ab)        # [S, H, 1, dh]
    # scale from the PROJECTED head dim (q's trailing axis), never from
    # h.shape[-1] // n_heads: under TP the local head count shrinks but the
    # per-head dim does not, and a local-count-derived scale silently
    # rescales attention (the causal_attention_core convention)
    dh = q.shape[-1]

    def upd(cache, new, p):
        return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

    kci = jax.vmap(upd)(kc[li], knew.astype(kc.dtype), pos)
    vci = jax.vmap(upd)(vc[li], vnew.astype(vc.dtype), pos)
    kc = kc.at[li].set(kci)
    vc = vc.at[li].set(vci)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, kci) / math.sqrt(dh)
    live = (jnp.arange(kci.shape[-2])[None, None, None, :]
            <= pos[:, None, None, None])
    scores = jnp.where(live, scores, -jnp.inf)
    a = jnp.einsum("bhqk,bhkd->bhqd",
                   jax.nn.softmax(scores, axis=-1), vci)
    return tail(bp, h, a), kc, vc


def make_slot_decode_step(stages, cfg: GPTConfig, max_len: int,
                          cache_dtype=None, mesh=None,
                          adapters: bool = False):
    """Serving decode tick: ``step(params, kc, vc, toks [S], pos [S],
    key_data [S, 2], temps [S], top_ks [S], top_ps [S]) -> (kc, vc,
    next_toks [S], next_key_data [S, 2])``.

    ONE batched token step over ALL ``n_slots`` slots — static shapes, so a
    single compiled program serves every tick regardless of occupancy.
    Each slot consumes its carried token at its own position, lands its K/V
    row via a per-slot scatter, attends its masked cache row, and samples
    with its own params and key stream (``vmap`` of :func:`_sample_dyn` —
    loop semantics, per-slot draws equal the unbatched calls). Inactive
    slots compute garbage that the engine discards host-side; their stale
    cache writes are invisible by construction (see ``serve/slots.py``).
    ``kc``/``vc`` are donated (same contract as :func:`make_slot_prefill`):
    one in-place pool update per tick, not a pool-sized copy per token.

    With ``cfg.n_tensor_parallel > 1`` (pass the ``mesh``): the shard_map
    twin over the head-sharded pool (:func:`make_slot_prefill`'s TP notes
    apply). ``adapters=True`` appends the traced ``(bank, aids [S])``
    multi-tenant args — each slot gathers its OWN adapter's low-rank
    factors by index, so one program serves any adapter mix per tick
    (:func:`make_slot_prefill`'s adapter notes apply).
    """
    _validate_slot_build(stages, cfg, max_len, "make_slot_decode_step",
                         cache_dtype)
    mesh = _validate_tp_serve(cfg, mesh, "make_slot_decode_step")
    H = cfg.n_heads
    key_ = ("slot_decode", cfg, max_len, mesh, adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_slot_decode_tp(cfg, mesh,
                                                               adapters))
    return _memo_build(key_, lambda: _build_slot_decode(H, adapters))


def _slot_decode_fwd(blocks, embed, head, kc, vc, toks, pos, H, tail,
                     ab_at=None):
    """The batched one-token-per-slot step's forward — shared by the
    single-device and TP builds and by the speculative draft proposer
    (which always runs base-model: the draft never takes ``ab_at``)."""
    pe = jnp.take(embed["pos"], pos, axis=0)[:, None]      # [S, 1, d]
    h = embedding_lookup(embed["tok"], toks[:, None]) + pe
    for li, bp in enumerate(blocks):
        h, kc, vc = _dense_block_step_slots(
            bp, h, li, kc, vc, pos, H, tail,
            None if ab_at is None else ab_at(li))
    return kc, vc, _head_logprobs(head, h[:, 0])           # rows: [S, V]


def _build_slot_decode(H, adapters=False):
    def run(params, kc, vc, toks, pos, key_data, temps, top_ks, top_ps,
            ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        kc, vc, rows = _slot_decode_fwd(blocks, embed, head, kc, vc, toks,
                                        pos, H, _dense_attn_tail, ab_at)
        toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                           top_ks, top_ps)
        return kc, vc, toks2, kd2

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, kc, vc, toks, pos, key_data, temps, top_ks,
                 top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, key_data, temps,
                       top_ks, top_ps, _adapter_layers(bank, aids))

        return step

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, kc, vc, toks, pos, key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, key_data, temps, top_ks,
                   top_ps)

    return step


def _build_slot_decode_tp(cfg, mesh, adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, toks, pos, key_data, temps, top_ks, top_ps,
            ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        kc, vc, rows = _slot_decode_fwd(blocks, embed, head, kc, vc, toks,
                                        pos, H_loc, tail, ab_at)
        rows = _close_rows(rows)
        toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                           top_ks, top_ps)
        return kc, vc, toks2, kd2

    if adapters:
        def body(params, kc, vc, toks, pos, key_data, temps, top_ks,
                 top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, key_data, temps,
                       top_ks, top_ps, _tp_adapter_layers(bank, aids, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=8, n_buf_out=2,
                       n_rest_out=2)

    def body(params, kc, vc, toks, pos, key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, key_data, temps, top_ks,
                   top_ps)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=6, n_buf_out=2,
                   n_rest_out=2)


def _validate_paged_build(stages, cfg: GPTConfig, max_len: int,
                          block_size: int, caller: str,
                          cache_dtype=None) -> None:
    """Paged-op validation: the slot-op restrictions plus a sane block.
    Quantized cache dtypes are allowed HERE (the paged pool carries the
    per-block scale planes) — only their availability is checked."""
    _validate_slot_build(stages, cfg, max_len, caller)
    _check_cache_quantization(cache_dtype, caller, paged=True)
    if block_size < 1:
        raise ValueError(f"{caller} needs block_size >= 1, got {block_size}")


def _gather_paged_rows(cache_l: jax.Array, table: jax.Array) -> jax.Array:
    """Assemble a sequence's contiguous K or V row from the paged pool.

    ``cache_l``: one layer's blocks ``[n_blocks, H, bs, dh]``; ``table``:
    logical->physical block ids, ``[NB]`` (one sequence) or ``[S, NB]``
    (one per slot). Returns ``[..., H, NB*bs, dh]`` with position ``p``
    of the sequence at flattened row index ``p`` — EXACTLY the dense
    layout's row order, so the attention math downstream is unchanged and
    the trailing garbage rows (trash-block entries past the allocated
    span) are removed by the same position mask that already hides
    not-yet-written dense rows."""
    rows = cache_l[table]                     # [..., NB, H, bs, dh]
    rows = jnp.moveaxis(rows, -4, -3)         # [..., H, NB, bs, dh]
    return rows.reshape(*rows.shape[:-3],
                        rows.shape[-3] * rows.shape[-2], rows.shape[-1])


def _paged_scatter(kc, li, phys, off, rows):
    """Land K/V ``rows`` (``[..., H, dh]``, aligned with the ``phys``/
    ``off`` index arrays ``[...]``) at layer ``li`` of a paged pool buffer
    — the ONE scatter every paged program uses. Plain buffers cast to the
    storage dtype; :class:`QuantKV` buffers quantize each row and land its
    scale in the matching plane, so a quantized pool never holds a
    half-updated (data, scale) pair."""
    if isinstance(kc, QuantKV):
        qd, sc = _quantize_rows(rows, kc.data.dtype)
        return QuantKV(kc.data.at[li, phys, :, off, :].set(qd),
                       kc.scale.at[li, phys, :, off].set(sc))
    return kc.at[li, phys, :, off, :].set(rows.astype(kc.dtype))


def _paged_gather(kc, li, table):
    """Layer ``li``'s gathered sequence rows (``[..., H, span, dh]``) for
    the dense-math attention path; :class:`QuantKV` buffers dequantize
    (``data * scale``, f32) so the downstream einsums see ordinary rows."""
    if isinstance(kc, QuantKV):
        rows = _gather_paged_rows(kc.data[li], table).astype(jnp.float32)
        sc = kc.scale[li][table]              # [..., NB, H, bs]
        sc = jnp.moveaxis(sc, -3, -2)         # [..., H, NB, bs]
        sc = sc.reshape(*sc.shape[:-2], sc.shape[-2] * sc.shape[-1])
        return rows * sc[..., None]
    return _gather_paged_rows(kc[li], table)


def _paged_attend(kc, vc, li, q, tables, qpos, bs):
    """The FUSED attention path: one Pallas pass over layer ``li``'s
    physical blocks (gather + mask + online-softmax attention, dequant
    fused for :class:`QuantKV` pools) — see ``ops/paged_attention.py``.
    ``q``: [S, H, K, dh]; ``qpos``: [S, K]. Returns f32 [S, H, K, dh],
    exactly the dense-math path's masked attention output."""
    from simple_distributed_machine_learning_tpu.ops.paged_attention import (
        paged_attention,
    )
    if isinstance(kc, QuantKV):
        return paged_attention(q, kc.data[li], vc.data[li], tables, qpos,
                               block_size=bs, kscale=kc.scale[li],
                               vscale=vc.scale[li])
    return paged_attention(q, kc[li], vc[li], tables, qpos, block_size=bs)


def _check_attn_kernel(kernel: str, caller: str) -> str:
    if kernel not in ("dense", "fused"):
        raise ValueError(
            f"{caller}: kernel must be 'dense' (gather-then-dense "
            f"attention, the parity anchor) or 'fused' (the Pallas "
            f"paged-attention kernel), got {kernel!r}")
    return kernel


def make_paged_prefill_chunk(stages, cfg: GPTConfig, max_len: int,
                             block_size: int, cache_dtype=None, mesh=None,
                             adapters: bool = False):
    """Chunked serving prefill into paged blocks: ``chunk(params, kc, vc,
    tokens [1, c], p0, table [NB], key_data, temperature, top_k, top_p) ->
    (kc, vc, token, key_data)``.

    Runs ONE request's prompt positions ``[p0, p0+c)`` through every block
    (batch 1, the solo decoder's math via the shared :func:`_dense_qkv` /
    :func:`_dense_attn_tail`), scattering each position's K/V into its
    physical block (``table[p // bs]``, offset ``p % bs``) and attending
    the gathered block row masked to ``<= position`` — which covers both
    earlier chunks (already in the cache, including SHARED prefix blocks
    another request prefilled) and the chunk's own freshly written rows.
    The engine interleaves these chunks with decode ticks so a long prompt
    never stalls in-flight requests; the last chunk's final position feeds
    the head and samples the request's first token (:func:`_sample_dyn` —
    the engine discards the sampled token and key for non-final chunks, so
    the request's key stream advances exactly once, at the same point as
    its solo decode).

    Retraces per distinct chunk length (like :func:`make_slot_prefill`
    retraces per prompt length). Bit-exactness vs the solo
    ``make_cached_decoder`` holds for f32 caches: the chunk reads earlier
    K/V back out of the cache, so a bf16 cache rounds where the solo
    monolithic prefill attends fresh f32 K/V — the one place the paged
    path's parity is dtype-conditional (the decode tick round-trips the
    cache in BOTH paths, so it is exempt).

    ``kc``/``vc`` (``[L, n_blocks+1, H, block_size, dh]``) are donated —
    the engine always threads the returned buffers back into the pool.
    ``adapters=True`` appends the traced ``(bank, aid)`` multi-tenant
    args (:func:`make_slot_prefill`'s adapter notes apply).
    """
    _validate_paged_build(stages, cfg, max_len, block_size,
                          "make_paged_prefill_chunk", cache_dtype)
    mesh = _validate_tp_serve(cfg, mesh, "make_paged_prefill_chunk")
    H, bs = cfg.n_heads, block_size
    dh = cfg.d_model // H
    key_ = ("paged_chunk", cfg, max_len, block_size, mesh, adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_paged_prefill_chunk_tp(
            cfg, bs, dh, mesh, adapters))
    return _memo_build(key_, lambda: _build_paged_prefill_chunk(
        H, bs, dh, adapters))


def _paged_chunk_fwd(blocks, embed, head, kc, vc, tokens, p0, table, H, bs,
                     dh, tail, ab_at=None):
    """One prompt chunk's scatter + block-gather attention — the shared
    forward of the single-device and TP paged prefill builds."""
    c = tokens.shape[1]
    ids = tokens.astype(jnp.int32)
    pos_emb = jax.lax.dynamic_slice_in_dim(embed["pos"], p0, c, 0)
    h = embedding_lookup(embed["tok"], ids) + pos_emb
    idx = p0 + jnp.arange(c)
    phys = table[idx // bs]                       # [c]
    off = idx % bs
    span = table.shape[0] * bs
    live = (jnp.arange(span)[None, :] <= idx[:, None])[None, None]
    for li, bp in enumerate(blocks):
        q, k_, v = _dense_qkv(bp, h, H,           # [1, H, c, dh]
                              None if ab_at is None else ab_at(li))
        kc = _paged_scatter(kc, li, phys, off, k_[0].swapaxes(0, 1))
        vc = _paged_scatter(vc, li, phys, off, v[0].swapaxes(0, 1))
        krow = _paged_gather(kc, li, table)       # [H, span, dh]
        vrow = _paged_gather(vc, li, table)
        scores = jnp.einsum("bhqd,hkd->bhqk", q, krow) / math.sqrt(dh)
        scores = jnp.where(live, scores, -jnp.inf)
        a = jnp.einsum("bhqk,hkd->bhqd",
                       jax.nn.softmax(scores, axis=-1), vrow)
        h = tail(bp, h, a)
    return kc, vc, _head_logprobs(head, h[:, -1])[0]    # row: [V]


def _build_paged_prefill_chunk(H, bs, dh, adapters=False):
    def run(params, kc, vc, tokens, p0, table, key_data, temperature,
            top_k, top_p, ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        kc, vc, row = _paged_chunk_fwd(blocks, embed, head, kc, vc,
                                       tokens, p0, table, H, bs, dh,
                                       _dense_attn_tail, ab_at)
        tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
        return kc, vc, tok, kd

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def chunk(params, kc, vc, tokens, p0, table, key_data,
                  temperature, top_k, top_p, bank, aid):
            return run(params, kc, vc, tokens, p0, table, key_data,
                       temperature, top_k, top_p,
                       _adapter_layers(bank, aid))

        return chunk

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def chunk(params, kc, vc, tokens, p0, table, key_data, temperature,
              top_k, top_p):
        return run(params, kc, vc, tokens, p0, table, key_data,
                   temperature, top_k, top_p)

    return chunk


def _build_paged_prefill_chunk_tp(cfg, bs, dh, mesh, adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, tokens, p0, table, key_data, temperature,
            top_k, top_p, ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        kc, vc, row = _paged_chunk_fwd(blocks, embed, head, kc, vc,
                                       tokens, p0, table, H_loc, bs, dh,
                                       tail, ab_at)
        row = _close_rows(row)
        tok, kd = _sample_dyn(row, key_data, temperature, top_k, top_p)
        return kc, vc, tok, kd

    if adapters:
        def body(params, kc, vc, tokens, p0, table, key_data, temperature,
                 top_k, top_p, bank, aid):
            return run(params, kc, vc, tokens, p0, table, key_data,
                       temperature, top_k, top_p,
                       _tp_adapter_layers(bank, aid, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=9, n_buf_out=2,
                       n_rest_out=2)

    def body(params, kc, vc, tokens, p0, table, key_data, temperature,
             top_k, top_p):
        return run(params, kc, vc, tokens, p0, table, key_data,
                   temperature, top_k, top_p)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=7, n_buf_out=2,
                   n_rest_out=2)


def make_paged_decode_step(stages, cfg: GPTConfig, max_len: int,
                           block_size: int, cache_dtype=None, mesh=None,
                           kernel: str = "dense",
                           adapters: bool = False):
    """Paged serving decode tick: ``step(params, kc, vc, toks [S], pos [S],
    tables [S, NB], key_data [S, 2], temps [S], top_ks [S], top_ps [S]) ->
    (kc, vc, next_toks [S], next_key_data [S, 2])``.

    The block-gather twin of :func:`make_slot_decode_step`: ONE batched
    token step over all slots, but each slot's K/V row is assembled from
    its block table (:func:`_gather_paged_rows`) instead of a dense pool
    row, and its new K/V lands via a per-slot scatter into physical block
    ``tables[s, pos // bs]`` at offset ``pos % bs``. Values for live
    positions are bit-identical to the dense layout's (same numbers,
    different storage), the mask removes everything else, so the PR-5
    bit-exactness anchor carries over unchanged.

    The dense pool's stale-write safety argument does NOT carry over: a
    non-decoding slot's table entries may alias blocks reused by a live
    request, so the ENGINE routes those slots' tick inputs to the trash
    block (``pos = 0``, all-trash table) — their garbage K/V lands where
    no real table points. ``kc``/``vc`` are donated (one in-place pool
    update per tick).

    With ``cfg.n_tensor_parallel > 1`` (pass the ``mesh``): the shard_map
    twin over the head-sharded block pool (:func:`make_slot_prefill`'s TP
    notes apply — block tables and positions stay replicated host inputs).

    ``kernel="fused"`` swaps the gather-then-dense attention for the
    single-pass Pallas paged-attention kernel (flash-decode layout,
    ``ops/paged_attention.py``): one HBM read of resident K/V per tick
    instead of read-materialize-reread. Greedy token streams are
    bit-exact vs ``kernel="dense"`` (logits to accumulation-order ulps);
    quantized pools dequantize inside the kernel.
    """
    _validate_paged_build(stages, cfg, max_len, block_size,
                          "make_paged_decode_step", cache_dtype)
    mesh = _validate_tp_serve(cfg, mesh, "make_paged_decode_step")
    _check_attn_kernel(kernel, "make_paged_decode_step")
    H, bs = cfg.n_heads, block_size
    dh = cfg.d_model // H
    key_ = ("paged_decode", cfg, max_len, block_size, mesh, kernel,
            adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_paged_decode_step_tp(
            cfg, bs, dh, mesh, kernel, adapters))
    return _memo_build(key_, lambda: _build_paged_decode_step(
        H, bs, dh, kernel, adapters))


def _paged_decode_fwd(blocks, embed, head, kc, vc, toks, pos, tables, H, bs,
                      dh, tail, kernel="dense", ab_at=None):
    """The batched one-token-per-slot block-gather step's forward — shared
    by the single-device and TP paged decode builds. ``kernel`` selects the
    attention path: ``"dense"`` gathers each slot's table span into a
    dense row buffer and runs masked softmax-attention einsums over it
    (two passes over resident K/V); ``"fused"`` runs the one-pass Pallas
    flash-decode kernel (:func:`_paged_attend`). Scatter (and quantize,
    for :class:`QuantKV` pools) happens before either path attends, so
    the new token's row is visible at its own position in both."""
    pe = jnp.take(embed["pos"], pos, axis=0)[:, None]     # [S, 1, d]
    h = embedding_lookup(embed["tok"], toks[:, None]) + pe
    phys = jnp.take_along_axis(tables, (pos // bs)[:, None],
                               axis=1)[:, 0]              # [S]
    off = pos % bs
    span = tables.shape[1] * bs
    live = (jnp.arange(span)[None, None, None, :]
            <= pos[:, None, None, None])
    for li, bp in enumerate(blocks):
        q, knew, vnew = _dense_qkv(bp, h, H,              # [S, H, 1, dh]
                                   None if ab_at is None else ab_at(li))
        kc = _paged_scatter(kc, li, phys, off, knew[:, :, 0, :])
        vc = _paged_scatter(vc, li, phys, off, vnew[:, :, 0, :])
        if kernel == "fused":
            a = _paged_attend(kc, vc, li, q, tables, pos[:, None], bs)
        else:
            krow = _paged_gather(kc, li, tables)          # [S,H,span,dh]
            vrow = _paged_gather(vc, li, tables)
            scores = (jnp.einsum("bhqd,bhkd->bhqk", q, krow)
                      / math.sqrt(dh))
            scores = jnp.where(live, scores, -jnp.inf)
            a = jnp.einsum("bhqk,bhkd->bhqd",
                           jax.nn.softmax(scores, axis=-1), vrow)
        h = tail(bp, h, a)
    return kc, vc, _head_logprobs(head, h[:, 0])          # rows: [S, V]


def _build_paged_decode_step(H, bs, dh, kernel="dense", adapters=False):
    def run(params, kc, vc, toks, pos, tables, key_data, temps, top_ks,
            top_ps, ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        kc, vc, rows = _paged_decode_fwd(blocks, embed, head, kc, vc, toks,
                                         pos, tables, H, bs, dh,
                                         _dense_attn_tail, kernel, ab_at)
        toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                           top_ks, top_ps)
        return kc, vc, toks2, kd2

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, kc, vc, toks, pos, tables, key_data, temps,
                 top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, tables, key_data,
                       temps, top_ks, top_ps, _adapter_layers(bank, aids))

        return step

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def step(params, kc, vc, toks, pos, tables, key_data, temps, top_ks,
             top_ps):
        return run(params, kc, vc, toks, pos, tables, key_data, temps,
                   top_ks, top_ps)

    return step


def _build_paged_decode_step_tp(cfg, bs, dh, mesh, kernel="dense",
                                adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, toks, pos, tables, key_data, temps, top_ks,
            top_ps, ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        kc, vc, rows = _paged_decode_fwd(blocks, embed, head, kc, vc, toks,
                                         pos, tables, H_loc, bs, dh, tail,
                                         kernel, ab_at)
        rows = _close_rows(rows)
        toks2, kd2 = jax.vmap(_sample_dyn)(rows, key_data, temps,
                                           top_ks, top_ps)
        return kc, vc, toks2, kd2

    if adapters:
        def body(params, kc, vc, toks, pos, tables, key_data, temps,
                 top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, tables, key_data,
                       temps, top_ks, top_ps,
                       _tp_adapter_layers(bank, aids, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=9, n_buf_out=2,
                       n_rest_out=2)

    def body(params, kc, vc, toks, pos, tables, key_data, temps, top_ks,
             top_ps):
        return run(params, kc, vc, toks, pos, tables, key_data, temps,
                   top_ks, top_ps)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=7, n_buf_out=2,
                   n_rest_out=2)


def make_paged_block_copy():
    """The copy-on-write device op: ``copy(kc, vc, dst, src) -> (kc, vc)``
    duplicates one physical block's rows across every layer before a
    divergent write. Buffers are donated so XLA updates the pool in place
    instead of materializing a second pool; ``dst``/``src`` are traced
    scalars so one compiled program serves every copy. Tree-mapped over
    the buffer leaves, so a quantized pool's :class:`QuantKV` pair (block
    data AND its scale plane, both with the physical-block axis at dim 1)
    copies atomically — a CoW that moved rows without their scales would
    silently rescale the destination block."""
    def build():
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def copy(kc, vc, dst, src):
            def one(buf):
                blk = jax.lax.dynamic_slice_in_dim(buf, src, 1, 1)
                return jax.lax.dynamic_update_slice_in_dim(buf, blk, dst, 1)

            return jax.tree.map(one, kc), jax.tree.map(one, vc)

        return copy

    return _memo_build(("paged_block_copy",), build)


def make_adapter_bank_update():
    """The tick-boundary adapter upload: ``update(bank, idx, adapter) ->
    bank`` rewrites ONE row of the stacked adapter bank in place (the
    bank is donated; ``idx`` is a traced scalar so one compiled program
    serves every upload/evict). This is how the AdapterStore hot-swaps a
    tenant's weights between ticks without retracing any decode program:
    the decode builders close over bank SHAPES only — bank contents are
    traced data, so a row rewrite is invisible to the trace cache."""
    def build():
        @functools.partial(jax.jit, donate_argnums=(0,))
        def update(bank, idx, adapter):
            return jax.tree.map(lambda b, a: b.at[idx].set(a), bank,
                                adapter)

        return update

    return _memo_build(("adapter_bank_update",), build)


# -- speculative decoding ---------------------------------------------------
#
# Draft/verify serving (ISSUE 9): a small draft model proposes tokens with
# cheap sequential steps, and the target model scores ALL of them in one
# batched K-token step, emitting the longest prefix it agrees with (plus
# its own correction at the first disagreement). With spec_k = K, a tick
# emits 1..K tokens per slot from TWO program dispatches (one propose scan,
# one verify) instead of one dispatch per token.
#
# Index discipline (the engine's contract): a slot at position p with
# pending input t0 would solo-decode by consuming t0@p -> g0, g0@p+1 -> g1,
# ... The draft's propose scan runs K steps (consuming t0, d0, .., d_{K-2}
# at p..p+K-1) producing proposals d0..d_{K-1}; verify consumes the K
# inputs [t0, d0, .., d_{K-2}] at positions p..p+K-1 in one forward,
# yielding rows r0..r_{K-1} where r_j is EXACTLY the row solo decode would
# sample token j+1 from — provided d0..d_{j-1} matched. Greedy acceptance
# therefore emits g_j = argmax(r_j) for j up to (and including) the first
# draft mismatch, which keeps greedy speculative decode bit-exact vs the
# solo make_cached_decoder stream (tests/test_serve.py). The last proposal
# d_{K-1} is never consumed by verify: the extra draft step exists so the
# draft cache already covers position p+K-1 when a tick accepts everything
# (static shapes; no conditional catch-up step next tick).
#
# Rejected-tail K/V: verify writes all K positions before it knows how
# many survive. In-budget positions land in the slot's own rows/blocks and
# are overwritten by the next tick before they can be attended (the same
# trailing-write argument the slot pools rest on); positions beyond the
# slot's remaining token budget (j >= valid_n) are routed to a trash sink —
# the dense layout's never-live row max_len-1, the paged pool's trash
# block 0 — so they cannot land past the reservation or in a neighbour.
#
# Sampled modes (temperature > 0) use standard residual-rejection
# sampling: accept draft token d with probability min(1, p(d)/q(d)) on the
# FILTERED target/draft distributions, else emit a sample from the
# normalized positive part of (p - q); the first rejection ends the tick's
# emission for that slot. Marginally each emitted token is distributed
# exactly as a solo sample, but the key stream spends TWO splits on a
# rejected position (accept draw + residual draw), so sampled speculative
# streams are deterministic-per-seed yet not token-identical to solo —
# only greedy carries the bit-exactness anchor.


def _check_spec_k(spec_k: int, caller: str) -> None:
    if spec_k < 2:
        raise ValueError(
            f"{caller}: spec_k must be >= 2 (spec_k=1 is plain one-token "
            f"decode — use the decode step), got {spec_k}")


def _spec_accept_sampled(rows, drafts, draft_rows, valid_n, key_data,
                         temperature, top_k, top_p):
    """Per-slot residual-rejection acceptance on the verify rows:
    ``(rows [K, V], drafts [K-1], draft_rows [K-1, V], valid_n, key_data,
    temperature, top_k, top_p) -> (toks [K], n_acc, key_data)`` —
    ``toks[:n_acc]`` are the emitted tokens. ``vmap`` over slots inside
    the SAMPLED branch of :func:`_spec_accept_rows` (the scheme is
    documented in the module-section comment); greedy slots' results are
    discarded by the caller's per-slot select, so the guard temperature
    below only keeps the math finite."""
    K = rows.shape[0]
    safe_t = jnp.where(temperature > 0, temperature, jnp.float32(1.0))

    def samp_step(carry, j):
        kd, alive = carry
        k = jax.random.wrap_key_data(kd)
        nk, ks = jax.random.split(k)               # _sample_dyn's split
        pt_log = _filter_top_dyn(rows[j] / safe_t, top_k, top_p)
        pt = jax.nn.softmax(pt_log)
        jj = jnp.minimum(j, K - 2)
        d = drafts[jj]
        qt = jax.nn.softmax(_filter_top_dyn(draft_rows[jj] / safe_t,
                                            top_k, top_p))
        accept = (jax.random.uniform(ks)
                  < jnp.minimum(pt[d] / jnp.maximum(qt[d], 1e-30), 1.0))
        # rejection: one more split funds the residual draw; an empty
        # residual (q >= p everywhere it matters, a numerical corner)
        # falls back to the plain filtered target distribution
        nk2, kr = jax.random.split(nk)
        resid = jnp.maximum(pt - qt, 0.0)
        resid_log = jnp.where(jnp.sum(resid) > 0,
                              jnp.log(jnp.maximum(resid, 1e-38)), pt_log)
        r_tok = jax.random.categorical(kr, resid_log).astype(jnp.int32)
        # the bonus row (j == K-1, no draft): a plain solo-style sample
        bonus = jax.random.categorical(ks, pt_log).astype(jnp.int32)
        has_draft = j < K - 1
        tok = jnp.where(has_draft, jnp.where(accept, d, r_tok), bonus)
        kd_next = jnp.where(has_draft & ~accept,
                            jax.random.key_data(nk2),
                            jax.random.key_data(nk))
        emit = alive & (j < valid_n)
        kd = jnp.where(emit, kd_next, kd)
        return (kd, emit & accept & has_draft), (tok, emit)

    (kd_s, _), (toks_s, emits) = jax.lax.scan(
        samp_step, (key_data, jnp.bool_(True)), jnp.arange(K))
    return (toks_s.astype(jnp.int32),
            jnp.sum(emits.astype(jnp.int32)).astype(jnp.int32), kd_s)


def _spec_accept_rows(rows, drafts, draft_rows, valid_n, key_data, temps,
                      top_ks, top_ps):
    """Batched speculative acceptance over every slot: ``(rows [S, K, V],
    drafts [S, K], draft_rows [S, K, V] — the propose outputs VERBATIM,
    only the first K-1 proposals are consumed — valid_n [S],
    key_data [S, 2], temps/top_ks/top_ps [S]) -> (toks [S, K],
    n_acc [S], key_data [S, 2])``.

    Greedy (``temps[s] == 0``): the slot's tokens are the target's own
    argmaxes; the emitted count is one more than the leading run of
    draft==argmax matches (the first mismatch position still emits the
    target's correction), capped at ``valid_n``; no randomness is
    consumed, so the key stream stays bit-aligned with solo decode.
    Sampled: the residual-rejection scheme of
    :func:`_spec_accept_sampled`. The sampled scan sits behind ONE
    batch-level ``lax.cond`` — an all-greedy tick (every greedy
    deployment, and the accept-all bench case the >= 2x throughput gate
    measures) never executes the K-step rejection scan at all, which is
    what keeps the verify program's marginal per-token cost near the
    attention math."""
    g = jnp.argmax(rows, axis=-1).astype(jnp.int32)          # [S, K]
    lead = jnp.cumprod((drafts[:, :-1] == g[:, :-1]).astype(jnp.int32),
                       axis=1)
    m_greedy = jnp.minimum(1 + jnp.sum(lead, axis=1),
                           valid_n).astype(jnp.int32)

    def sampled(_):
        return jax.vmap(_spec_accept_sampled)(
            rows, drafts[:, :-1], draft_rows[:, :-1], valid_n, key_data,
            temps, top_ks, top_ps)

    def greedy(_):
        return g, m_greedy, key_data

    toks_s, n_s, kd_s = jax.lax.cond(jnp.any(temps > 0), sampled, greedy,
                                     None)
    sm = temps > 0
    toks = jnp.where(sm[:, None], toks_s, g).astype(jnp.int32)
    n_acc = jnp.where(sm, n_s, m_greedy).astype(jnp.int32)
    kd = jnp.where(sm[:, None], kd_s, key_data)
    return toks, n_acc, kd


def make_slot_propose(stages, cfg: GPTConfig, max_len: int, spec_k: int,
                      cache_dtype=None):
    """Draft proposer: ``propose(params, kc, vc, toks [S], pos [S],
    key_data [S, 2], temps [S], top_ks [S], top_ps [S]) -> (kc, vc,
    drafts [S, K], draft_rows [S, K, V], key_data [S, 2])``.

    ``spec_k`` sequential draft decode steps over the draft's DENSE slot
    pool, fused into ONE compiled ``lax.scan`` — one dispatch proposes the
    whole tick's draft tokens (plus their raw log-prob rows, which the
    sampled verify's rejection test needs). Step j consumes the carried
    token at position ``pos + j`` (clamped to the never-live trash row
    ``max_len - 1`` past the budget; see the section comment) and per-slot
    math is exactly the decode tick's, so draft K/V rows stay valid for
    every accepted continuation. ``key_data`` is the request's SEPARATE
    draft key stream (greedy proposals consume none of it). The draft runs
    single-device/replicated even under a TP target — it is small by
    design; ``kc``/``vc`` are donated."""
    _validate_slot_build(stages, cfg, max_len, "make_slot_propose",
                         cache_dtype)
    _check_spec_k(spec_k, "make_slot_propose")
    if cfg.n_tensor_parallel > 1:
        raise ValueError(
            "make_slot_propose runs the draft model single-device "
            "(replicated under a TP target): build the draft with "
            "n_tensor_parallel=1")
    H = cfg.n_heads
    key_ = ("slot_propose", cfg, max_len, spec_k)
    return _memo_build(key_, lambda: _build_slot_propose(H, spec_k,
                                                         max_len))


def _build_slot_propose(H, K, ml):
    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def propose(params, kc, vc, toks, pos, key_data, temps, top_ks,
                top_ps):
        embed, blocks, head = _merged_stage_trees(params)

        def step(carry, j):
            kc, vc, tok, kd = carry
            p = jnp.minimum(pos + j, ml - 1)
            kc, vc, rows = _slot_decode_fwd(blocks, embed, head, kc, vc,
                                            tok, p, H, _dense_attn_tail)
            nxt, kd = jax.vmap(_sample_dyn)(rows, kd, temps, top_ks,
                                            top_ps)
            return (kc, vc, nxt, kd), (nxt, rows)

        (kc, vc, _, kd2), (drafts, rows) = jax.lax.scan(
            step, (kc, vc, toks, key_data), jnp.arange(K))
        return (kc, vc, jnp.moveaxis(drafts, 0, 1),
                jnp.moveaxis(rows, 0, 1), kd2)

    return propose


def _slot_verify_fwd(blocks, embed, head, kc, vc, xs, qpos, wpos, H, tail,
                     ab_at=None):
    """K-tokens-per-slot verify forward over the dense slot pool (``xs``:
    [S, K] input tokens, ``qpos``: [S, K] query positions, ``wpos``:
    [S, K] K/V write positions — ``qpos`` in budget, the never-live trash
    row past it). Per-position math is exactly the decode tick's (same
    projections, same masked-row softmax), which is what extends the PR-5
    bit-exactness anchor to speculative verify."""
    S, K = xs.shape
    pe = jnp.take(embed["pos"], qpos.reshape(-1),
                  axis=0).reshape(S, K, -1)
    h = embedding_lookup(embed["tok"], xs) + pe              # [S, K, d]
    ml = kc.shape[-2]
    live = (jnp.arange(ml)[None, None, None, :]
            <= qpos[:, None, :, None])                       # [S,1,K,ml]
    for li, bp in enumerate(blocks):
        q, knew, vnew = _dense_qkv(                          # [S, H, K, dh]
            bp, h, H, None if ab_at is None else ab_at(li))
        dh = q.shape[-1]          # the projected head dim (TP-safe scale)

        def upd(cache, new, wp):
            return cache.at[:, wp, :].set(new)               # [H, ml, dh]

        kci = jax.vmap(upd)(kc[li], knew.astype(kc.dtype), wpos)
        vci = jax.vmap(upd)(vc[li], vnew.astype(vc.dtype), wpos)
        kc = kc.at[li].set(kci)
        vc = vc.at[li].set(vci)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, kci) / math.sqrt(dh)
        scores = jnp.where(live, scores, -jnp.inf)
        a = jnp.einsum("bhqk,bhkd->bhqd",
                       jax.nn.softmax(scores, axis=-1), vci)
        h = tail(bp, h, a)
    return kc, vc, _head_logprobs(head, h)                   # [S, K, V]


def make_slot_verify_step(stages, cfg: GPTConfig, max_len: int, spec_k: int,
                          cache_dtype=None, mesh=None,
                          adapters: bool = False):
    """Target verify tick (dense layout): ``verify(params, kc, vc,
    toks [S], pos [S], drafts [S, K], draft_rows [S, K, V],
    valid_n [S], key_data [S, 2], temps [S], top_ks [S], top_ps [S]) ->
    (kc, vc, toks [S, K], n_acc [S], key_data [S, 2])``.

    ONE batched forward scores all ``spec_k`` positions of every slot
    (inputs ``[t0, d0, .., d_{K-2}]`` at positions ``pos .. pos+K-1``) and
    runs :func:`_spec_accept` per slot; ``valid_n`` is the slot's clamp
    ``min(spec_k, remaining token budget)`` (0 for non-decoding slots),
    bounding both emission and which positions write real K/V (the rest go
    to the trash row). ``kc``/``vc`` are donated.

    With ``cfg.n_tensor_parallel > 1`` (pass the ``mesh``): the shard_map
    twin — head-sharded QKV/O over the head-sharded pool, rows re-closed
    across the model axis before acceptance, so every shard accepts the
    same prefix."""
    _validate_slot_build(stages, cfg, max_len, "make_slot_verify_step",
                         cache_dtype)
    _check_spec_k(spec_k, "make_slot_verify_step")
    mesh = _validate_tp_serve(cfg, mesh, "make_slot_verify_step")
    H = cfg.n_heads
    key_ = ("slot_verify", cfg, max_len, spec_k, mesh, adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_slot_verify_tp(
            cfg, spec_k, max_len, mesh, adapters))
    return _memo_build(key_, lambda: _build_slot_verify(H, spec_k,
                                                        max_len, adapters))


def _verify_positions(pos, valid_n, K, ml):
    """Query/write position plan shared by the dense verify builds:
    queries at ``pos + j`` (clamped in-table), writes routed to the
    never-live trash row ``ml - 1`` once past the slot's budget."""
    j = jnp.arange(K)[None, :]
    qpos = jnp.minimum(pos[:, None] + j, ml - 1)
    wpos = jnp.where(j < valid_n[:, None], qpos, ml - 1)
    return qpos, wpos


def _build_slot_verify(H, K, ml, adapters=False):
    def run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
            key_data, temps, top_ks, top_ps, ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        xs = jnp.concatenate([toks[:, None], drafts[:, :-1]], axis=1)
        qpos, wpos = _verify_positions(pos, valid_n, K, ml)
        kc, vc, rows = _slot_verify_fwd(blocks, embed, head, kc, vc, xs,
                                        qpos, wpos, H, _dense_attn_tail,
                                        ab_at)
        toks2, n_acc, kd2 = _spec_accept_rows(
            rows, drafts, draft_rows, valid_n, key_data, temps, top_ks,
            top_ps)
        return kc, vc, toks2, n_acc, kd2

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def verify(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   key_data, temps, top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, drafts, draft_rows,
                       valid_n, key_data, temps, top_ks, top_ps,
                       _adapter_layers(bank, aids))

        return verify

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def verify(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
               key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   key_data, temps, top_ks, top_ps)

    return verify


def _build_slot_verify_tp(cfg, K, ml, mesh, adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
            key_data, temps, top_ks, top_ps, ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        xs = jnp.concatenate([toks[:, None], drafts[:, :-1]], axis=1)
        qpos, wpos = _verify_positions(pos, valid_n, K, ml)
        kc, vc, rows = _slot_verify_fwd(blocks, embed, head, kc, vc, xs,
                                        qpos, wpos, H_loc, tail, ab_at)
        rows = _close_rows(rows)
        toks2, n_acc, kd2 = _spec_accept_rows(
            rows, drafts, draft_rows, valid_n, key_data, temps, top_ks,
            top_ps)
        return kc, vc, toks2, n_acc, kd2

    if adapters:
        def body(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                 key_data, temps, top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, drafts, draft_rows,
                       valid_n, key_data, temps, top_ks, top_ps,
                       _tp_adapter_layers(bank, aids, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=11, n_buf_out=2,
                       n_rest_out=3)

    def body(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
             key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   key_data, temps, top_ks, top_ps)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=9, n_buf_out=2,
                   n_rest_out=3)


def _paged_verify_fwd(blocks, embed, head, kc, vc, xs, qpos, wphys, woff,
                      tables, H, bs, dh, tail, kernel="dense", ab_at=None):
    """K-tokens-per-slot verify forward over the paged block pool: scatter
    each position's K/V into ``(wphys, woff)`` (the trash block past the
    budget) and attend the table span, masked per query — via the
    gather-then-dense einsums (``kernel="dense"``) or the one-pass Pallas
    paged-attention kernel's K-token variant (``kernel="fused"``; the
    per-query mask is the kernel's own ``qpos`` plan)."""
    S, K = xs.shape
    pe = jnp.take(embed["pos"], qpos.reshape(-1),
                  axis=0).reshape(S, K, -1)
    h = embedding_lookup(embed["tok"], xs) + pe              # [S, K, d]
    span = tables.shape[1] * bs
    live = (jnp.arange(span)[None, None, None, :]
            <= qpos[:, None, :, None])                       # [S,1,K,span]
    for li, bp in enumerate(blocks):
        q, knew, vnew = _dense_qkv(                          # [S, H, K, dh]
            bp, h, H, None if ab_at is None else ab_at(li))
        kc = _paged_scatter(kc, li, wphys, woff, knew.swapaxes(1, 2))
        vc = _paged_scatter(vc, li, wphys, woff, vnew.swapaxes(1, 2))
        if kernel == "fused":
            a = _paged_attend(kc, vc, li, q, tables, qpos, bs)
        else:
            krow = _paged_gather(kc, li, tables)             # [S,H,span,dh]
            vrow = _paged_gather(vc, li, tables)
            scores = (jnp.einsum("bhqd,bhkd->bhqk", q, krow)
                      / math.sqrt(dh))
            scores = jnp.where(live, scores, -jnp.inf)
            a = jnp.einsum("bhqk,bhkd->bhqd",
                           jax.nn.softmax(scores, axis=-1), vrow)
        h = tail(bp, h, a)
    return kc, vc, _head_logprobs(head, h)                   # [S, K, V]


def make_paged_verify_step(stages, cfg: GPTConfig, max_len: int,
                           block_size: int, spec_k: int, cache_dtype=None,
                           mesh=None, kernel: str = "dense",
                           adapters: bool = False):
    """Target verify tick (paged layout): ``verify(params, kc, vc,
    toks [S], pos [S], drafts [S, K], draft_rows [S, K, V],
    valid_n [S], tables [S, NB], key_data [S, 2], temps [S], top_ks [S],
    top_ps [S]) -> (kc, vc, toks [S, K], n_acc [S], key_data [S, 2])``.

    The block-gather twin of :func:`make_slot_verify_step`: per-position
    physical blocks come from the slot's table (``tables[s, (pos+j)//bs]``
    at offset ``(pos+j) % bs``), with positions past ``valid_n`` routed to
    the pool's trash block 0 — a rejected tail (or a non-decoding slot)
    can neither overrun the slot's reservation nor touch a neighbour's
    blocks. The engine must have ``ensure_writable``'d positions
    ``pos .. pos+valid_n-1`` first (same contract as the decode tick).
    ``kc``/``vc`` are donated. TP: :func:`make_slot_verify_step`'s notes
    apply. ``kernel="fused"`` runs the K-token variant of the Pallas
    paged-attention kernel instead of gather-then-dense (same greedy
    bit-exactness contract as :func:`make_paged_decode_step`)."""
    _validate_paged_build(stages, cfg, max_len, block_size,
                          "make_paged_verify_step", cache_dtype)
    _check_spec_k(spec_k, "make_paged_verify_step")
    mesh = _validate_tp_serve(cfg, mesh, "make_paged_verify_step")
    _check_attn_kernel(kernel, "make_paged_verify_step")
    H, bs = cfg.n_heads, block_size
    dh = cfg.d_model // H
    key_ = ("paged_verify", cfg, max_len, block_size, spec_k, mesh, kernel,
            adapters)
    if cfg.n_tensor_parallel > 1:
        return _memo_build(key_, lambda: _build_paged_verify_step_tp(
            cfg, spec_k, max_len, bs, dh, mesh, kernel, adapters))
    return _memo_build(key_, lambda: _build_paged_verify_step(
        H, spec_k, max_len, bs, dh, kernel, adapters))


def _paged_verify_routing(pos, valid_n, tables, K, bs, ml):
    """Per-position write routing for the paged verify: physical block and
    offset for ``pos + j``, the trash block (0) once past the budget."""
    j = jnp.arange(K)[None, :]
    qpos = jnp.minimum(pos[:, None] + j, ml - 1)
    NB = tables.shape[1]
    phys = jnp.take_along_axis(tables, jnp.clip(qpos // bs, 0, NB - 1),
                               axis=1)                       # [S, K]
    wphys = jnp.where(j < valid_n[:, None], phys, 0)         # 0 == TRASH
    woff = qpos % bs
    return qpos, wphys, woff


def _build_paged_verify_step(H, K, ml, bs, dh, kernel="dense",
                             adapters=False):
    def run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
            tables, key_data, temps, top_ks, top_ps, ab_at=None):
        embed, blocks, head = _merged_stage_trees(params)
        xs = jnp.concatenate([toks[:, None], drafts[:, :-1]], axis=1)
        qpos, wphys, woff = _paged_verify_routing(pos, valid_n, tables, K,
                                                  bs, ml)
        kc, vc, rows = _paged_verify_fwd(blocks, embed, head, kc, vc, xs,
                                         qpos, wphys, woff, tables, H, bs,
                                         dh, _dense_attn_tail, kernel,
                                         ab_at)
        toks2, n_acc, kd2 = _spec_accept_rows(
            rows, drafts, draft_rows, valid_n, key_data, temps, top_ks,
            top_ps)
        return kc, vc, toks2, n_acc, kd2

    if adapters:
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def verify(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   tables, key_data, temps, top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, drafts, draft_rows,
                       valid_n, tables, key_data, temps, top_ks, top_ps,
                       _adapter_layers(bank, aids))

        return verify

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def verify(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
               tables, key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   tables, key_data, temps, top_ks, top_ps)

    return verify


def _build_paged_verify_step_tp(cfg, K, ml, bs, dh, mesh, kernel="dense",
                                adapters=False):
    tp = cfg.n_tensor_parallel
    tail = functools.partial(_tp_attn_tail, overlap=cfg.overlap)
    H_loc = cfg.n_heads // tp

    def run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
            tables, key_data, temps, top_ks, top_ps, ab_at=None):
        blocks, embed, head = _tp_local_trees(params)
        xs = jnp.concatenate([toks[:, None], drafts[:, :-1]], axis=1)
        qpos, wphys, woff = _paged_verify_routing(pos, valid_n, tables, K,
                                                  bs, ml)
        kc, vc, rows = _paged_verify_fwd(blocks, embed, head, kc, vc, xs,
                                         qpos, wphys, woff, tables, H_loc,
                                         bs, dh, tail, kernel, ab_at)
        rows = _close_rows(rows)
        toks2, n_acc, kd2 = _spec_accept_rows(
            rows, drafts, draft_rows, valid_n, key_data, temps, top_ks,
            top_ps)
        return kc, vc, toks2, n_acc, kd2

    if adapters:
        def body(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                 tables, key_data, temps, top_ks, top_ps, bank, aids):
            return run(params, kc, vc, toks, pos, drafts, draft_rows,
                       valid_n, tables, key_data, temps, top_ks, top_ps,
                       _tp_adapter_layers(bank, aids, tp))

        return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=12, n_buf_out=2,
                       n_rest_out=3)

    def body(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
             tables, key_data, temps, top_ks, top_ps):
        return run(params, kc, vc, toks, pos, drafts, draft_rows, valid_n,
                   tables, key_data, temps, top_ks, top_ps)

    return _tp_jit(body, mesh, n_buf_in=2, n_rest_in=10, n_buf_out=2,
                   n_rest_out=3)


def _check_spec_tick_build(cfg: GPTConfig, draft_cfg: GPTConfig,
                           caller: str) -> None:
    if cfg.n_tensor_parallel > 1:
        raise ValueError(
            f"{caller} fuses the single-device tick only — a TP target "
            f"runs propose and verify as separate dispatches (the verify "
            f"is a shard_map program; see InferenceEngine)")
    if draft_cfg.vocab != cfg.vocab:
        raise ValueError(
            f"{caller}: draft vocab {draft_cfg.vocab} != target vocab "
            f"{cfg.vocab}")


def make_slot_spec_tick(stages, cfg: GPTConfig, draft_stages,
                        draft_cfg: GPTConfig, max_len: int, spec_k: int,
                        cache_dtype=None, adapters: bool = False):
    """The FUSED speculative tick (dense layout, single-device targets):
    ``tick(dparams, dkc, dvc, params, kc, vc, toks [S], pos [S],
    valid_n [S], draft_key_data [S, 2], key_data [S, 2], temps [S],
    top_ks [S], top_ps [S]) -> (dkc, dvc, kc, vc, toks [S, K],
    n_acc [S], key_data, draft_key_data)``.

    One compiled program runs the draft propose scan AND the batched
    target verify — ONE dispatch per speculative tick instead of two, and
    the ``[S, K, V]`` draft log-prob rows never materialize as a program
    output (they flow straight into the acceptance test inside the fused
    program). Exactly :func:`make_slot_propose` composed with
    :func:`make_slot_verify_step`, so the greedy bit-exactness contract
    carries over unchanged. All four pool buffers are donated.

    With ``adapters=True`` the tick takes trailing ``(bank, aids)`` and
    forwards them to the VERIFY side only: the draft proposer stays the
    base model (a wrong proposal only costs acceptance rate, never
    correctness — verify's adapted rows decide every emitted token)."""
    _check_spec_tick_build(cfg, draft_cfg, "make_slot_spec_tick")
    propose = make_slot_propose(draft_stages, draft_cfg, max_len, spec_k,
                                cache_dtype)
    verify = make_slot_verify_step(stages, cfg, max_len, spec_k,
                                   cache_dtype, adapters=adapters)

    def build():
        def run(dparams, dkc, dvc, params, kc, vc, toks, pos, valid_n,
                dkd, kd, temps, top_ks, top_ps, extra=()):
            dkc, dvc, drafts, qrows, dkd2 = propose(
                dparams, dkc, dvc, toks, pos, dkd, temps, top_ks, top_ps)
            kc, vc, otoks, nacc, kd2 = verify(
                params, kc, vc, toks, pos, drafts, qrows, valid_n, kd,
                temps, top_ks, top_ps, *extra)
            return dkc, dvc, kc, vc, otoks, nacc, kd2, dkd2

        if adapters:
            @functools.partial(jax.jit, donate_argnums=(1, 2, 4, 5))
            def tick(dparams, dkc, dvc, params, kc, vc, toks, pos,
                     valid_n, dkd, kd, temps, top_ks, top_ps, bank, aids):
                return run(dparams, dkc, dvc, params, kc, vc, toks, pos,
                           valid_n, dkd, kd, temps, top_ks, top_ps,
                           (bank, aids))

            return tick

        @functools.partial(jax.jit, donate_argnums=(1, 2, 4, 5))
        def tick(dparams, dkc, dvc, params, kc, vc, toks, pos, valid_n,
                 dkd, kd, temps, top_ks, top_ps):
            return run(dparams, dkc, dvc, params, kc, vc, toks, pos,
                       valid_n, dkd, kd, temps, top_ks, top_ps)

        return tick

    return _memo_build(("slot_spec_tick", cfg, draft_cfg, max_len, spec_k,
                        adapters), build)


def make_paged_spec_tick(stages, cfg: GPTConfig, draft_stages,
                         draft_cfg: GPTConfig, max_len: int,
                         block_size: int, spec_k: int, cache_dtype=None,
                         kernel: str = "dense", adapters: bool = False):
    """Paged twin of :func:`make_slot_spec_tick`: ``tick(dparams, dkc,
    dvc, params, kc, vc, toks, pos, valid_n, tables [S, NB], dkd, kd,
    temps, top_ks, top_ps) -> (dkc, dvc, kc, vc, toks [S, K], n_acc [S],
    key_data, draft_key_data)`` — the draft pool stays the dense slot
    layout (the engine's draft discipline), the target side is the
    block-gather :func:`make_paged_verify_step` (``kernel="fused"``
    routes it through the Pallas paged-attention kernel)."""
    _check_spec_tick_build(cfg, draft_cfg, "make_paged_spec_tick")
    # the draft pool is dense slot rows: a quantized TARGET dtype falls
    # back to f32 for the draft (the engine builds its draft buffers with
    # the same rule)
    draft_cd = None if _is_quantized_dtype(cache_dtype) else cache_dtype
    propose = make_slot_propose(draft_stages, draft_cfg, max_len, spec_k,
                                draft_cd)
    verify = make_paged_verify_step(stages, cfg, max_len, block_size,
                                    spec_k, cache_dtype, kernel=kernel,
                                    adapters=adapters)

    def build():
        def run(dparams, dkc, dvc, params, kc, vc, toks, pos, valid_n,
                tables, dkd, kd, temps, top_ks, top_ps, extra=()):
            dkc, dvc, drafts, qrows, dkd2 = propose(
                dparams, dkc, dvc, toks, pos, dkd, temps, top_ks, top_ps)
            kc, vc, otoks, nacc, kd2 = verify(
                params, kc, vc, toks, pos, drafts, qrows, valid_n,
                tables, kd, temps, top_ks, top_ps, *extra)
            return dkc, dvc, kc, vc, otoks, nacc, kd2, dkd2

        if adapters:
            @functools.partial(jax.jit, donate_argnums=(1, 2, 4, 5))
            def tick(dparams, dkc, dvc, params, kc, vc, toks, pos,
                     valid_n, tables, dkd, kd, temps, top_ks, top_ps,
                     bank, aids):
                return run(dparams, dkc, dvc, params, kc, vc, toks, pos,
                           valid_n, tables, dkd, kd, temps, top_ks,
                           top_ps, (bank, aids))

            return tick

        @functools.partial(jax.jit, donate_argnums=(1, 2, 4, 5))
        def tick(dparams, dkc, dvc, params, kc, vc, toks, pos, valid_n,
                 tables, dkd, kd, temps, top_ks, top_ps):
            return run(dparams, dkc, dvc, params, kc, vc, toks, pos,
                       valid_n, tables, dkd, kd, temps, top_ks, top_ps)

        return tick

    return _memo_build(("paged_spec_tick", cfg, draft_cfg, max_len,
                        block_size, spec_k, kernel, adapters), build)


# The memoized decode-path builders, by name — the single list the
# analyzer's program registry and host-side AST lint key off
# (analysis/programs.py enumerates these as compiled entry points;
# analysis/hostlint.py checks each definition routes through _memo_build
# and that no call site bypasses it).
DECODE_BUILDERS = {
    "make_cached_decoder": make_cached_decoder,
    "make_slot_prefill": make_slot_prefill,
    "make_slot_decode_step": make_slot_decode_step,
    "make_paged_prefill_chunk": make_paged_prefill_chunk,
    "make_paged_decode_step": make_paged_decode_step,
    "make_paged_block_copy": make_paged_block_copy,
    "make_adapter_bank_update": make_adapter_bank_update,
    "make_slot_propose": make_slot_propose,
    "make_slot_verify_step": make_slot_verify_step,
    "make_paged_verify_step": make_paged_verify_step,
    "make_slot_spec_tick": make_slot_spec_tick,
    "make_paged_spec_tick": make_paged_spec_tick,
}


def decoder_from_pipeline(pipe, cfg: GPTConfig, prompt_len: int, n_new: int,
                          temperature: float = 0.0, top_k: int | None = None,
                          top_p: float | None = None, cache_dtype=None):
    """Cached decode bound to a training :class:`~..parallel.pipeline.Pipeline`:
    returns ``decode(buf, prompt, key)`` taking the LIVE packed param buffer.

    The bridge from training to inference: no manual unpacking, no separate
    weight copy — checkpoint-restore or train, then decode from the same
    buffer. The buffer is gathered to host and re-split into stage trees per
    call (``Pipeline.unpack``), then the single-device KV-cache decoder runs
    on them; for a training run that decodes once per eval epoch this
    host-side gather is noise. Tensor-/expert-sharded stages are rejected
    (their trees are per-shard slices, not the whole model).
    """
    if any(s.shards is not None or s.expert_shards is not None
           for s in pipe.stages):
        raise ValueError(
            "decoder_from_pipeline needs unsharded stage params — gather "
            "tensor/expert shards into a dense build first")
    dec = make_cached_decoder(pipe.stages, cfg, prompt_len, n_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p, cache_dtype=cache_dtype)

    def decode(buf, prompt, key):
        return dec(pipe.unpack(buf), prompt, key)

    return decode


def make_decoder(stages, prompt_len: int, n_new: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None):
    """Build the jitted decode fn: ``decode(params, prompt, key) ->
    [B, prompt_len + n_new]`` tokens.

    Like the ``make_train_step`` pattern: build ONCE and reuse across calls
    to amortize the trace/compile (``generate`` is the one-shot convenience
    wrapper and rebuilds per call). Single-device composition only: stages
    from a ``cfg.n_seq > 1`` build use mesh collectives in their applies and
    cannot run here — decode with an ``n_seq=1`` build of the same weights.
    """
    from jax import lax

    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    if prompt_len < 1:
        raise ValueError(
            "generate needs a non-empty prompt (t0 >= 1): the first decoded "
            "token is conditioned on the prompt's last position")
    # vocab-bound validation of top_k happens at trace time in _filter_top
    # against the actual row width — no reach into the param layout here
    _check_sampling_args(temperature, top_k, top_p)
    # the stages are traced at a fixed sequence length (stage 0's in_shape);
    # decode inside that static buffer
    seq_len = int(stages[0].in_shape[0])
    if prompt_len + n_new > seq_len:
        raise ValueError(
            f"prompt {prompt_len} + n_new {n_new} exceeds the model's "
            f"sequence length {seq_len}")
    fused = fused_reference(stages)

    @jax.jit
    def decode(params, prompt, key):
        b = prompt.shape[0]
        buf = jnp.zeros((b, seq_len), jnp.int32)
        buf = lax.dynamic_update_slice_in_dim(
            buf, prompt.astype(jnp.int32), 0, 1)

        def step(carry, i):
            buf, k = carry
            logp = fused(params, buf.astype(jnp.float32), k, True)
            # prediction for position i comes from the read at i-1
            row = lax.dynamic_index_in_dim(logp, i - 1, 1, keepdims=False)
            tok, k = _sample_row(row, k, temperature, top_k, top_p)
            buf = lax.dynamic_update_slice_in_dim(
                buf, tok[:, None].astype(jnp.int32), i, 1)
            return (buf, k), None

        (buf, _), _ = lax.scan(step, (buf, key),
                               prompt_len + jnp.arange(n_new))
        return buf[:, :prompt_len + n_new]

    return decode
