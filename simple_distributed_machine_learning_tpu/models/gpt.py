"""Tiny GPT as pipeline stages (BASELINE.json config 5).

A decoder-only transformer LM — token+position embeddings, pre-LN blocks
(causal MHA + GELU MLP), final LN + untied head + log_softmax — expressed in
the same :class:`~..parallel.pipeline.Stage` form as MLP/LeNet, so the exact
GPipe/ppermute machinery that runs the reference's conv↔fc split also runs a
transformer with per-token next-token loss.

The reference has no attention or sequence models at all (SURVEY §5.7); this
is pure capability extension mandated by the driver's config 5 ("2-layer
tiny-GPT d=128, 2-stage pipeline with GPipe microbatching").

Wire notes: stage 0 consumes tokens (cast to float on the wire, exact for any
realistic vocab), emits the [T, d] hidden state; the last stage emits [T, V]
log-probs. The engine's per-token loss path (``Pipeline(out_dim=(T, V))``)
averages NLL over batch and sequence.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from simple_distributed_machine_learning_tpu.ops.attention import (
    causal_attention,
    mha_init,
)
from simple_distributed_machine_learning_tpu.ops.layers import (
    dropout,
    embedding_init,
    embedding_lookup,
    layer_norm,
    layer_norm_init,
    linear,
    linear_init,
)
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab: int = 128
    seq_len: int = 64
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    mlp_ratio: int = 4
    dropout_rate: float = 0.0   # tiny-GPT default: no dropout
    attn_impl: str = "dense"    # "dense" | "flash" (Pallas fused kernel)
    # MoE: n_experts > 0 replaces each block's MLP with a mixture-of-experts
    # FFN (top-k routed, see parallel/expert.py). Inside the pipeline the MoE
    # runs dense per stage with a generous capacity (the router's Switch aux
    # loss is exposed via expert.moe_apply for standalone use; the pipeline's
    # NLL-only loss path does not add it — acceptable at tiny expert counts).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0

    def __post_init__(self):
        if self.attn_impl not in ("dense", "flash"):
            raise ValueError(
                f"attn_impl must be 'dense' or 'flash', got {self.attn_impl!r}")
        if self.n_experts < 0 or (self.n_experts > 0 and not
                                  1 <= self.moe_top_k <= self.n_experts):
            raise ValueError(
                f"invalid MoE config: n_experts={self.n_experts}, "
                f"top_k={self.moe_top_k}")


def _block_init(key: jax.Array, cfg: GPTConfig) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    d, dh = cfg.d_model, cfg.mlp_ratio * cfg.d_model
    p = {
        "ln1": layer_norm_init(d),
        "attn": mha_init(k1, d, cfg.n_heads),
        "ln2": layer_norm_init(d),
    }
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            moe_init,
        )
        p["moe"] = moe_init(k2, d, dh, cfg.n_experts)
    else:
        p["mlp_in"] = linear_init(k2, d, dh)
        p["mlp_out"] = linear_init(k3, dh, d)
    return p


def _block_apply(params: dict, h: jax.Array, cfg: GPTConfig, key: jax.Array,
                 deterministic: bool) -> jax.Array:
    k1, k2 = jax.random.split(key)
    if cfg.attn_impl == "flash":
        from simple_distributed_machine_learning_tpu.ops.flash_attention import (
            flash_mha,
        )
        a = flash_mha(params["attn"], layer_norm(params["ln1"], h),
                      cfg.n_heads)
    else:
        a = causal_attention(params["attn"], layer_norm(params["ln1"], h),
                             cfg.n_heads)
    a = dropout(k1, a, cfg.dropout_rate, deterministic)
    h = h + a
    hn = layer_norm(params["ln2"], h)
    if cfg.n_experts > 0:
        from simple_distributed_machine_learning_tpu.parallel.expert import (
            default_capacity,
            moe_apply,
        )
        # route per sequence (vmap over batch): keeps the [T, E, C] dispatch
        # tensors at seq_len scale instead of batch*seq_len (C grows with the
        # routed group size, so global routing would cost O((B*T)^2/E))
        cap = default_capacity(hn.shape[1], cfg.n_experts, cfg.moe_top_k,
                               cfg.moe_capacity_factor)
        m, _aux = jax.vmap(
            lambda t: moe_apply(params["moe"], t, k=cfg.moe_top_k,
                                capacity=cap))(hn)
    else:
        m = linear(params["mlp_out"], jax.nn.gelu(linear(params["mlp_in"], hn)))
    m = dropout(k2, m, cfg.dropout_rate, deterministic)
    return h + m


def make_gpt_stages(key: jax.Array, cfg: GPTConfig = GPTConfig(),
                    n_stages: int = 2) -> tuple[list[Stage], int, tuple[int, int]]:
    """Build the GPT as ``n_stages`` pipeline stages.

    Blocks are split contiguously; stage 0 additionally owns the embeddings,
    the last stage owns the final LN + head. Returns
    ``(stages, wire_dim, (seq_len, vocab))`` — pass the tuple as the
    Pipeline's ``out_dim`` for the per-token loss.
    """
    if cfg.n_layers < n_stages and not (n_stages == 1 and cfg.n_layers == 0):
        raise ValueError(
            f"{cfg.n_layers} layers cannot fill {n_stages} stages")
    ke, kp, kh, *kb = jax.random.split(key, 3 + cfg.n_layers)
    embed = {"tok": embedding_init(ke, cfg.vocab, cfg.d_model),
             "pos": 0.02 * jax.random.normal(kp, (cfg.seq_len, cfg.d_model))}
    blocks = [_block_init(kb[i], cfg) for i in range(cfg.n_layers)]
    head = {"ln_f": layer_norm_init(cfg.d_model),
            "out": linear_init(kh, cfg.d_model, cfg.vocab)}

    per = [cfg.n_layers // n_stages + (1 if i < cfg.n_layers % n_stages else 0)
           for i in range(n_stages)]

    stages: list[Stage] = []
    start = 0
    for s in range(n_stages):
        stage_blocks = blocks[start:start + per[s]]
        first, last = s == 0, s == n_stages - 1
        params: dict = {"blocks": stage_blocks}
        if first:
            params["embed"] = embed
        if last:
            params["head"] = head

        def apply(params, x, key, deterministic,
                  _first=first, _last=last, _n=len(stage_blocks)):
            if _first:
                ids = x.astype(jnp.int32)                     # tokens on the wire
                h = (embedding_lookup(params["embed"]["tok"], ids)
                     + params["embed"]["pos"])
            else:
                h = x                                         # [B, T, d]
            for i in range(_n):
                h = _block_apply(params["blocks"][i], h, cfg,
                                 jax.random.fold_in(key, i), deterministic)
            if _last:
                h = layer_norm(params["head"]["ln_f"], h)
                return log_softmax(linear(params["head"]["out"], h))
            return h

        in_shape = (cfg.seq_len,) if first else (cfg.seq_len, cfg.d_model)
        stages.append(Stage(apply=apply, params=params, in_shape=in_shape))
        start += per[s]

    wire_dim = cfg.seq_len * max(cfg.d_model, cfg.vocab)
    return stages, wire_dim, (cfg.seq_len, cfg.vocab)
