"""MLP as a pipeline of linear(+relu) stages, ending in log_softmax.

This is BASELINE.json config 1/2/3: a 2-stage split (stage0=fc1, stage1=fc2)
generalized to N layers over S stages. It is the minimal end-to-end slice of
the framework (SURVEY §7) — same stage/wire machinery as LeNet and GPT, no
convs or attention.
"""

from __future__ import annotations

from typing import Sequence

import jax

from simple_distributed_machine_learning_tpu.ops.layers import linear, linear_init, relu
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage


def make_mlp_stages(key: jax.Array, dims: Sequence[int], n_stages: int
                    ) -> tuple[list[Stage], int, int]:
    """Build an MLP ``dims[0] -> ... -> dims[-1]`` split into ``n_stages``.

    Layers are assigned contiguously to stages (earlier stages take the
    remainder). Hidden activations are relu; the final layer ends in
    log_softmax (matching the reference model family's output convention,
    ``/root/reference/simple_distributed.py:79``).

    Returns ``(stages, wire_dim, out_dim)``.
    """
    n_layers = len(dims) - 1
    if n_layers < n_stages:
        raise ValueError(f"{n_layers} layers cannot fill {n_stages} stages")
    keys = jax.random.split(key, n_layers)
    layer_params = [linear_init(keys[i], dims[i], dims[i + 1])
                    for i in range(n_layers)]
    from simple_distributed_machine_learning_tpu.parallel.staging import (
        contiguous_split,
    )
    split = contiguous_split(layer_params, n_stages)

    stages: list[Stage] = []
    start = 0
    for s in range(n_stages):
        params = split[s]
        is_last = s == n_stages - 1

        def apply(params, x, key, deterministic,
                  _n=len(params), _last=is_last):
            h = x
            for i, p in enumerate(params):
                h = linear(p, h)
                if i < _n - 1 or not _last:
                    h = relu(h)
            return log_softmax(h) if _last else h

        stages.append(Stage(apply=apply, params=params,
                            in_shape=(dims[start],)))
        start += len(params)

    wire_dim = max(dims)
    return stages, wire_dim, dims[-1]
