"""Model zoo, expressed as pipeline stages (see ``parallel.pipeline.Stage``).

Scope per BASELINE.json configs: N-layer MLPs (2- and 4-stage pipelines),
LeNet with the reference's conv↔fc split, and a tiny GPT with GPipe
microbatching.
"""

from simple_distributed_machine_learning_tpu.models.beam import (  # noqa: F401
    make_beam_decoder,
)
from simple_distributed_machine_learning_tpu.models.gpt import (  # noqa: F401
    GPTConfig,
    decoder_from_pipeline,
    generate,
    make_cached_decoder,
    make_decoder,
    make_gpt_stages,
    make_slot_decode_step,
    make_slot_prefill,
)
from simple_distributed_machine_learning_tpu.models.lenet import (  # noqa: F401
    make_lenet_stages,
)
from simple_distributed_machine_learning_tpu.models.pp_decode import (  # noqa: F401
    make_pp_decoder,
)
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages  # noqa: F401
