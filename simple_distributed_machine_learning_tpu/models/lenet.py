"""LeNet with the reference's exact conv↔fc pipeline split.

Stage 0 is the reference's ``Network1`` spec — conv(1→10,k5) → maxpool2 → relu;
conv(10→20,k5) → dropout2d → maxpool2 → relu → flatten-to-320
(``/root/reference/simple_distributed.py:42-46``). Stage 1 is ``Network2`` —
fc(320→50) → relu → dropout → fc(50→10) → log_softmax (``:75-79``).

Differences by design (not oversights):
- activations are NHWC (TPU MXU layout), so the 320-feature flatten interleaves
  (H, W, C) rather than torch's (C, H, W) — a fixed permutation of the same
  features, irrelevant to learning dynamics;
- dropout takes explicit keys and honours ``deterministic`` — the reference's
  eval keeps worker-side dropout active (``:75`` vs ``:120``; SURVEY §3.5 rules
  this a quirk not to carry over).

``n_stages=1`` returns the fused single-device LeNet (for parity baselines);
``n_stages=2`` is the reference topology (BASELINE.json config 4).
"""

from __future__ import annotations

import jax

from simple_distributed_machine_learning_tpu.ops.layers import (
    conv2d,
    conv2d_init,
    dropout,
    dropout2d,
    linear,
    linear_init,
    max_pool2d,
    relu,
)
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.pipeline import Stage

IN_SHAPE = (28, 28, 1)   # NHWC per-sample
FEATURES = 320           # 20 channels * 4 * 4 after two conv/pool blocks
N_CLASSES = 10


def _conv_apply(params, x, key, deterministic):
    h = relu(max_pool2d(conv2d(params["conv1"], x), 2))
    h = conv2d(params["conv2"], h)
    h = dropout2d(key, h, rate=0.5, deterministic=deterministic)
    h = relu(max_pool2d(h, 2))
    return h.reshape(h.shape[0], FEATURES)


def _fc_apply(params, x, key, deterministic):
    h = relu(linear(params["fc1"], x))
    h = dropout(key, h, rate=0.5, deterministic=deterministic)
    h = linear(params["fc2"], h)
    return log_softmax(h)


def make_lenet_stages(key: jax.Array, n_stages: int = 2
                      ) -> tuple[list[Stage], int, int]:
    """Build LeNet as pipeline stages. Returns ``(stages, wire_dim, out_dim)``."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    conv_params = {"conv1": conv2d_init(k1, 1, 10, 5),
                   "conv2": conv2d_init(k2, 10, 20, 5)}
    fc_params = {"fc1": linear_init(k3, FEATURES, 50),
                 "fc2": linear_init(k4, 50, N_CLASSES)}
    wire_dim = max(28 * 28 * 1, FEATURES, N_CLASSES)  # input image is widest

    if n_stages == 2:
        stages = [
            Stage(apply=_conv_apply, params=conv_params, in_shape=IN_SHAPE),
            Stage(apply=_fc_apply, params=fc_params, in_shape=(FEATURES,)),
        ]
    elif n_stages == 1:
        def fused(params, x, key, deterministic):
            kc, kf = jax.random.split(key)
            h = _conv_apply(params["conv"], x, kc, deterministic)
            return _fc_apply(params["fc"], h, kf, deterministic)
        stages = [Stage(apply=fused,
                        params={"conv": conv_params, "fc": fc_params},
                        in_shape=IN_SHAPE)]
    else:
        raise ValueError(f"LeNet supports 1 or 2 stages, got {n_stages}")
    return stages, wire_dim, N_CLASSES
