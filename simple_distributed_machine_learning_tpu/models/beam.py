"""Beam-search decoding on the KV-cache infrastructure.

``make_beam_decoder(stages, cfg, prompt_len, n_new, beam_size)`` returns
``decode(params, prompt, key) -> (tokens [B, total], scores [B])``: the
highest-cumulative-log-prob continuation among ``beam_size`` beams per
sequence, decoded with the same static-shape per-layer K/V caches as
:func:`~.gpt.make_cached_decoder` (one prefill, one token per step; beams
ride the batch axis as ``B*K`` rows, and each step's beam reordering gathers
the cache rows along it).

Scoring is the plain sum of token log-probs over the generated suffix (no
length normalization — all beams have the same fixed length here, so
normalization would not change the argmax). ``beam_size=1`` is exactly
greedy decoding (pinned in tests/test_beam.py).

EOS termination (``eos_id``): a beam that emits ``eos_id`` is *finished* —
its score freezes at the log-prob of its sequence up to and including EOS,
and its only continuation is EOS itself at log-prob 0, so it rides the
remaining (static-length) scan as an eos-padded row competing on its frozen
score. The returned tokens are therefore eos-padded after the first EOS and
the score is the finished prefix's, the standard fixed-shape beam-EOS
treatment.

The reference has no inference path at all
(``/root/reference/simple_distributed.py:119-132`` is eval-only); greedy /
sampled (top-k/top-p) / beam decoding are capability extensions completing
the standard decode suite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    _cache_dtype,
    _dense_block_prefill,
    _dense_block_step,
    _head_logprobs,
    _merged_stage_trees,
    _validate_decode_build,
)
from simple_distributed_machine_learning_tpu.ops.layers import (
    embedding_lookup,
)


def make_beam_decoder(stages, cfg: GPTConfig, prompt_len: int, n_new: int,
                      beam_size: int = 4, cache_dtype=None,
                      eos_id: int | None = None):
    """Build the jitted beam decoder. Single-device dense builds only (the
    :func:`~.gpt.make_cached_decoder` restrictions; ``cache_dtype`` as there
    — bf16 halves the K*B beam-cache memory). ``eos_id``: beams finishing on
    this token freeze their score and eos-pad (module docstring)."""
    if cfg.n_seq > 1:
        raise ValueError(
            "beam decode is single-device; rebuild the stages with n_seq=1")
    if not 1 <= beam_size <= cfg.vocab:
        raise ValueError(
            f"beam_size={beam_size} out of range [1, vocab={cfg.vocab}]")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(
            f"eos_id={eos_id} outside [0, vocab={cfg.vocab})")
    total = _validate_decode_build(stages, cfg, prompt_len, n_new,
                                   "make_beam_decoder")
    K = beam_size
    H, d = cfg.n_heads, cfg.d_model
    dh = d // H
    V = cfg.vocab
    cd = _cache_dtype(cache_dtype)

    @jax.jit
    def decode(params, prompt, key):
        del key                                  # beam search is deterministic
        embed, blocks, head = _merged_stage_trees(params)
        b = prompt.shape[0]
        L = len(blocks)

        # ---- prefill at batch B (beams share the prompt prefix)
        kc = jnp.zeros((L, b, H, total, dh), cd)
        vc = jnp.zeros((L, b, H, total, dh), cd)
        ids = prompt.astype(jnp.int32)
        h = embedding_lookup(embed["tok"], ids) + embed["pos"][:prompt_len]
        for li, bp in enumerate(blocks):
            h, kc, vc = _dense_block_prefill(bp, h, li, kc, vc,
                                             prompt_len, H)
        row = _head_logprobs(head, h[:, -1])                     # [B, V]

        # ---- beam init: top-K first tokens; caches tile to B*K rows
        # (beam-major within each sequence: row index = b*K + k)
        s0, t0 = lax.top_k(row, K)                          # [B, K] each
        scores = s0
        toks = jnp.zeros((b, K, n_new), jnp.int32)
        toks = toks.at[:, :, 0].set(t0)
        kc = jnp.repeat(kc, K, axis=1)                      # [L, B*K, ...]
        vc = jnp.repeat(vc, K, axis=1)
        done = (t0 == eos_id) if eos_id is not None else jnp.zeros((b, K),
                                                                   bool)

        def step(carry, i):
            kc, vc, toks, scores, done = carry
            # last chosen token of every beam enters at position i-? — the
            # token written at step j sits at buffer col j and global
            # position prompt_len + j; at loop index i we consume col i-1
            tok_in = lax.dynamic_index_in_dim(toks, i - 1, 2,
                                              keepdims=False)  # [B, K]
            pos_i = prompt_len + i - 1          # its global position
            pos = lax.dynamic_slice_in_dim(embed["pos"], pos_i, 1, 0)
            h = (embedding_lookup(embed["tok"],
                                  tok_in.reshape(b * K)[:, None]) + pos)
            for li, bp in enumerate(blocks):
                h, kc, vc = _dense_block_step(bp, h, li, kc, vc, pos_i,
                                              total, H)
            row = _head_logprobs(head, h[:, 0]).reshape(b, K, V)
            if eos_id is not None:
                # finished beams: only continuation is EOS at log-prob 0 —
                # the beam rides the rest of the scan on its frozen score
                pad = jnp.full((V,), -jnp.inf).at[eos_id].set(0.0)
                row = jnp.where(done[:, :, None], pad[None, None, :], row)
            cand = scores[:, :, None] + row                 # [B, K, V]
            scores, flat = lax.top_k(cand.reshape(b, K * V), K)
            beam_idx = flat // V                            # [B, K]
            new_tok = flat % V
            # reorder every beam-indexed structure by its source beam
            def regather(x):                                # [L, B*K, ...]
                xr = x.reshape((L, b, K) + x.shape[2:])
                xr = jnp.take_along_axis(
                    xr, beam_idx[None, :, :, None, None, None], axis=2)
                return xr.reshape((L, b * K) + x.shape[2:])
            kc = regather(kc)
            vc = regather(vc)
            toks = jnp.take_along_axis(toks, beam_idx[:, :, None], axis=1)
            toks = lax.dynamic_update_index_in_dim(
                toks, new_tok, i, 2)
            if eos_id is not None:
                done = (jnp.take_along_axis(done, beam_idx, axis=1)
                        | (new_tok == eos_id))
            return (kc, vc, toks, scores, done), None

        if n_new > 1:
            (kc, vc, toks, scores, done), _ = lax.scan(
                step, (kc, vc, toks, scores, done), 1 + jnp.arange(n_new - 1))
        best = jnp.argmax(scores, axis=1)                   # [B]
        best_toks = jnp.take_along_axis(
            toks, best[:, None, None], axis=1)[:, 0]        # [B, n_new]
        out = jnp.concatenate([prompt.astype(jnp.int32), best_toks], axis=1)
        return out, jnp.take_along_axis(scores, best[:, None], axis=1)[:, 0]

    return decode
