"""Pipeline-parallel KV-cache decoding: inference that scales like training.

``make_pp_decoder(pipe, cfg, ...)`` returns ``decode(buf, prompt, key)``
running UNDER ``shard_map`` on the training mesh: each stage device keeps its
packed param row and a KV cache for ITS OWN blocks only (inference memory
shards with the model, like training), and the single-token hidden state
relays across stages over the same ``lax.ppermute`` stage ring the trainer
uses. One compiled program decodes ``n_new`` tokens; the data axis shards the
batch exactly as in training.

Why this exists: the single-device decoders (``make_cached_decoder``,
``decoder_from_pipeline``) gather the whole model onto one chip — fine until
the model only exists stage-sharded. This decoder never gathers: a model
that trains at S stages decodes at S stages, straight from the live packed
buffer. Parity with the single-device cached decoder is exact (same math,
same key stream; tests/test_pp_decode.py).

Schedule note: single-sequence-batch decoding through a pipeline has an
inherent S-tick latency per token (the hidden state must cross every stage);
each tick moves one [B, d] vector over ICI. Inactive stages' per-tick
compute is predicated out value-wise (``jnp.where``) — at one token per
tick the redundant FLOPs are negligible next to the HBM-resident weights.

Scope: dense blocks (no MoE), n_seq == n_model == n_expert == 1; the data
axis may be > 1 (prompt/batch shard over it). The reference has no inference
path at all (``/root/reference/simple_distributed.py:119-132`` is eval-only).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    _cache_dtype,
    _check_sampling_args,
    _dense_block_prefill,
    _dense_block_step,
    _sample_from,
    _validate_decode_build,
)
from simple_distributed_machine_learning_tpu.ops.layers import (
    embedding_lookup,
    layer_norm,
    linear,
)
from simple_distributed_machine_learning_tpu.ops.losses import log_softmax
from simple_distributed_machine_learning_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
)
from simple_distributed_machine_learning_tpu.parallel.compat import (
    pvary_to as _pvary_to,
    shard_map as _shard_map,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    unpack_stage_params,
)


def make_pp_decoder(pipe, cfg: GPTConfig, prompt_len: int, n_new: int,
                    temperature: float = 0.0, top_k: int | None = None,
                    top_p: float | None = None, cache_dtype=None):
    """Build ``decode(buf, prompt, key) -> [B, prompt_len + n_new]`` tokens,
    stage-sharded end to end. ``buf`` is the pipeline's packed param buffer
    (the live training state); ``prompt``: [B, prompt_len] int tokens with
    ``B`` divisible by the mesh's data axis."""
    if pipe.n_seq != 1 or pipe.n_model != 1 or pipe.n_expert != 1:
        raise ValueError(
            "make_pp_decoder shards over stage (x data) only — rebuild "
            "without seq/model/expert axes for decoding")
    _check_sampling_args(temperature, top_k, top_p, cfg.vocab)
    total = _validate_decode_build(pipe.stages, cfg, prompt_len, n_new,
                                   "make_pp_decoder")

    S = pipe.n_stages
    metas = list(pipe.metas)
    H, d = cfg.n_heads, cfg.d_model
    dh = d // H
    # per-stage block counts come from the stage param trees ("blocks" key);
    # caches are padded to the deepest stage so every device runs one program
    n_blocks = [len(pipe.stages[s].params["blocks"]) for s in range(S)]
    L_max = max(n_blocks)
    has_embed = [("embed" in pipe.stages[s].params) for s in range(S)]
    has_head = [("head" in pipe.stages[s].params) for s in range(S)]
    if not (has_embed[0] and has_head[-1]):
        raise ValueError("stage 0 must own 'embed' and the last stage "
                         "'head' (the make_gpt_stages layout)")
    # the packed row is typed varying over stage AND the (size-1) model/
    # expert axes its sharding names — the anchors must match that type
    vary = (DATA_AXIS, STAGE_AXIS, MODEL_AXIS, EXPERT_AXIS)

    def _head_row(params, h_last):
        return log_softmax(linear(params["head"]["out"],
                                  layer_norm(params["head"]["ln_f"], h_last)))

    def _pick(row, ks):
        """ks: the per-token subkey (split uniformly on every device, so
        the stream matches make_cached_decoder's exactly); the sampling
        math itself is gpt.py's shared _sample_from."""
        return _sample_from(row, ks, temperature, top_k, top_p)

    fwd = [(i, (i + 1) % S) for i in range(S)]

    # cache_dtype: as make_cached_decoder (bf16 halves each stage's cache)
    cd = _cache_dtype(cache_dtype)

    def per_device(row4d, prompt, key):
        row = row4d[0, 0, 0]
        stage = lax.axis_index(STAGE_AXIS)
        b = prompt.shape[0]
        kc = jnp.zeros((L_max, b, H, total, dh), cd)
        vc = jnp.zeros((L_max, b, H, total, dh), cd)
        kc = _pvary_to(kc, vary)
        vc = _pvary_to(vc, vary)

        # ---- prefill relay: S ticks; the wire carries the [b, T0, d]
        # hidden state plus one token slot (the last stage writes the first
        # sampled token there; the final ring hop lands it on stage 0)
        def prefill_branch(s):
            def br(wire, kc, vc, ks):
                params = unpack_stage_params(row, metas[s])
                if s == 0:
                    ids = prompt.astype(jnp.int32)
                    h = (embedding_lookup(params["embed"]["tok"], ids)
                         + params["embed"]["pos"][:prompt_len])
                else:
                    h = wire[:, :-1].reshape(b, prompt_len, d)
                for li in range(n_blocks[s]):
                    h, kc, vc = _dense_block_prefill(params["blocks"][li],
                                                     h, li, kc, vc,
                                                     prompt_len, H)
                tok = jnp.zeros((b,), jnp.float32)
                if s == S - 1:
                    tok = _pick(_head_row(params, h[:, -1]), ks).astype(
                        jnp.float32)
                out = jnp.concatenate([h.reshape(b, prompt_len * d),
                                       tok[:, None]], axis=1)
                anchor = _pvary_to(jnp.float32(0.0) * (jnp.sum(wire)
                                                       + jnp.sum(row)), vary)
                return (_pvary_to(out, vary) + anchor,
                        jax.tree.map(lambda a: (_pvary_to(a, vary)
                                                + anchor.astype(a.dtype)),
                                     (kc, vc)))
            return br

        pre_branches = [prefill_branch(s) for s in range(S)]

        # key discipline = make_cached_decoder's: exactly ONE split per
        # sampled token, performed identically on every device (replicated
        # key stream). The prefill consumes one (the first token).
        key0 = _pvary_to(key, vary)
        if temperature > 0.0:
            key1, ks0 = jax.random.split(key0)
        else:
            key1, ks0 = key0, key0

        def pre_tick(carry, t):
            wire, kc, vc = carry
            out, (kc2, vc2) = lax.switch(stage, pre_branches, wire, kc, vc,
                                         ks0)
            active = stage == t
            wire = jnp.where(active, out, wire)
            kc = jnp.where(active, kc2, kc)
            vc = jnp.where(active, vc2, vc)
            wire = lax.ppermute(wire, STAGE_AXIS, fwd)
            return (wire, kc, vc), None

        wire0 = _pvary_to(jnp.zeros((b, prompt_len * d + 1), jnp.float32),
                          vary)
        (wire, kc, vc), _ = lax.scan(
            pre_tick, (wire0, kc, vc), jnp.arange(S))

        # ---- decode relay: for each position i the [b, d+1] wire makes S
        # ticks; stage 0 reads the token slot, the last stage writes the
        # next sampled token into it, and the wrap-around hop returns it
        def decode_branch(s):
            def br(wire, kc, vc, i, ks):
                params = unpack_stage_params(row, metas[s])
                if s == 0:
                    tok = wire[:, -1].astype(jnp.int32)
                    pos = lax.dynamic_slice_in_dim(params["embed"]["pos"],
                                                   i, 1, 0)
                    h = embedding_lookup(params["embed"]["tok"],
                                         tok[:, None]) + pos
                else:
                    h = wire[:, :-1].reshape(b, 1, d)
                for li in range(n_blocks[s]):
                    h, kc, vc = _dense_block_step(params["blocks"][li], h,
                                                  li, kc, vc, i, total, H)
                tok_out = jnp.zeros((b,), jnp.float32)
                if s == S - 1:
                    tok_out = _pick(_head_row(params, h[:, 0]), ks).astype(
                        jnp.float32)
                out = jnp.concatenate([h.reshape(b, d), tok_out[:, None]],
                                      axis=1)
                anchor = _pvary_to(jnp.float32(0.0) * (jnp.sum(wire)
                                                       + jnp.sum(row)), vary)
                return (_pvary_to(out, vary) + anchor,
                        jax.tree.map(lambda a: (_pvary_to(a, vary)
                                                + anchor.astype(a.dtype)),
                                     (kc, vc)))
            return br

        dec_branches = [decode_branch(s) for s in range(S)]

        def outer(carry, i):
            wire, kc, vc, key = carry
            # one key split per generated token (the cached decoder's
            # stream); every device splits identically
            if temperature > 0.0:
                key, ks = jax.random.split(key)
            else:
                ks = key
            # the token being consumed at position i sits in stage 0's slot
            tok_in = lax.psum(
                jnp.where(stage == 0, wire[:, -1], jnp.zeros((b,))),
                STAGE_AXIS)

            def tick(dc, t):
                wire, kc, vc = dc
                out, (kc2, vc2) = lax.switch(stage, dec_branches, wire, kc,
                                             vc, i, ks)
                active = stage == t
                wire = jnp.where(active, out, wire)
                kc = jnp.where(active, kc2, kc)
                vc = jnp.where(active, vc2, vc)
                wire = lax.ppermute(wire, STAGE_AXIS, fwd)
                return (wire, kc, vc), None

            (wire, kc, vc), _ = lax.scan(tick, (wire, kc, vc),
                                         jnp.arange(S))
            return (wire, kc, vc, key), tok_in

        # seed the decode wire: only the token slot matters and the prefill
        # left the first sampled token on stage 0's slot
        dec_wire = jnp.concatenate(
            [jnp.zeros((b, d), jnp.float32), wire[:, -1:]], axis=1)
        (wire, _, _, _), toks = lax.scan(
            outer, (_pvary_to(dec_wire, vary), kc, vc, key1),
            prompt_len + jnp.arange(n_new - 1))
        last = lax.psum(
            jnp.where(stage == 0, wire[:, -1], jnp.zeros((b,))), STAGE_AXIS)
        out = jnp.concatenate(
            [prompt.astype(jnp.int32),
             jnp.moveaxis(toks, 0, 1).astype(jnp.int32),
             last[:, None].astype(jnp.int32)], axis=1)
        # replication proof for the (size-1, anchor-typed) model/expert
        # axes: psum over a size-1 axis is the identity value-wise and
        # types the output invariant for the out_spec
        return lax.psum(lax.psum(out, MODEL_AXIS), EXPERT_AXIS)

    fn = _shard_map(
        per_device,
        mesh=pipe.mesh,
        in_specs=(pipe.param_spec(), P(DATA_AXIS), P()),
        out_specs=P(DATA_AXIS),
    )

    @jax.jit
    def decode(buf, prompt, key):
        if prompt.shape[1] != prompt_len:
            raise ValueError(
                f"prompt length {prompt.shape[1]} != built {prompt_len}")
        return fn(buf, prompt, key)

    return decode
