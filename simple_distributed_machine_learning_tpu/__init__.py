"""simple_distributed_machine_learning_tpu — a TPU-native distributed training framework.

A brand-new, SPMD-first rebuild of the capabilities of
``maduc238/simple_distributed_machine_learning`` (a 2-process pipeline-model-parallel
trainer built on torch.distributed.rpc; see ``/root/reference/simple_distributed.py``):

- the reference's TensorPipe RPC bootstrap (``simple_distributed.py:167-186``) becomes
  :func:`jax.distributed.initialize` behind the same CLI (``cli.py``);
- its blocking activation/gradient RPC hops (``simple_distributed.py:49,:112``) become
  ``lax.ppermute`` collective-permutes over ICI inside a single compiled step
  (``parallel/pipeline.py``);
- its DistributedOptimizer owner-local SGD (``simple_distributed.py:100-104,:113``)
  becomes sharding-local updates on a stage-sharded parameter buffer
  (``train/optimizer.py``);
- its master/worker MPMD layout becomes one SPMD program over a
  ``jax.sharding.Mesh`` with ``(data, stage)`` axes (``parallel/mesh.py``).

Subpackages
-----------
``ops``       functional NN kernels (conv/pool/linear/dropout/losses, attention)
``parallel``  mesh construction, collectives, the pipeline engine (GPipe schedule)
``models``    MLP / LeNet / tiny-GPT expressed as pipeline stages
``train``     optimizers, train/eval driver, checkpointing
``data``      MNIST (IDX files or synthetic fallback), batching
``utils``     metrics, profiling, failure detection (heartbeat watchdog)
"""

__version__ = "0.1.0"

__all__ = ["make_mesh"]


def __getattr__(name: str):
    # Lazy (PEP 562) so jax-free tooling — analysis.hostlint, the watchdog
    # monitor — can import subpackages without pulling jax through here.
    if name == "make_mesh":
        from simple_distributed_machine_learning_tpu.parallel.mesh import (
            make_mesh,
        )
        return make_mesh
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
