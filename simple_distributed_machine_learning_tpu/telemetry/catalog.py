"""The metric-help catalog: ``# HELP`` text sourced from module docstrings.

The instruments this repo emits are already documented — ``serve/metrics.py``'s
module docstring is a maintained per-metric catalog in the

    - ``name`` (kind) — description

bullet format. Rather than duplicating every description into a second
hand-maintained table (which would drift), this module parses those
docstrings into a ``name -> help`` map that
:meth:`~.registry.MetricsRegistry.prometheus_text` turns into ``# HELP``
lines. Parsing happens on the SOURCE file via ``ast`` — no import of the
documented module, so the exposition path never drags ``serve/`` (and with
it jax) into a light context.

Training-side metrics whose docs live in prose rather than bullets get
explicit entries in :data:`EXTRA_HELP`. Coverage is best-effort by design:
a metric without catalog text simply emits no HELP line (never a wrong
one).
"""

from __future__ import annotations

import ast
import os
import re

# serve/metrics.py documents every serve_* instrument; the SLO engine and
# the attribution module document their own instruments in their module
# docstrings (same bullet grammar). Parsed lazily once.
_DOC_FILES = (("serve", "metrics.py"), ("telemetry", "slo.py"),
              ("telemetry", "attribution.py"))

#: metrics documented in prose (trainer / session / bench paths) rather
#: than catalog bullets — the explicit side of the catalog.
EXTRA_HELP: dict[str, str] = {
    "epochs_total": "training epochs completed by this session",
    "bubble_fraction": "modeled pipeline-bubble fraction "
                       "(S-1)/(M+S-1) of the schedule that ran",
    "bubble_fraction_measured": "measured pipeline-bubble fraction: "
                                "1 - ideal_step_s / steady p50 step time",
    "bubble_drift": "measured minus modeled pipeline-bubble fraction "
                    "(0 when the schedule model holds)",
    "examples_per_sec": "steady-state training throughput in examples/s",
    "tokens_per_sec": "steady-state training throughput in tokens/s",
    "step_time_ms": "per-step wall latency from fenced timing windows",
    "ici_bytes_per_step": "statically expected collective bytes per step "
                          "over the interconnect",
    # self-healing training (resilience/sentinel.py)
    "train_anomalies_total": "numeric anomalies the training sentinel "
                             "detected, by verdict kind (nan/inf/spike)",
    "train_rollbacks_total": "in-memory micro-rollbacks to a snapshot-ring "
                             "entry (no disk restore)",
    "train_quarantined_batches_total": "batches journaled as quarantined "
                                       "and deterministically skipped",
    "train_snapshot_ring_bytes": "resident host bytes of the sentinel's "
                                 "bounded snapshot ring",
    "train_preempt_graceful": "1 when the run ended on a graceful "
                              "preemption (SIGTERM): in-flight step "
                              "finished, synchronous checkpoint + "
                              "quarantine-journal flush",
}

_NAME_RE = re.compile(r"``([A-Za-z_][A-Za-z0-9_]*)(?:\{[^`]*\})?``")
_cached: dict[str, str] | None = None


def _bullets(doc: str):
    """Yield the ``- ...`` bullet chunks of a docstring (a bullet runs to
    the next bullet or blank line)."""
    chunk: list[str] = []
    for line in doc.splitlines():
        stripped = line.strip()
        if stripped.startswith("- "):
            if chunk:
                yield " ".join(chunk)
            chunk = [stripped[2:]]
        elif chunk and stripped:
            chunk.append(stripped)
        elif chunk:
            yield " ".join(chunk)
            chunk = []
    if chunk:
        yield " ".join(chunk)


def _parse_doc(doc: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for bullet in _bullets(doc):
        head, sep, help_text = bullet.partition("—")
        if not sep:
            continue
        help_text = " ".join(help_text.split()).strip()
        if not help_text:
            continue
        for name in _NAME_RE.findall(head):
            out.setdefault(name, help_text)
    return out


def metric_help() -> dict[str, str]:
    """The merged ``metric name -> help text`` catalog (cached)."""
    global _cached
    if _cached is None:
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        merged = dict(EXTRA_HELP)
        for parts in _DOC_FILES:
            path = os.path.join(pkg_root, *parts)
            try:
                with open(path) as f:
                    doc = ast.get_docstring(ast.parse(f.read())) or ""
            except (OSError, SyntaxError):  # pragma: no cover - env guard
                continue
            for name, text in _parse_doc(doc).items():
                merged.setdefault(name, text)
        _cached = merged
    return _cached
