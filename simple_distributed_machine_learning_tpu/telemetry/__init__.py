"""Structured metrics, tracing & step profiling for every run path.

The observability layer the reference (one ``print`` per 2000 batches) never
had: a per-run :class:`Telemetry` session that the trainer, CLI and bench
harness thread through. Module map:

- ``registry.py`` — :class:`MetricsRegistry`: labeled counters / gauges /
  histograms, JSONL snapshots, Prometheus text exposition;
- ``timer.py`` — :class:`StepTimer`: fenced timing windows with the
  compile-vs-steady split, p50/p95/max step latency, examples/sec and
  tokens/sec (+ opt-in ``jax.stages`` compiled cost stats);
- ``tracing.py`` — :class:`Tracer`: host spans with wall-clock durations,
  exported as Chrome-trace JSON (inspectable without XProf; doubles onto the
  XProf timeline via ``utils/profiler.annotate`` when capturing);
- ``memory.py`` — ``jax.live_arrays()`` byte totals + per-device
  ``memory_stats()`` sampling;
- ``ici.py`` — static expected collective bytes/step, read-only reuse of
  ``analysis``'s bytes-over-ICI cost table;
- ``bubble.py`` — the GPipe / 1F1B pipeline-bubble schedule model, plus
  measured-vs-modeled drift helpers (``measured_bubble_fraction``,
  ``bubble_drift``);
- ``session.py`` — :class:`Telemetry`, the orchestrator (``metrics.jsonl``,
  ``trace.json``, ``metrics.prom`` under one directory);
- ``catalog.py`` — the docstring-sourced metric-help catalog behind the
  Prometheus exposition's ``# HELP`` lines (source-parsed via ``ast``, no
  heavy imports);
- ``report.py`` — the stdlib-only run-report CLI: ``python -m
  simple_distributed_machine_learning_tpu.telemetry.report --dir DIR``
  renders per-class attainment, shed breakdown, restart timeline,
  latency quantiles, drift gauges and post-mortem bundles from a
  telemetry directory.

The serving twin lives in ``serve/tracing.py`` (request-scoped async span
timelines on this module's :class:`Tracer` async-event support) and
``serve/flight.py`` (tick flight recorder + post-mortem bundles).

Entry points: ``Trainer(..., telemetry=Telemetry(dir))``, ``cli.py
--telemetry-dir DIR [--telemetry-every N]``, and ``bench.py`` rows (step-time
quantiles + ``bubble_fraction`` ride every result row unconditionally).
"""

from __future__ import annotations

from simple_distributed_machine_learning_tpu.telemetry.bubble import (
    ideal_step_time,
    schedule_bubble_fraction,
)
from simple_distributed_machine_learning_tpu.telemetry.ici import (
    expected_ici_bytes,
)
from simple_distributed_machine_learning_tpu.telemetry.memory import (
    device_memory_stats,
    live_array_bytes,
)
from simple_distributed_machine_learning_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    append_jsonl,
)
from simple_distributed_machine_learning_tpu.telemetry.session import (
    METRICS_FILE,
    PROM_FILE,
    TRACE_FILE,
    Telemetry,
)
from simple_distributed_machine_learning_tpu.telemetry.timer import (
    StepTimer,
    compiled_cost_stats,
)
from simple_distributed_machine_learning_tpu.telemetry.tracing import Tracer

__all__ = [
    "METRICS_FILE", "PROM_FILE", "TRACE_FILE",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "StepTimer",
    "Telemetry", "Tracer", "append_jsonl", "compiled_cost_stats",
    "device_memory_stats", "expected_ici_bytes", "ideal_step_time",
    "live_array_bytes", "schedule_bubble_fraction",
]
