"""The per-run telemetry session: registry + tracer + step timer, wired.

:class:`Telemetry` is the object the trainer/CLI/bench thread through: it
owns one :class:`MetricsRegistry`, one :class:`Tracer` and one
:class:`StepTimer`, drives the fence-every-N-steps sampling discipline, and
emits per-epoch records to ``<dir>/metrics.jsonl`` plus the Chrome trace
(``trace.json``) and Prometheus exposition (``metrics.prom``) — rewritten at
every epoch so the artifacts exist and parse mid-run, not only after a clean
exit.

Sampling discipline (``every``): fencing the device every step serializes
dispatch with execution — correct timing, but it forfeits the async-dispatch
overlap the engine is built around. ``every=N`` fences only every Nth step
and attributes the window to all N steps (a weighted histogram observation),
so steady-state telemetry costs one pipeline drain per N steps. ``every=1``
(the default) is exact per-step latency.

Multi-process runs: every process records (spans and timers are host-local),
only process 0 writes files — same rule as the reference-format console.
"""

from __future__ import annotations

import os
import time

from simple_distributed_machine_learning_tpu.telemetry import memory
from simple_distributed_machine_learning_tpu.telemetry.bubble import (
    schedule_bubble_fraction,
)
from simple_distributed_machine_learning_tpu.telemetry.registry import (
    MetricsRegistry,
    append_jsonl,
)
from simple_distributed_machine_learning_tpu.telemetry.timer import StepTimer
from simple_distributed_machine_learning_tpu.telemetry.tracing import Tracer

METRICS_FILE = "metrics.jsonl"
TRACE_FILE = "trace.json"
PROM_FILE = "metrics.prom"


class Telemetry:
    """One training/bench run's telemetry session; see module docstring."""

    def __init__(self, outdir: str, every: int = 1,
                 process_name: str = "sdml") -> None:
        if every < 1:
            raise ValueError(f"telemetry every={every}: must be >= 1")
        self.outdir = outdir
        self.every = int(every)
        self.registry = MetricsRegistry()
        self.tracer = Tracer(process_name=process_name)
        self.timer = StepTimer(registry=self.registry)
        self._steps_seen = 0
        self._mark = time.perf_counter()
        self._win_steps = 0
        self._win_examples = 0.0
        self._win_tokens = 0.0
        self._probe = None          # (fn, args, kwargs, mesh, steps) thunk args
        self._ici_info = None
        self._ici_done = False
        self._ideal_step_s = None   # bubble-free reference (set_bubble_reference)
        if self._is_main():
            os.makedirs(outdir, exist_ok=True)

    @staticmethod
    def _is_main() -> bool:
        import jax
        try:
            return jax.process_index() == 0
        except Exception:  # noqa: BLE001 - before distributed init
            return True

    # -- spans -------------------------------------------------------------

    def span(self, name: str, **attrs):
        """Host span: on the Chrome trace, and on XProf when capturing."""
        return self.tracer.span(name, **attrs)

    # -- step sampling -----------------------------------------------------

    def mark(self) -> None:
        """Reset the timing window start (call when entering a training
        loop, or after untimed work — checkpointing, eval — so the next
        window measures only steps). Any unfenced partial window is
        discarded, not misattributed."""
        self._mark = time.perf_counter()
        self._win_steps = 0
        self._win_examples = 0.0
        self._win_tokens = 0.0

    def on_step(self, fence, *, examples: float = 0, tokens: float = 0,
                force_fence: bool = False) -> None:
        """Account one dispatched training step.

        ``fence`` is anything the step returned (``jax.block_until_ready``
        target). Every ``every``-th step (or on ``force_fence`` — the
        trainer forces the first batch, which is the compile window) the
        device is fenced and the whole window is recorded.
        """
        self._steps_seen += 1
        self._win_steps += 1
        self._win_examples += examples
        self._win_tokens += tokens
        if not (force_fence or self._steps_seen % self.every == 0):
            return
        import jax
        jax.block_until_ready(fence)
        now = time.perf_counter()
        self.timer.record_window(now - self._mark, steps=self._win_steps,
                                 examples=self._win_examples,
                                 tokens=self._win_tokens)
        self._mark = now
        self._win_steps = 0
        self._win_examples = 0.0
        self._win_tokens = 0.0

    # -- bubble drift (measured vs modeled pipeline idle) ------------------

    def set_bubble_reference(self, ideal_step_s: float) -> None:
        """Register a bubble-free step-time reference (a fused/1-stage run
        of the same work, or an analytic estimate). With it, every epoch
        record gains ``bubble_fraction_measured`` and ``bubble_drift``
        (measured − modeled — the schedule model checked against reality,
        the training twin of serving's ``serve_kv_drift_bytes``). Without
        a reference the drift is simply not emitted — never fabricated
        from the model itself, which would be a tautology."""
        if ideal_step_s <= 0:
            raise ValueError(
                f"ideal_step_s must be > 0, got {ideal_step_s}")
        self._ideal_step_s = float(ideal_step_s)

    # -- static step probe (ICI bytes) ------------------------------------

    def set_step_probe(self, fn, *abstract_args, mesh=None,
                       **abstract_kwargs) -> None:
        """Register the exact step fn + abstract args for the static
        ICI-bytes gauge (``telemetry/ici.py``). Evaluated lazily once, at
        the first epoch emission — trace-only, no device buffers."""
        if self._probe is None:
            self._probe = (fn, abstract_args, abstract_kwargs, mesh)

    def _ici_bytes(self):
        if not self._ici_done:
            self._ici_done = True
            if self._probe is not None:
                from simple_distributed_machine_learning_tpu.telemetry import (
                    ici,
                )
                fn, args, kwargs, mesh = self._probe
                self._ici_info = ici.expected_ici_bytes(
                    fn, *args, mesh=mesh, name="train_step", **kwargs)
                ici.record(self.registry, self._ici_info)
        return self._ici_info

    # -- emission ----------------------------------------------------------

    def epoch_record(self, epoch: int, pipe=None, extra: dict | None = None
                     ) -> dict:
        """Build the per-epoch record: step-latency quantiles + throughput
        (StepTimer), memory sample, schedule bubble estimate, static ICI
        bytes, and any caller fields (losses, accuracy)."""
        self.registry.counter("epochs_total").inc()
        rec: dict = {"kind": "epoch", "epoch": int(epoch)}
        rec.update(self.timer.summary())
        rec.update(memory.sample(self.registry))
        if pipe is not None:
            frac = schedule_bubble_fraction(pipe.n_stages,
                                            pipe.n_microbatches,
                                            pipe.schedule)
            rec["schedule"] = pipe.schedule
            rec["n_stages"] = pipe.n_stages
            rec["n_microbatches"] = pipe.n_microbatches
            rec["bubble_fraction"] = round(frac, 4)
            self.registry.gauge("bubble_fraction").set(frac)
            p50 = rec.get("step_time_ms_p50")
            if self._ideal_step_s is not None and p50:
                from simple_distributed_machine_learning_tpu.telemetry.bubble import (  # noqa: E501
                    measured_bubble_fraction,
                )
                measured = measured_bubble_fraction(p50 / 1e3,
                                                    self._ideal_step_s)
                rec["bubble_fraction_measured"] = round(measured, 4)
                rec["bubble_drift"] = round(measured - frac, 4)
                self.registry.gauge("bubble_fraction_measured").set(measured)
                self.registry.gauge("bubble_drift").set(measured - frac)
        info = self._ici_bytes()
        if info is not None:
            rec["ici_bytes_per_step"] = info["ici_bytes_per_step"]
            rec["ici_top_collectives"] = info["collectives"]
        for name in ("examples_per_sec", "tokens_per_sec"):
            if rec.get(name):
                self.registry.gauge(name).set(rec[name])
        if extra:
            rec.update(extra)
        return rec

    def on_epoch(self, epoch: int, pipe=None, extra: dict | None = None
                 ) -> dict:
        """Emit one epoch record and refresh every on-disk artifact."""
        rec = self.epoch_record(epoch, pipe=pipe, extra=extra)
        self.tracer.instant("epoch_end", epoch=epoch)
        if self._is_main():
            rec = append_jsonl(os.path.join(self.outdir, METRICS_FILE), rec)
            self.flush()
        return rec

    def flush(self) -> None:
        """Rewrite trace.json and metrics.prom from current state."""
        if not self._is_main():
            return
        self.tracer.write(os.path.join(self.outdir, TRACE_FILE))
        with open(os.path.join(self.outdir, PROM_FILE), "w") as f:
            f.write(self.registry.prometheus_text())

    def close(self) -> None:
        self.flush()
