"""Labeled metric instruments: counters, gauges, histograms.

The reference's only observability is a loss ``print`` every ``log_interval``
batches; ``train/trainer.py`` grew a per-epoch JSONL append on top. This
module replaces both ad-hoc paths with one registry: named, optionally
labeled series that snapshot to a JSON record (the ``metrics.jsonl`` stream)
and to a Prometheus-style text exposition (``metrics.prom``), so a run can
feed dashboards without any scraping shim.

Semantics (the subset of the Prometheus data model the trainer needs):

- :class:`Counter` is monotonic — ``inc`` of a negative amount raises, so a
  consumer may compute rates without guarding against resets mid-run;
- :class:`Gauge` is a settable last-value;
- :class:`Histogram` keeps exact weighted observations (bounded reservoir of
  the most recent ``max_samples`` distinct observe calls) and answers
  nearest-rank quantiles — p50/p95 step latency is the whole point;
- two series with the same name must agree on instrument kind AND label-key
  set (``registry.counter("steps"); registry.gauge("steps")`` is a bug, as is
  the same name with different label keys) — :class:`MetricsRegistry` raises
  on the collision instead of silently forking the series.
"""

from __future__ import annotations

import json
import threading
import time


class Counter:
    """Monotonic counter. ``inc(n)`` with ``n < 0`` raises ``ValueError``."""

    kind = "counter"

    def __init__(self, name: str, labels: dict | None = None) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"counter {self.name!r} is monotonic: inc({amount}) — use a "
                f"Gauge for values that go down")
        self.value += amount


class Gauge:
    """Last-value instrument."""

    kind = "gauge"

    def __init__(self, name: str, labels: dict | None = None) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)


class Histogram:
    """Weighted observations with exact nearest-rank quantiles.

    ``observe(v, n=k)`` records ``k`` observations of value ``v`` in O(1) —
    the shape a windowed step timer needs (one fenced window covers ``k``
    steps of identical estimated duration). ``count``/``sum``/``max`` cover
    ALL observations; quantiles are computed over a bounded reservoir of the
    most recent ``max_samples`` observe calls (a ring buffer — steady-state
    training is stationary enough that recency beats reservoir sampling and
    stays deterministic).
    """

    kind = "histogram"

    def __init__(self, name: str, labels: dict | None = None,
                 max_samples: int = 8192) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self.count = 0.0
        self.sum = 0.0
        self.max = None
        self._ring: list[tuple[float, float]] = []   # (value, weight)
        self._next = 0
        self._max_samples = max_samples

    def observe(self, value: float, n: float = 1.0) -> None:
        if n <= 0:
            raise ValueError(f"histogram {self.name!r}: observe weight {n} "
                             f"must be positive")
        value = float(value)
        self.count += n
        self.sum += value * n
        self.max = value if self.max is None else max(self.max, value)
        if len(self._ring) < self._max_samples:
            self._ring.append((value, n))
        else:
            self._ring[self._next] = (value, n)
            self._next = (self._next + 1) % self._max_samples

    def quantile(self, q: float) -> float | None:
        """Weighted nearest-rank quantile over the reservoir, ``q in [0,1]``."""
        if not self._ring:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        pairs = sorted(self._ring)
        total = sum(w for _, w in pairs)
        target = q * total
        cum = 0.0
        for v, w in pairs:
            cum += w
            if cum >= target:
                return v
        return pairs[-1][0]

    def fraction_below(self, threshold: float) -> float | None:
        """Weighted fraction of reservoir observations ``<= threshold`` —
        the SLO-attainment primitive ("what share of requests met the
        target?"); None before any observation."""
        if not self._ring:
            return None
        total = sum(w for _, w in self._ring)
        hit = sum(w for v, w in self._ring if v <= threshold)
        return hit / total

    @property
    def mean(self) -> float | None:
        return self.sum / self.count if self.count else None

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.mean,
            "max": self.max,
            "p50": self.quantile(0.5),
            "p95": self.quantile(0.95),
        }


class MetricsRegistry:
    """Name -> instrument registry with label-series fan-out.

    ``registry.counter("x", labels={"stage": "0"})`` returns the one live
    instrument for that (name, labels) pair — repeated calls accumulate into
    the same series. A name re-registered as a different kind or with a
    different label-KEY set raises (a silent fork of the series is exactly
    the observability bug this layer exists to prevent).
    """

    def __init__(self) -> None:
        self._series: dict[tuple[str, tuple], Counter | Gauge | Histogram] = {}
        self._schemas: dict[str, tuple[str, tuple[str, ...]]] = {}
        self._lock = threading.Lock()

    # -- instrument constructors ------------------------------------------

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, labels: dict | None = None,
                  max_samples: int = 8192) -> Histogram:
        return self._get(Histogram, name, labels, max_samples=max_samples)

    def _get(self, cls, name: str, labels: dict | None, **kw):
        labels = dict(labels or {})
        key = (name, tuple(sorted(labels.items())))
        label_keys = tuple(sorted(labels))
        with self._lock:
            schema = self._schemas.get(name)
            if schema is not None and schema != (cls.kind, label_keys):
                raise ValueError(
                    f"metric {name!r} already registered as {schema[0]} with "
                    f"label keys {schema[1]}; got {cls.kind} with "
                    f"{label_keys} — one name, one schema")
            inst = self._series.get(key)
            if inst is None:
                inst = cls(name, labels, **kw)
                self._series[key] = inst
                self._schemas[name] = (cls.kind, label_keys)
            return inst

    # -- export -----------------------------------------------------------

    def instruments(self) -> list:
        return list(self._series.values())

    def snapshot(self) -> dict:
        """JSON-serializable map ``name{k=v,...} -> value`` (histograms map
        to their summary dict)."""
        out = {}
        for inst in self._series.values():
            out[_series_key(inst)] = (inst.summary()
                                      if isinstance(inst, Histogram)
                                      else inst.value)
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (histograms as summary quantiles).

        Each name's first series is preceded by a ``# HELP`` line when the
        metric-catalog (``telemetry/catalog.py`` — sourced from the module
        docstrings that document the instruments) knows the name, then the
        ``# TYPE`` line. Label VALUES are escaped per the exposition format
        (backslash, double quote, newline) — a class label containing ``"``
        must scrape, not corrupt the series (tests pin a round-trip
        parse)."""
        from simple_distributed_machine_learning_tpu.telemetry.catalog import (
            metric_help,
        )
        help_catalog = metric_help()
        lines = []
        seen_type: set[str] = set()
        for inst in sorted(self._series.values(), key=_series_key):
            if inst.name not in seen_type:
                seen_type.add(inst.name)
                doc = help_catalog.get(inst.name)
                if doc:
                    lines.append(f"# HELP {inst.name} {_escape_help(doc)}")
                kind = "summary" if isinstance(inst, Histogram) else inst.kind
                lines.append(f"# TYPE {inst.name} {kind}")
            if isinstance(inst, Histogram):
                for q in (0.5, 0.95):
                    v = inst.quantile(q)
                    if v is not None:
                        lines.append(f"{inst.name}"
                                     f"{_labels(inst.labels, quantile=q)} "
                                     f"{_num(v)}")
                lines.append(f"{inst.name}_count{_labels(inst.labels)} "
                             f"{_num(inst.count)}")
                lines.append(f"{inst.name}_sum{_labels(inst.labels)} "
                             f"{_num(inst.sum)}")
            else:
                lines.append(f"{inst.name}{_labels(inst.labels)} "
                             f"{_num(inst.value)}")
        return "\n".join(lines) + "\n"


def _series_key(inst) -> str:
    if not inst.labels:
        return inst.name
    inner = ",".join(f"{k}={v}" for k, v in sorted(inst.labels.items()))
    return f"{inst.name}{{{inner}}}"


def _escape_label_value(v) -> str:
    """Prometheus exposition-format label-value escaping: backslash first
    (or the other escapes would double-escape), then double quote and
    newline. Without this, a label value containing ``"`` emits a series
    no scraper can parse."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping (backslash and newline only — quotes are legal
    in help text), collapsed to one line."""
    return " ".join(str(text).split()).replace("\\", r"\\")


def _labels(labels: dict, **extra) -> str:
    items = {**labels, **extra}
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(items.items()))
    return "{" + inner + "}"


def _num(v: float) -> str:
    return repr(int(v)) if float(v).is_integer() else repr(float(v))


def append_jsonl(path: str, record: dict, schema: int = 2) -> dict:
    """Append one schema-versioned JSON line to ``path`` and return the full
    record written. The ``schema`` key is injected first so consumers can
    dispatch on it before touching any other field; an explicit ``schema``
    already in ``record`` wins."""
    full = {"schema": schema, "time": round(time.time(), 3), **record}
    with open(path, "a") as f:
        f.write(json.dumps(full) + "\n")
    return full
