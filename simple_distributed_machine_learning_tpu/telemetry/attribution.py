"""Per-request latency attribution: where did this request's TTFT go?

``serve/tracing.py`` writes a per-request timeline (one JSONL row per
event, engine timestamps the serving layer already read); this module
folds each completed request's rows into an **additive** decomposition of
its latency — a cursor walk over the event boundaries where every elapsed
span is assigned to exactly one component, so the components sum to the
observed TTFT *by construction* (telescoping), and the fold asserts the
reconciliation against the ``first_token`` row's independently-computed
``ttft_ms`` (drift beyond float rounding raises :class:`AttributionError`
— a test failure, never a silently-wrong autopsy).

Span → component (by the event OPENING the span):

==================  =============  ========================================
previous event      component      the time is spent...
==================  =============  ========================================
``submit``          ``queue``      waiting for a slot
``gate``            ``prefetch``   blocked on an in-flight host->HBM upload
``admit``           ``prefill``    building K/V (incl. inter-chunk waits)
``prefill_chunk``   ``prefill``    (same span, later chunks)
``preempt``         ``preempt``    evicted, waiting to re-board + rebuild
``crash``           ``crash``      engine died; journal recovery + re-queue
``migrate``         ``handoff``    adopted across replicas (fleet handoff)
``readmit``         (cause's)      still the crash/handoff gap until board
``first_token`` /   ``decode``     decode-tick cadence (TPOT side)
``tick``/``resume``
==================  =============  ========================================

A recovered rid's rows span engine incarnations (``inc``); the fold joins
them — one attribution covers both lives, with ``crash`` holding the
crash+readmit gap. The TTFT side covers ``submit``→``first_token``; the
decode side (``first_token``→``done``) aggregates separately.

Registry instruments (when :func:`attribute` is given a ``registry``):

- ``serve_ttft_component_ms{component=...}`` (histogram) — one
  observation per attributed request per non-zero TTFT component: the
  fleet-wide answer to "is TTFT going to queueing or to prefill".
"""

from __future__ import annotations

import collections

#: |computed - journaled| TTFT tolerance (ms): timeline rows round ``t``
#: to 6 decimals and ``ttft_ms`` to 3, so honest folds drift < 0.0025 ms.
DRIFT_TOL_MS = 0.005

#: attribution components, render order (docs table + report autopsy).
COMPONENTS = ("queue", "prefetch", "prefill", "preempt", "crash",
              "handoff", "decode")

# event opening a span -> component the span's time belongs to, before
# the first token (readmit resolved dynamically from its cause).
_PRE_TTFT = {"submit": "queue", "gate": "prefetch", "admit": "prefill",
             "prefill_chunk": "prefill", "preempt": "preempt",
             "crash": "crash", "migrate": "handoff"}
# after the first token everything is decode cadence except interruptions.
_POST_TTFT = {"first_token": "decode", "tick": "decode", "resume": "decode",
              "preempt": "preempt", "admit": "preempt",
              "prefill_chunk": "preempt", "gate": "prefetch",
              "crash": "crash", "migrate": "handoff"}


class AttributionError(ValueError):
    """A fold whose components do not reconcile with the journaled TTFT
    — the timeline is corrupt or the component map missed an event."""


def fold_request(rows: list[dict]) -> dict | None:
    """Fold ONE rid's timeline rows (file order = chronological) into an
    attribution record, or None when the request never reached its first
    token (shed / still in flight — nothing to decompose)."""
    ft_row = next((r for r in rows if r["ev"] == "first_token"), None)
    if ft_row is None or ft_row.get("ttft_ms") is None:
        return None
    submit = next((r for r in rows if r["ev"] == "submit"), None)
    pre = collections.defaultdict(float)
    post = collections.defaultdict(float)
    cursor = comp = None
    seen_ft = False
    done_row = None
    incs = sorted({r["inc"] for r in rows})
    for row in rows:
        ev, t = row["ev"], row["t"]
        if ev == "restart":              # rid-less supervisor row; the
            continue                     # per-rid crash row marks the gap
        if cursor is not None and comp is not None:
            (post if seen_ft else pre)[comp] += (t - cursor) * 1e3
        if ev == "first_token":
            # the span ENDING here was still prefill; spans after it are
            # decode cadence — flip before the component lookup
            seen_ft = True
        if ev == "readmit":
            # still the crash/handoff gap until the request re-boards
            comp = comp if comp in ("crash", "handoff") else "queue"
        else:
            comp = (_POST_TTFT if seen_ft else _PRE_TTFT).get(ev, comp)
        cursor = t
        if ev in ("done", "shed"):
            done_row = row
            break
    ttft_ms = ft_row["ttft_ms"]
    total = sum(pre.values())
    drift = total - ttft_ms
    if abs(drift) > DRIFT_TOL_MS:
        raise AttributionError(
            f"rid {ft_row['rid']}: TTFT components sum to {total:.6f} ms "
            f"but the timeline journaled ttft_ms={ttft_ms} "
            f"(drift {drift:+.6f} ms > {DRIFT_TOL_MS}) — the attribution "
            f"fold and the engine's own TTFT no longer agree")
    components = {c: round(pre[c], 3) for c in COMPONENTS if pre.get(c)}
    out = {
        "rid": ft_row["rid"],
        "cls": submit.get("cls") if submit is not None else None,
        "prompt_len": (submit.get("prompt_len")
                       if submit is not None else None),
        "ttft_ms": ttft_ms,
        "components_ms": components,
        "drift_ms": round(drift, 6),
        "incarnations": incs,
        "recovered": len(incs) > 1,
    }
    if done_row is not None and seen_ft:
        out["decode_ms"] = round(sum(post.values()), 3)
        out["decode_components_ms"] = {
            c: round(post[c], 3) for c in COMPONENTS if post.get(c)}
        out["tokens"] = done_row.get("tokens")
        out["finish"] = done_row.get("reason")
    return out


def attribute(rows: list[dict], *, top_k: int = 5,
              registry=None) -> dict:
    """Fold a whole timeline (all rids) and aggregate per class.

    Returns the deterministic ``attribution`` block ``run_scenario``
    lands in the scenario record: per-class component means, the top-K
    slow-request autopsy list (sorted by TTFT desc, rid asc — the table
    ``telemetry.report`` renders), and the worst reconciliation drift
    seen (pinned ≤ :data:`DRIFT_TOL_MS` by the fold itself)."""
    by_rid: dict = collections.OrderedDict()
    for row in rows:
        rid = row.get("rid")
        if rid is None:
            continue
        by_rid.setdefault(rid, []).append(row)
    atts = []
    for rid_rows in by_rid.values():
        att = fold_request(rid_rows)
        if att is not None:
            atts.append(att)
    by_class: dict = {}
    for att in atts:
        cls = att["cls"] or "none"
        agg = by_class.setdefault(
            cls, {"n": 0, "ttft_ms_sum": 0.0,
                  "components": collections.defaultdict(float)})
        agg["n"] += 1
        agg["ttft_ms_sum"] += att["ttft_ms"]
        for c, ms in att["components_ms"].items():
            agg["components"][c] += ms
    classes = {
        cls: {
            "n": agg["n"],
            "ttft_ms_mean": round(agg["ttft_ms_sum"] / agg["n"], 3),
            "components_ms_mean": {
                c: round(agg["components"][c] / agg["n"], 3)
                for c in COMPONENTS if agg["components"].get(c)},
        }
        for cls, agg in sorted(by_class.items())
    }
    top = sorted(atts, key=lambda a: (-a["ttft_ms"], a["rid"]))[:top_k]
    if registry is not None:
        hists = {}
        for att in atts:
            for c, ms in sorted(att["components_ms"].items()):
                h = hists.get(c)
                if h is None:
                    h = hists[c] = registry.histogram(
                        "serve_ttft_component_ms",
                        labels={"component": c})
                h.observe(ms)
    return {
        "requests": len(atts),
        "recovered": sum(1 for a in atts if a["recovered"]),
        "by_class": classes,
        "top_slow": top,
        "max_abs_drift_ms": round(
            max((abs(a["drift_ms"]) for a in atts), default=0.0), 6),
    }
