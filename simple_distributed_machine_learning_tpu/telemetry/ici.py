"""Static ICI-traffic gauge: expected collective bytes per step.

Read-only reuse of ``analysis``'s bytes-over-ICI cost table: the step about
to run is traced to a jaxpr (zero FLOPs, no device buffers) and every
collective's ring-traffic estimate is summed, so the metrics stream and
bench rows carry *bytes/step* next to *ms/step*. This is the STATIC expected
traffic — what the program asks the interconnect to move — not a hardware
counter; the point is ranking and regression-tracking ("did this change
double the gradient all-reduce?"), not nanosecond accounting.
"""

from __future__ import annotations


def expected_ici_bytes(fn, *abstract_args, mesh=None, name: str = "step",
                       steps: int = 1, top: int = 5, **abstract_kwargs
                       ) -> dict | None:
    """Expected collective bytes moved per step by ``fn``.

    ``abstract_args`` as for ``analysis.analyze`` (``jax.ShapeDtypeStruct``
    trees; use ``analysis.abstractify`` on live buffers). ``steps`` divides
    the total for step-scanned programs (a ``pool_steps=N`` bench window
    traces as one program whose scan trips already multiply the cost table).

    Returns ``{"ici_bytes_per_step": int, "collectives": [{prim, axes,
    bytes_per_step, where}, ...]}`` (top-``top`` ranked), or ``None`` when
    the step cannot be traced — telemetry must never turn a runnable program
    into a crash.
    """
    try:
        from simple_distributed_machine_learning_tpu.analysis import analyze

        report = analyze(fn, *abstract_args, mesh=mesh, name=name,
                         **abstract_kwargs)
        return from_report(report, steps=steps, top=top)
    except Exception:  # noqa: BLE001 - strictly best-effort introspection
        return None


def from_report(report, steps: int = 1, top: int = 5) -> dict | None:
    """Summarize an already-computed ``analysis.Report``'s cost table into
    the :func:`expected_ici_bytes` record shape — for callers (``bench.py
    --lint``) that have just analyzed the exact same step and must not pay
    the jaxpr trace twice."""
    if report is None or (report.errors and not report.costs):
        return None                          # trace failed: no table to sum
    total = sum(c.total_bytes for c in report.costs)
    ranked = sorted(report.costs, key=lambda c: -c.total_bytes)[:top]
    return {
        "ici_bytes_per_step": total // max(1, steps),
        "collectives": [
            {"prim": c.prim, "axes": list(c.axes),
             "bytes_per_step": c.total_bytes // max(1, steps),
             "where": c.where}
            for c in ranked],
    }


def record(registry, info: dict | None) -> None:
    """Mirror an :func:`expected_ici_bytes` result into registry gauges."""
    if not info or registry is None:
        return
    registry.gauge("ici_bytes_per_step").set(info["ici_bytes_per_step"])
    grouped: dict[tuple[str, str], int] = {}
    for c in info["collectives"]:
        k = (c["prim"], ",".join(c["axes"]) or "-")
        grouped[k] = grouped.get(k, 0) + c["bytes_per_step"]
    for (prim, axes), nbytes in grouped.items():
        registry.gauge("ici_collective_bytes_per_step",
                       labels={"prim": prim, "axes": axes}).set(nbytes)
