"""Tick-stamped alert state machines: inactive→pending→firing→resolved.

The SLO engine (``telemetry/slo.py``) turns windowed latency/shed series
into burn rates; this module turns burn rates into *alerts* the way an
SRE pager pipeline does, with one deliberate twist: **nothing here ever
reads a clock**. Every evaluation is stamped with the engine/fleet tick
the caller passes in, so under ``resilience/scenarios.py``'s virtual
clock the full transition history is exactly reproducible — the scenario
suite pins fire/resolve *ticks*, not wall timestamps.

State machine (one transition per evaluation, never a same-tick cascade,
so ``pending`` is always journaled before ``firing``)::

    inactive --cond--> pending --cond x pending_ticks--> firing
       ^                  |                                 |
       |               !cond                     !cond x resolve_ticks
       +------------------+                                 |
       +---- (next evaluation) <---------- resolved <-------+

``resolved`` is a one-evaluation state — the explicit "this alert just
cleared" journal row — decaying to ``inactive`` (or straight back to
``pending`` if the condition re-trips) on the next evaluation.

Every transition appends one journal dict ``{"tick", "alert", "from",
"to", ...context}`` to :attr:`AlertBook.journal` — the joinable record
the FlightRecorder bundle test replays against per-tick ``active_alerts``
snapshots, and the rows ``run_scenario`` lands in ``metrics.jsonl`` as
``kind: "slo_alert"`` records (the CI chaos drill greps a
fired-and-resolved pair out of exactly these).
"""

from __future__ import annotations

STATES = ("inactive", "pending", "firing", "resolved")


class Alert:
    """One alert key's state machine; see module docstring.

    ``pending_ticks`` — consecutive breaching evaluations required in
    ``pending`` before ``firing`` (≥ 1: an alert is never firing before
    its second consecutive breach, so a single-tick blip cannot page);
    ``resolve_ticks`` — consecutive clear evaluations required in
    ``firing`` before ``resolved`` (the un-flap hysteresis).
    """

    def __init__(self, key: str, *, pending_ticks: int = 2,
                 resolve_ticks: int = 4) -> None:
        if pending_ticks < 1 or resolve_ticks < 1:
            raise ValueError(
                f"pending_ticks/resolve_ticks must be >= 1, got "
                f"{pending_ticks}/{resolve_ticks}")
        self.key = key
        self.state = "inactive"
        self.pending_ticks = int(pending_ticks)
        self.resolve_ticks = int(resolve_ticks)
        self._true_streak = 0
        self._false_streak = 0
        self.fired_at: int | None = None     # tick of the last -> firing
        self.resolved_at: int | None = None  # tick of the last -> resolved

    @property
    def firing(self) -> bool:
        return self.state == "firing"

    def evaluate(self, tick: int, breaching: bool) -> tuple | None:
        """One evaluation at ``tick``; returns ``(from, to)`` when the
        state moved, else None. At most one transition per call."""
        if breaching:
            self._true_streak += 1
            self._false_streak = 0
        else:
            self._true_streak = 0
            self._false_streak += 1
        prev = self.state
        if prev == "inactive":
            if breaching:
                self.state = "pending"
        elif prev == "pending":
            if not breaching:
                self.state = "inactive"
            elif self._true_streak >= self.pending_ticks:
                self.state = "firing"
                self.fired_at = int(tick)
        elif prev == "firing":
            if not breaching and self._false_streak >= self.resolve_ticks:
                self.state = "resolved"
                self.resolved_at = int(tick)
        else:                                   # resolved: one-eval state
            self.state = "pending" if breaching else "inactive"
        return (prev, self.state) if self.state != prev else None


class AlertBook:
    """All of one engine's alerts plus their shared transition journal."""

    def __init__(self, *, pending_ticks: int = 2,
                 resolve_ticks: int = 4) -> None:
        self.pending_ticks = int(pending_ticks)
        self.resolve_ticks = int(resolve_ticks)
        self._alerts: dict[str, Alert] = {}
        self.journal: list[dict] = []

    def get(self, key: str) -> Alert:
        a = self._alerts.get(key)
        if a is None:
            a = self._alerts[key] = Alert(
                key, pending_ticks=self.pending_ticks,
                resolve_ticks=self.resolve_ticks)
        return a

    def evaluate(self, key: str, tick: int, breaching: bool,
                 **context) -> dict | None:
        """Evaluate ``key`` at ``tick``; journals and returns the
        transition row when the state moved. ``context`` (burn rates,
        window counts) rides along on the journal row."""
        moved = self.get(key).evaluate(tick, breaching)
        if moved is None:
            return None
        row = {"tick": int(tick), "alert": key,
               "from": moved[0], "to": moved[1], **context}
        self.journal.append(row)
        return row

    def firing(self) -> list[str]:
        """Sorted keys currently in ``firing`` — the ``active_alerts``
        set FlightRecorder rows and post-mortem bundles carry."""
        return sorted(k for k, a in self._alerts.items() if a.firing)

    def states(self) -> dict[str, str]:
        return {k: a.state for k, a in sorted(self._alerts.items())}

    def active_at(self, tick: int) -> list[str]:
        """Replay the journal: the firing set as of ``tick`` (inclusive)
        — what a flight row recorded at that tick must agree with (the
        bundle/journal tick-join contract, extended to alerts)."""
        state: dict[str, str] = {}
        for row in self.journal:
            if row["tick"] > tick:
                break
            state[row["alert"]] = row["to"]
        return sorted(k for k, s in state.items() if s == "firing")
