"""Host-side spans exported as Chrome-trace JSON.

``utils/profiler.annotate`` already names host intervals on an XProf
timeline — but reading that timeline needs a TensorBoard/XProf install and a
captured device trace. This module records the same spans host-side with
wall-clock durations and writes the ``chrome://tracing`` / Perfetto JSON
format, so every run with ``--telemetry-dir`` is timeline-inspectable with
nothing but a browser.

Each :meth:`Tracer.span` also enters ``profiler.annotate`` (a
``jax.profiler.TraceAnnotation``), so when an XProf capture IS active the
host spans land on both timelines with the same names.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from simple_distributed_machine_learning_tpu.utils import profiler


class Tracer:
    """Collects completed spans; thread-safe; ``write`` emits Chrome JSON."""

    def __init__(self, process_name: str = "sdml") -> None:
        self._t0_ns = time.perf_counter_ns()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._process_name = process_name

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """``with tracer.span("step", epoch=3): ...`` — one complete event.

        Nesting is rendered by the viewer from ts/dur containment within the
        thread's track; exceptions still close the span (the trace must show
        the failing interval, not lose it).
        """
        t0 = self._now_us()
        with profiler.annotate(name):
            try:
                yield self
            finally:
                t1 = self._now_us()
                ev = {"name": name, "ph": "X", "ts": t0, "dur": t1 - t0,
                      "pid": self._pid, "tid": threading.get_ident()}
                if attrs:
                    ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
                with self._lock:
                    self._events.append(ev)

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker (``ph: "i"``) — epoch boundaries etc."""
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._now_us(),
              "pid": self._pid, "tid": threading.get_ident()}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    def to_chrome_trace(self) -> dict:
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": self._process_name}}]
        with self._lock:
            return {"traceEvents": meta + list(self._events),
                    "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        """Write the Chrome-trace JSON to ``path`` (atomic rename so a
        reader never sees a torn file) and return the path."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


def _jsonable(v):
    return v if isinstance(v, (int, float, str, bool, type(None))) else str(v)
