"""Host-side spans exported as Chrome-trace JSON.

``utils/profiler.annotate`` already names host intervals on an XProf
timeline — but reading that timeline needs a TensorBoard/XProf install and a
captured device trace. This module records the same spans host-side with
wall-clock durations and writes the ``chrome://tracing`` / Perfetto JSON
format, so every run with ``--telemetry-dir`` is timeline-inspectable with
nothing but a browser.

Each :meth:`Tracer.span` also enters ``profiler.annotate`` (a
``jax.profiler.TraceAnnotation``), so when an XProf capture IS active the
host spans land on both timelines with the same names.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from simple_distributed_machine_learning_tpu.utils import profiler


class Tracer:
    """Collects completed spans; thread-safe; ``write`` emits Chrome JSON.

    Two event families:

    - :meth:`span` / :meth:`instant` — synchronous host intervals on the
      calling thread's track (``ph: "X"``/``"i"``), stamped from this
      process's wall clock;
    - :meth:`async_begin` / :meth:`async_end` / :meth:`async_instant` —
      Chrome *async* events (``ph: "b"``/``"e"``/``"n"``) keyed by an
      explicit ``(cat, id)`` pair, so arbitrarily overlapping timelines
      (e.g. concurrent serving requests) render as separate tracks instead
      of nesting wrongly by ts containment. Async events accept an explicit
      ``ts_us`` so a caller with its own clock (the serve engine's —
      possibly a :class:`~..resilience.scenarios.VirtualClock`) can stamp
      events without this tracer ever reading a clock itself.

    ``pid`` overrides the recorded process id (``ServeTrace`` pins it to 0
    so virtual-clock traces are byte-identical across runs and machines).
    """

    def __init__(self, process_name: str = "sdml",
                 pid: int | None = None) -> None:
        self._t0_ns = time.perf_counter_ns()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._pid = os.getpid() if pid is None else int(pid)
        self._process_name = process_name

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """``with tracer.span("step", epoch=3): ...`` — one complete event.

        Nesting is rendered by the viewer from ts/dur containment within the
        thread's track; exceptions still close the span (the trace must show
        the failing interval, not lose it).
        """
        t0 = self._now_us()
        with profiler.annotate(name):
            try:
                yield self
            finally:
                t1 = self._now_us()
                ev = {"name": name, "ph": "X", "ts": t0, "dur": t1 - t0,
                      "pid": self._pid, "tid": threading.get_ident()}
                if attrs:
                    ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
                with self._lock:
                    self._events.append(ev)

    def instant(self, name: str, **attrs) -> None:
        """A zero-duration marker (``ph: "i"``) — epoch boundaries etc."""
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._now_us(),
              "pid": self._pid, "tid": threading.get_ident()}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    # -- async (overlapping) spans ----------------------------------------

    def _async_event(self, ph: str, name: str, aid, ts_us, cat: str,
                     attrs: dict) -> None:
        ev = {"name": name, "ph": ph, "cat": cat, "id": str(aid),
              "ts": self._now_us() if ts_us is None else float(ts_us),
              "pid": self._pid, "tid": 0}
        if attrs:
            ev["args"] = {k: _jsonable(v) for k, v in attrs.items()}
        with self._lock:
            self._events.append(ev)

    def async_begin(self, name: str, aid, ts_us: float | None = None,
                    cat: str = "async", **attrs) -> None:
        """Open one async span keyed by ``(cat, aid, name)`` (Chrome ``b``
        phase). Overlapping spans with distinct ids never nest into each
        other — the property per-request serve timelines need."""
        self._async_event("b", name, aid, ts_us, cat, attrs)

    def async_end(self, name: str, aid, ts_us: float | None = None,
                  cat: str = "async", **attrs) -> None:
        """Close the matching ``async_begin`` (Chrome ``e`` phase); the
        viewer pairs strictly on ``(cat, id, name)``, never on nesting."""
        self._async_event("e", name, aid, ts_us, cat, attrs)

    def async_instant(self, name: str, aid, ts_us: float | None = None,
                      cat: str = "async", **attrs) -> None:
        """A zero-duration marker on an async track (Chrome ``n`` phase)."""
        self._async_event("n", name, aid, ts_us, cat, attrs)

    def to_chrome_trace(self) -> dict:
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": self._process_name}}]
        with self._lock:
            return {"traceEvents": meta + list(self._events),
                    "displayTimeUnit": "ms"}

    def write(self, path: str) -> str:
        """Write the Chrome-trace JSON to ``path`` (atomic rename so a
        reader never sees a torn file) and return the path."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)
        return path


def _jsonable(v):
    return v if isinstance(v, (int, float, str, bool, type(None))) else str(v)
