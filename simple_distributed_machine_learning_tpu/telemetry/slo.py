"""Streaming SLO engine: windowed quantiles + multi-window burn alerts.

End-of-run attainment (``ServeMetrics.attainment``) answers "did we make
the SLO"; this engine answers "are we burning error budget RIGHT NOW" —
the signal the fleet feeds back into routing and autoscaling
(``serve/fleet.py``) instead of reading post-hoc. Three layers:

- **Windowed quantile tracking** — :class:`WindowHistogram`: fixed-bucket
  latency histograms over a sliding window of the last N *ticks*. The
  bucket bounds are static configuration, never data-dependent (no
  GK/t-digest sketches whose internal state depends on arrival order), so
  two identical runs produce byte-identical windowed quantiles and the
  scenario suite can pin them exactly.
- **Burn rates** — per traffic class (and per fleet replica), each tick
  bucket counts REQUEST-level ``(observations, violations)``: one
  observation per request — its TTFT sample (a violation when over the
  :class:`SLOObjective` target) **or its shed** (a rejected request
  failed its SLO by definition — the SRE error-budget view). Per-token
  TPOT samples deliberately do NOT enter the burn series (hundreds of
  good token observations per request would dilute a shed storm into
  invisibility); they feed the windowed quantile histograms instead.
  Burn rate = violation fraction / error budget where budget =
  ``1 - target`` (target 0.9 → budget 0.1; burn 1.0 = exactly eating the
  budget, sustained burn ≥ threshold pages).
- **Multi-window alerts** — SRE-style fast+slow window pairs: the alert
  condition requires the burn over BOTH the fast window (reacts quickly,
  flappy alone) and the slow window (smooth, slow alone) to clear the
  threshold, then drives ``telemetry/alerts.py``'s tick-stamped state
  machine (inactive→pending→firing→resolved; transitions journaled).

**The engine never reads a clock.** Observations carry latencies the
serving layer already measured; evaluations are stamped with the
engine/fleet tick the driver passes to :meth:`SLOEngine.evaluate`. Under
the virtual-clock scenarios this is what keeps every pre-existing pinned
number unchanged and makes alert fire/resolve ticks themselves pinnable
(``analysis/hostlint.py`` enforces the no-wall-clock rule on this module
exactly as it does on ``serve/``).

Registry instruments (when constructed with ``registry=``):

- ``serve_slo_burn_rate{class=...}`` (gauge) — the class's fast-window
  burn rate as of the last evaluation: violation fraction over the error
  budget, 0.0 when the window holds fewer than ``min_count`` samples;
- ``serve_alerts_firing`` (gauge) — how many alerts are currently in the
  ``firing`` state across all classes and replicas.
"""

from __future__ import annotations

import bisect
import collections
import math

from simple_distributed_machine_learning_tpu.telemetry.alerts import (
    AlertBook,
)

#: default fixed bucket upper bounds (ms) for windowed latency quantiles
#: — static config, never data-dependent (see module docstring).
DEFAULT_BOUNDS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                  1000.0, 2000.0, 5000.0)


class SLOObjective:
    """One traffic class's online SLO: TTFT/TPOT targets (ms; None =
    untracked) at an attainment ``target`` (0.9 → 10% error budget)."""

    def __init__(self, cls: str, *, ttft_slo_ms: float | None = None,
                 tpot_slo_ms: float | None = None,
                 target: float = 0.9) -> None:
        if not 0.0 < target < 1.0:
            raise ValueError(f"target must be in (0, 1), got {target}")
        if ttft_slo_ms is None and tpot_slo_ms is None:
            raise ValueError(f"objective for class {cls!r} tracks nothing "
                             f"— give ttft_slo_ms and/or tpot_slo_ms")
        self.cls = cls
        self.ttft_slo_ms = ttft_slo_ms
        self.tpot_slo_ms = tpot_slo_ms
        self.target = float(target)

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def describe(self) -> dict:
        return {"ttft_slo_ms": self.ttft_slo_ms,
                "tpot_slo_ms": self.tpot_slo_ms, "target": self.target}


class WindowHistogram:
    """Fixed-bucket histogram over a sliding window of the last
    ``window`` ticks. ``observe`` lands in the current (open) tick
    bucket; :meth:`roll` closes it. Quantiles are bucket UPPER bounds
    (nearest-rank over merged window counts) — a deterministic
    overestimate, never an interpolation whose value depends on sample
    order."""

    def __init__(self, bounds=DEFAULT_BOUNDS, window: int = 32) -> None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError("bucket bounds must be strictly increasing")
        self.bounds = tuple(float(b) for b in bounds)
        self.window = int(window)
        # one overflow bucket past the last bound; counts[i] <= bounds[i]
        self._ticks: collections.deque[list[int]] = collections.deque(
            maxlen=self.window)
        self._cur = [0] * (len(self.bounds) + 1)

    def observe(self, value: float) -> None:
        self._cur[bisect.bisect_left(self.bounds, float(value))] += 1

    def roll(self) -> None:
        self._ticks.append(self._cur)
        self._cur = [0] * (len(self.bounds) + 1)

    @property
    def n(self) -> int:
        return sum(sum(t) for t in self._ticks)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the window as a bucket upper bound
        (overflow clamps to the last bound); None on an empty window."""
        counts = [sum(t[i] for t in self._ticks)
                  for i in range(len(self.bounds) + 1)]
        total = sum(counts)
        if total == 0:
            return None
        rank = max(1, math.ceil(q * total))
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= rank:
                return self.bounds[min(i, len(self.bounds) - 1)]
        return self.bounds[-1]          # pragma: no cover - loop covers


class _Series:
    """One alert scope's per-tick ``(n, violations)`` window."""

    def __init__(self, slow_window: int) -> None:
        self._ticks: collections.deque[tuple[int, int]] = collections.deque(
            maxlen=slow_window)
        self._n = 0
        self._bad = 0

    def observe(self, bad: bool) -> None:
        self._n += 1
        if bad:
            self._bad += 1

    def roll(self) -> None:
        self._ticks.append((self._n, self._bad))
        self._n = 0
        self._bad = 0

    def counts(self, last: int | None = None) -> tuple[int, int]:
        ticks = (list(self._ticks)[-last:] if last is not None
                 else self._ticks)
        return (sum(n for n, _ in ticks), sum(b for _, b in ticks))


class SLOEngine:
    """The streaming SLO engine; see module docstring.

    Observations arrive via ``observe_ttft`` / ``observe_tpot`` /
    ``observe_shed`` (``ServeMetrics`` forwards its hooks when bound via
    ``ServeMetrics.bind_slo``); whoever owns the tick — the serve
    supervisor or the fleet — calls :meth:`evaluate` exactly once per
    tick. Per-replica series (``replica=`` on the observe calls, set by
    the fleet around each replica's step) get their own
    ``slo_burn{replica=N}`` alerts — the router-demotion signal.
    """

    def __init__(self, objectives, *, fast_window: int = 8,
                 slow_window: int = 32, burn_threshold: float = 1.0,
                 pending_ticks: int = 2, resolve_ticks: int = 4,
                 min_count: int = 1, target: float = 0.9,
                 bounds=DEFAULT_BOUNDS, registry=None) -> None:
        if fast_window < 1 or slow_window < fast_window:
            raise ValueError(
                f"windows must satisfy 1 <= fast <= slow, got "
                f"{fast_window}/{slow_window}")
        if min_count < 1:
            raise ValueError(f"min_count must be >= 1, got {min_count}")
        objectives = list(objectives)
        self.objectives: dict[str, SLOObjective] = {
            o.cls: o for o in objectives}
        if len(self.objectives) != len(objectives):
            raise ValueError("duplicate class in objectives")
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.burn_threshold = float(burn_threshold)
        self.min_count = int(min_count)
        self.target = float(target)        # replica-scope error budget
        self.tick = 0                      # last evaluated tick
        self.evaluations = 0
        self.alerts = AlertBook(pending_ticks=pending_ticks,
                                resolve_ticks=resolve_ticks)
        self._class_series = {cls: _Series(slow_window)
                              for cls in self.objectives}
        self._replica_series: dict[int, _Series] = {}
        self._hists = {(cls, sig): WindowHistogram(bounds, slow_window)
                       for cls, o in self.objectives.items()
                       for sig, tgt in (("ttft", o.ttft_slo_ms),
                                        ("tpot", o.tpot_slo_ms))
                       if tgt is not None}
        self._burn: dict[str, float] = dict.fromkeys(self.objectives, 0.0)
        self._burn_gauges = {}
        self._firing_gauge = None
        if registry is not None:
            self._burn_gauges = {
                cls: registry.gauge("serve_slo_burn_rate",
                                    labels={"class": cls})
                for cls in sorted(self.objectives)}
            self._firing_gauge = registry.gauge("serve_alerts_firing")

    @classmethod
    def from_classes(cls, classes, **kw) -> "SLOEngine | None":
        """Build from ``TrafficClass``-shaped records (``.name``,
        ``.ttft_slo_ms``, ``.tpot_slo_ms``) — the scenario wiring. None
        when no class carries an SLO target (nothing to track)."""
        target = kw.get("target", 0.9)
        objectives = [
            SLOObjective(tc.name, ttft_slo_ms=tc.ttft_slo_ms,
                         tpot_slo_ms=tc.tpot_slo_ms, target=target)
            for tc in classes
            if tc.ttft_slo_ms is not None or tc.tpot_slo_ms is not None]
        if not objectives:
            return None
        return cls(objectives, **kw)

    # -- observations ------------------------------------------------------

    def _observe(self, cls, sig: str, ms: float, replica) -> None:
        o = self.objectives.get(cls)
        if o is None:
            return
        target_ms = o.ttft_slo_ms if sig == "ttft" else o.tpot_slo_ms
        if target_ms is None:
            return
        self._hists[(cls, sig)].observe(ms)
        if sig != "ttft":
            # per-token TPOT stays out of the burn series (request-level
            # SLI — see module docstring); quantile window only
            return
        bad = ms > target_ms
        self._class_series[cls].observe(bad)
        if replica is not None:
            self._replica(replica).observe(bad)

    def observe_ttft(self, cls, ttft_ms: float, replica=None) -> None:
        self._observe(cls, "ttft", ttft_ms, replica)

    def observe_tpot(self, cls, tpot_ms: float, replica=None) -> None:
        self._observe(cls, "tpot", tpot_ms, replica)

    def observe_shed(self, cls, replica=None) -> None:
        """A structured rejection: counts as a violated observation — a
        request the system refused failed its SLO by definition."""
        if cls not in self.objectives:
            return
        self._class_series[cls].observe(True)
        if replica is not None:
            self._replica(replica).observe(True)

    def _replica(self, idx) -> _Series:
        s = self._replica_series.get(idx)
        if s is None:
            s = self._replica_series[idx] = _Series(self.slow_window)
        return s

    # -- evaluation --------------------------------------------------------

    def _burn_pair(self, series: _Series, budget: float) -> tuple:
        nf, bf = series.counts(self.fast_window)
        ns, bs = series.counts()
        fast = (bf / nf) / budget if nf >= self.min_count else 0.0
        slow = (bs / ns) / budget if ns >= self.min_count else 0.0
        return fast, slow, nf, ns

    def evaluate(self, tick: int) -> list[dict]:
        """Close the current tick bucket and evaluate every alert;
        returns this tick's journaled transitions. Call exactly once per
        engine/fleet tick — the ONLY timestamps in the alert pipeline are
        the ticks handed in here."""
        self.tick = int(tick)
        self.evaluations += 1
        transitions: list[dict] = []
        for cls in sorted(self.objectives):
            series = self._class_series[cls]
            series.roll()
            fast, slow, nf, ns = self._burn_pair(
                series, self.objectives[cls].budget)
            self._burn[cls] = fast
            breaching = (fast >= self.burn_threshold
                         and slow >= self.burn_threshold)
            row = self.alerts.evaluate(
                f"slo_burn{{class={cls}}}", tick, breaching,
                burn_fast=round(fast, 4), burn_slow=round(slow, 4))
            if row is not None:
                transitions.append(row)
            g = self._burn_gauges.get(cls)
            if g is not None:
                g.set(round(fast, 6))
        budget = 1.0 - self.target
        for idx in sorted(self._replica_series):
            series = self._replica_series[idx]
            series.roll()
            fast, slow, nf, ns = self._burn_pair(series, budget)
            breaching = (fast >= self.burn_threshold
                         and slow >= self.burn_threshold)
            row = self.alerts.evaluate(
                f"slo_burn{{replica={idx}}}", tick, breaching,
                burn_fast=round(fast, 4), burn_slow=round(slow, 4))
            if row is not None:
                transitions.append(row)
        for h in self._hists.values():
            h.roll()
        if self._firing_gauge is not None:
            self._firing_gauge.set(len(self.alerts.firing()))
        return transitions

    # -- read side ---------------------------------------------------------

    def active_alerts(self) -> list[str]:
        return self.alerts.firing()

    def firing_replicas(self) -> set:
        """Replica indices whose per-replica burn alert is firing — the
        fleet's router-demotion signal."""
        out = set()
        for idx in self._replica_series:
            if self.alerts.get(f"slo_burn{{replica={idx}}}").firing:
                out.add(idx)
        return out

    def burn_rates(self) -> dict:
        """Per-class fast-window burn as of the last evaluation (the
        autoscaler's optional scale-out trigger)."""
        return dict(self._burn)

    def window_quantiles(self, q: float = 0.95) -> dict:
        out: dict = {}
        for (cls, sig), h in sorted(self._hists.items()):
            v = h.quantile(q)
            if v is not None:
                out[f"{cls}_{sig}_p{int(q * 100)}_ms"] = v
        return out

    def summary(self) -> dict:
        """The deterministic record block ``run_scenario`` lands in the
        scenario report (and tests pin byte-identically)."""
        return {
            "tick": self.tick,
            "objectives": {cls: o.describe()
                           for cls, o in sorted(self.objectives.items())},
            "windows": {"fast": self.fast_window, "slow": self.slow_window,
                        "burn_threshold": self.burn_threshold},
            "transitions": list(self.alerts.journal),
            "firing": self.alerts.firing(),
            "states": self.alerts.states(),
            "window_quantiles": self.window_quantiles(),
        }
