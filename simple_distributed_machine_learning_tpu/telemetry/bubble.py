"""Pipeline-bubble accounting for the GPipe and 1F1B schedules.

The schedule model: a pipeline step is a sequence of *slots* (one
microbatch's forward-or-backward on one stage). With ``S`` stages and ``M``
microbatches, both schedules this engine implements fill and drain the
pipeline once per optimizer step:

- GPipe (``parallel/pipeline.py``): a scanned all-forward sweep of
  ``M + S - 1`` ticks, then autodiff runs the transposed sweep — another
  ``M + S - 1`` ticks of backward slots;
- non-interleaved 1F1B / PipeDream-flush (``parallel/onefb.py``): ``S - 1``
  warmup forwards, a steady one-forward-one-backward phase, ``S - 1``
  cooldown backwards — ``M + S - 1`` combined fwd+bwd ticks.

Either way every stage is idle for ``S - 1`` of the ``M + S - 1`` ticks, so
the bubble fraction — idle time over total time, equivalently
``1 - ideal_step_time / measured_step_time`` under the uniform-slot model —
is ``(S - 1) / (M + S - 1)`` for BOTH schedules. Non-interleaved 1F1B's win
is activation MEMORY (O(S) vs O(M) live microbatches), not bubble time, so
callers may rely on ``bubble('1f1b') <= bubble('gpipe')`` holding with
equality; an interleaved (virtual-stage) schedule would strictly shrink it.
"""

from __future__ import annotations


def schedule_bubble_fraction(n_stages: int, n_microbatches: int,
                             schedule: str = "gpipe") -> float:
    """Fraction of a step each stage spends idle under the schedule model.

    ``(S - 1) / (M + S - 1)``; 0.0 for a single-stage (fused) pipeline.
    ``schedule`` is validated against the engine's two schedules so a typo
    cannot silently read as GPipe.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown schedule {schedule!r}")
    s = max(1, int(n_stages))
    m = max(1, int(n_microbatches))
    if s == 1:
        return 0.0
    return (s - 1) / (m + s - 1)


def ideal_step_time(measured_step_s: float, n_stages: int,
                    n_microbatches: int, schedule: str = "gpipe") -> float:
    """Bubble-free step time implied by a measured one.

    Anchors the slot model to a measurement: the measured step is
    ``M + S - 1`` uniform ticks, the ideal (every stage busy every tick)
    would be ``M`` — i.e. ``measured x (1 - bubble_fraction)``. This is the
    "ideal stage time x stages vs measured step time" estimate: the gap to
    the returned value is what schedule tuning (more microbatches,
    interleaving) can recover; the rest needs faster stages.
    """
    frac = schedule_bubble_fraction(n_stages, n_microbatches, schedule)
    return measured_step_s * (1.0 - frac)


def measured_bubble_fraction(measured_step_s: float,
                             ideal_step_s: float) -> float:
    """The MEASURED bubble: idle share implied by a real step time against
    a bubble-free reference — ``1 - ideal / measured``, clamped to
    ``[0, 1]``.

    ``ideal_step_s`` is a bubble-free calibration of the same work: a
    single-stage (fused) run of the identical model and microbatch count,
    or an analytic estimate. Unlike :func:`ideal_step_time` (which
    *assumes* the schedule model to back the ideal out of one
    measurement), this takes the reference as an independent input — so
    comparing the result to :func:`schedule_bubble_fraction` is a real
    check, not a tautology."""
    if measured_step_s <= 0 or ideal_step_s <= 0:
        raise ValueError(
            f"step times must be > 0, got measured={measured_step_s}, "
            f"ideal={ideal_step_s}")
    return min(1.0, max(0.0, 1.0 - ideal_step_s / measured_step_s))


def bubble_drift(n_stages: int, n_microbatches: int, schedule: str,
                 measured_step_s: float, ideal_step_s: float) -> float:
    """Measured minus modeled bubble fraction — the pipeline twin of the
    serving KV-drift gauge: ~0 when the uniform-slot schedule model holds,
    positive when real stages idle longer than ``(S-1)/(M+S-1)`` predicts
    (imbalanced stages, comm on the critical path), negative when overlap
    hides more than the model credits."""
    return (measured_bubble_fraction(measured_step_s, ideal_step_s)
            - schedule_bubble_fraction(n_stages, n_microbatches, schedule))
