"""The run-report CLI: one human summary from a telemetry directory.

``python -m simple_distributed_machine_learning_tpu.telemetry.report
--dir DIR`` renders everything a run left behind — ``metrics.jsonl``
(serve / scenario / epoch records), the request journal(s), the
request-scoped trace timeline(s) and any post-mortem bundles — as one
summary: per-class SLO attainment, the shed breakdown, the SLO alert
transition log (``kind: "slo_alert"`` records — the burn-rate state
machine's journal) and the per-scenario TTFT attribution block with its
top-K slow-request autopsy table, the restart
timeline (journal ``restart`` events with their monotonic ticks), TTFT /
TPOT quantiles, KV-drift, the multi-tenant adapter block (bank residency
bytes, swaps, adapter-affinity routing hits, per-tenant completions and
the per-journal tenant split), the disaggregated-pool block (per-role replica/
queue/slot gauges plus the host offload tier's demote/promote/prefetch
counters and the per-journal snap-cause split), the training-resilience
block (the self-healing sentinel's anomaly/rollback/quarantine counters
and per-event timeline from the epoch records), and the bundle inventory. ``--json`` emits the
same content as one machine-readable object.

This module is deliberately stdlib-only (``json``/``os``/``glob``/
``argparse``) — the artifacts are plain JSONL and parsing them is the
whole job; no device, registry or engine state is touched. (Running it
via ``python -m`` still executes the package ``__init__``, which imports
jax — import :func:`collect`/:func:`render` directly for a jax-free
consumer.) Exit codes: 0 on success, 2 when the directory is missing or
holds nothing reportable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _read_jsonl(path: str) -> list[dict]:
    """Valid JSON-object lines of ``path`` (torn/corrupt lines skipped —
    a report renders what survived, it does not police durability)."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict):
                    out.append(rec)
    except OSError:
        pass
    return out


def _fmt(v, nd: int = 3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{round(v, nd):g}"
    return str(v)


def collect(outdir: str) -> dict:
    """Gather every artifact in ``outdir`` into one report dict — the
    single source both renderers (text and ``--json``) consume."""
    metrics = _read_jsonl(os.path.join(outdir, "metrics.jsonl"))
    serve = [r for r in metrics if r.get("kind") == "serve"]
    scenarios = [r for r in metrics if r.get("kind") == "scenario"]
    epochs = [r for r in metrics if r.get("kind") == "epoch"]
    # SLO alert transitions (one joinable row each, telemetry/alerts.py)
    # and the per-scenario TTFT attribution blocks (telemetry/
    # attribution.py) — both land in metrics.jsonl via run_scenario
    slo_alerts = [r for r in metrics if r.get("kind") == "slo_alert"]
    attribution = {r.get("scenario"): r["attribution"]
                   for r in scenarios if r.get("attribution")}

    journals = {}
    for path in sorted(glob.glob(os.path.join(outdir, "journal*.jsonl"))):
        events = _read_jsonl(path)
        counts: dict[str, int] = {}
        snap_why: dict[str, int] = {}
        adapters: dict[str, int] = {}
        for ev in events:
            counts[ev.get("ev", "?")] = counts.get(ev.get("ev", "?"), 0) + 1
            if ev.get("ev") == "snap":
                # migration cause ("failure" vs "handoff"); reason-less
                # snaps predate the field and count as "-"
                why = ev.get("why") or "-"
                snap_why[why] = snap_why.get(why, 0) + 1
            if ev.get("ev") == "submit" and ev.get("adp"):
                # tenant split: the adp field is absent for base-model
                # requests and in pre-adapter journals
                adapters[ev["adp"]] = adapters.get(ev["adp"], 0) + 1
        journals[os.path.basename(path)] = {
            "events": len(events),
            "by_kind": dict(sorted(counts.items())),
            "snap_why": dict(sorted(snap_why.items())),
            "adapters": dict(sorted(adapters.items())),
            "restarts": [
                {"n": ev.get("n"), "cause": ev.get("cause"),
                 "degraded": ev.get("degraded"), "tick": ev.get("tick")}
                for ev in events if ev.get("ev") == "restart"],
        }

    timelines = {}
    for path in sorted(glob.glob(
            os.path.join(outdir, "request_timeline*.jsonl"))):
        rows = _read_jsonl(path)
        timelines[os.path.basename(path)] = {
            "events": len(rows),
            "requests": len({r.get("rid") for r in rows
                             if r.get("rid") is not None}),
            "incarnations": len({r.get("inc", 0) for r in rows}),
        }

    traces = {}
    for path in sorted(glob.glob(os.path.join(outdir, "serve_trace*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
            traces[os.path.basename(path)] = {
                "events": len(doc.get("traceEvents", []))}
        except (OSError, json.JSONDecodeError):
            traces[os.path.basename(path)] = {"events": None,
                                              "error": "unparseable"}

    bundles = []
    for path in sorted(glob.glob(os.path.join(outdir, "postmortem-*.json"))):
        try:
            with open(path) as f:
                b = json.load(f)
            bundles.append({
                "file": os.path.basename(path),
                "trigger": b.get("trigger"), "cause": b.get("cause"),
                "tick": b.get("tick"), "restarts": b.get("restarts"),
                "flight_rows": len(b.get("flight", [])),
                "requests": len(b.get("requests", [])),
            })
        except (OSError, json.JSONDecodeError):
            bundles.append({"file": os.path.basename(path),
                            "error": "unparseable"})

    # the training-resilience block (self-healing sentinel): counters are
    # cumulative WITHIN one process but reset when the run restarts
    # (graceful-preempt resume, a supervisor restart) — so totals are
    # summed across process generations (a counter DROPPING marks a new
    # generation), not read off the newest record, or a resumed clean run
    # would report "0 anomalies" above a non-empty anomaly timeline.
    sent_recs = [r for r in epochs if r.get("rollbacks") is not None]
    sentinel = None
    if sent_recs:
        generations: list[list[dict]] = [[]]
        prev = -1
        for r in sent_recs:
            v = r.get("anomalies", 0) or 0
            if generations[-1]:
                # primary boundary signal: the per-sentinel run id each
                # record carries; fallback for id-less records is a counter
                # DROP (which misses a resumed run that re-accumulates past
                # the previous generation before its first record — hence
                # the id)
                pk = generations[-1][-1].get("sentinel_run")
                key = r.get("sentinel_run")
                if (key != pk) if (key is not None or pk is not None) \
                        else v < prev:
                    generations.append([])
            generations[-1].append(r)
            prev = v

        def total(key):
            return sum(g[-1].get(key, 0) or 0 for g in generations)

        by_kind: dict[str, int] = {}
        for g in generations:
            for kind, n in (g[-1].get("by_kind") or {}).items():
                by_kind[kind] = by_kind.get(kind, 0) + int(n)
        # quarantine totals: a PERSISTENT journal (on disk next to the
        # checkpoints) carries the previous generation's count forward on
        # reload, so consecutive persistent generations dedup against the
        # predecessor's last value; an in-memory generation restarted from
        # zero contributes its whole count
        quarantined = 0
        prev_last = 0
        prev_persistent = False
        for g in generations:
            last = g[-1].get("quarantined_batches", 0) or 0
            persistent = bool(g[-1].get("quarantine_persistent"))
            carried = prev_last if (persistent and prev_persistent) else 0
            quarantined += max(0, last - carried)
            prev_last, prev_persistent = last, persistent
        sentinel = {
            "anomalies": total("anomalies"),
            "by_kind": by_kind,
            "rollbacks": total("rollbacks"),
            "quarantined_batches": quarantined,
            "snapshot_ring_bytes": sent_recs[-1].get(
                "snapshot_ring_bytes", 0),
            "events": [e for r in sent_recs
                       for e in (r.get("anomaly_events") or [])],
        }

    return {
        "dir": outdir,
        "serve": serve[-1] if serve else None,
        "scenarios": scenarios,
        "slo_alerts": slo_alerts,
        "attribution": attribution,
        "epochs": len(epochs),
        "last_epoch": epochs[-1] if epochs else None,
        "sentinel": sentinel,
        "journals": journals,
        "timelines": timelines,
        "traces": traces,
        "postmortems": bundles,
    }


def render(report: dict) -> str:
    """The human rendering of :func:`collect`'s output."""
    lines = [f"run report: {report['dir']}"]
    s = report["serve"]
    if s:
        lines.append(
            f"  serve: {s.get('requests_submitted', 0)} submitted, "
            f"{s.get('requests_completed', 0)} completed, "
            f"{s.get('tokens_generated', 0)} tokens "
            f"({_fmt(s.get('tokens_per_sec'))} tok/s)")
        lines.append(
            f"  latency: ttft p50/p95 {_fmt(s.get('ttft_ms_p50'))}/"
            f"{_fmt(s.get('ttft_ms_p95'))} ms, tpot p50/p95 "
            f"{_fmt(s.get('tpot_ms_p50'))}/{_fmt(s.get('tpot_ms_p95'))} ms, "
            f"occupancy {_fmt(s.get('slot_occupancy_mean'))}")
        if "restarts" in s:
            lines.append(
                f"  resilience: {s['restarts']} restart(s), "
                f"{s.get('recovered_requests', 0)} recovered, "
                f"{s.get('shed_total', 0)} shed {s.get('shed_by_reason', {})}"
                f", degraded={s.get('degraded', 0)}")
        if "fleet_replicas" in s:
            lines.append(
                f"  fleet: {s['fleet_replicas']} replica(s) in rotation, "
                f"{s.get('fleet_replica_losses', 0)} loss(es), "
                f"{s.get('fleet_migrations', 0)} migration(s), "
                f"{s.get('route_affinity_hits', 0)} affinity hit(s), "
                f"{s.get('fleet_scale_outs', 0)} scale-out(s), "
                f"{s.get('fleet_retired', 0)} retired, "
                f"{s.get('fleet_handoffs', 0)} handoff(s)")
        for pool, blk in sorted((s.get("pools") or {}).items()):
            lines.append(
                f"  pool {pool}: {blk.get('replicas', 0)} replica(s), "
                f"queue depth {blk.get('queue_depth', 0)}, "
                f"{blk.get('slots_active', 0)} slot(s) active")
        if "host_blocks" in s:
            lines.append(
                f"  host tier: {s['host_blocks']} block(s) resident "
                f"({s.get('host_bytes_resident', 0)} bytes), "
                f"{s.get('host_inflight_blocks', 0)} in flight, "
                f"{s.get('host_demotes', 0)} demote(s), "
                f"{s.get('host_promotes', 0)} promote(s), "
                f"{s.get('host_evictions', 0)} eviction(s), prefetch "
                f"{s.get('host_prefetch_hits', 0)} hit(s)/"
                f"{s.get('host_prefetch_misses', 0)} miss(es), "
                f"{s.get('host_transfer_bytes', 0)} bytes transferred")
        if "kv_drift_bytes" in s:
            ok = "OK" if s["kv_drift_bytes"] == 0 else "NONZERO"
            lines.append(
                f"  kv drift: live-vs-model {s['kv_drift_bytes']} bytes "
                f"[{ok}] (predicted {s.get('kv_bytes_predicted')}, "
                f"resident {s.get('kv_bytes_resident', 'n/a')})")
        if "adapter_resident_bytes" in s:
            lines.append(
                f"  adapters: {s['adapter_resident_bytes']} bytes "
                f"resident (bank), {s.get('adapter_swaps', 0)} swap(s), "
                f"{s.get('route_adapter_affinity_hits', 0)} "
                f"adapter-affinity hit(s)")
            for tenant, n in sorted(
                    (s.get("per_adapter_completed") or {}).items()):
                lines.append(f"    tenant {tenant}: {n} completed")
        for cls, blk in sorted((s.get("per_class") or {}).items()):
            lines.append(
                f"  class {cls}: {blk.get('completed', 0)} completed, "
                f"{blk.get('shed', 0)} shed, ttft p95 "
                f"{_fmt(blk.get('ttft_ms_p95'))} ms, tpot p95 "
                f"{_fmt(blk.get('tpot_ms_p95'))} ms")
    for scen in report["scenarios"]:
        verdict = "PASS" if scen.get("slo_ok") else "FAIL"
        lines.append(
            f"  scenario {scen.get('scenario')} [{verdict}]: "
            f"{scen.get('completed')}/{scen.get('n_requests')} completed, "
            f"{scen.get('shed', 0)} shed"
            + (f", {scen['restarts']} restart(s)"
               if "restarts" in scen else ""))
        fl = scen.get("fleet")
        if fl:
            split = (f" = {fl['prefill_replicas']} prefill + "
                     f"{fl.get('replicas', 0) - fl['prefill_replicas']} "
                     f"decode" if fl.get("prefill_replicas") else "")
            lines.append(
                f"    fleet: {fl.get('replicas')} replica(s){split} "
                f"(route {fl.get('route')}), "
                f"{fl.get('replica_losses', 0)} loss(es), "
                f"{fl.get('migrations', 0)} migration(s), "
                f"{fl.get('affinity_hits', 0)} affinity hit(s), "
                f"{fl.get('scale_outs', 0)} scale-out(s), "
                f"{fl.get('retired', 0)} retired"
                + (f", {fl['handoffs']} handoff(s)"
                   if "handoffs" in fl else ""))
        ht = scen.get("host_tier")
        if ht:
            lines.append(
                f"    host tier: {ht.get('host_cache_blocks')} block "
                f"capacity, {ht.get('demotes', 0)} demote(s), "
                f"{ht.get('promotes', 0)} promote(s), "
                f"{ht.get('host_evictions', 0)} eviction(s), prefetch "
                f"{ht.get('prefetch_hits', 0)} hit(s)/"
                f"{ht.get('prefetch_misses', 0)} miss(es), "
                f"{ht.get('transfer_bytes', 0)} bytes transferred")
        for cls, att in sorted((scen.get("slo") or {}).items()):
            gates = [f"{k.split('_')[0]} {_fmt(att[k])}"
                     for k in ("ttft_attainment", "tpot_attainment")
                     if k in att]
            lines.append(f"    {cls}: attainment {', '.join(gates)} "
                         f"[{'ok' if att.get('ok') else 'MISS'}]")
    for rec in report.get("slo_alerts") or []:
        lines.append(
            f"  alert {rec.get('alert')}: {rec.get('from')} -> "
            f"{rec.get('to')} @tick {rec.get('tick')} (burn fast/slow "
            f"{_fmt(rec.get('burn_fast'))}/{_fmt(rec.get('burn_slow'))})"
            + (f" [{rec['scenario']}]" if rec.get("scenario") else ""))
    for scen_name, att in sorted((report.get("attribution") or {}).items()):
        lines.append(
            f"  attribution [{scen_name}]: {att.get('requests', 0)} "
            f"request(s) folded, {att.get('recovered', 0)} recovered, "
            f"max drift {_fmt(att.get('max_abs_drift_ms'), 6)} ms")
        for cls, blk in sorted((att.get("by_class") or {}).items()):
            comps = ", ".join(
                f"{c} {_fmt(v)}" for c, v in
                (blk.get("components_ms_mean") or {}).items())
            lines.append(
                f"    class {cls}: mean ttft "
                f"{_fmt(blk.get('ttft_ms_mean'))} ms = {comps}")
        top = att.get("top_slow") or []
        if top:
            lines.append("    top slow requests (TTFT autopsy):")
            lines.append(f"      {'rid':>5}  {'class':<12} "
                         f"{'ttft_ms':>9}  components")
            for a in top:
                comps = " ".join(
                    f"{c}={_fmt(v)}" for c, v in
                    (a.get("components_ms") or {}).items())
                lines.append(
                    f"      {a.get('rid'):>5}  {str(a.get('cls')):<12} "
                    f"{_fmt(a.get('ttft_ms')):>9}  {comps}"
                    + (" [recovered]" if a.get("recovered") else ""))
    for name, j in report["journals"].items():
        lines.append(f"  journal {name}: {j['events']} events "
                     f"{j['by_kind']}")
        why = {k: v for k, v in (j.get("snap_why") or {}).items()
               if k != "-"}
        if why:
            lines.append(f"    snap cause: {why}")
        if j.get("adapters"):
            lines.append(f"    tenants: {j['adapters']}")
        for r in j["restarts"]:
            lines.append(
                f"    restart #{r['n']} @tick {_fmt(r['tick'])} "
                f"cause {r['cause']} degraded={r['degraded']}")
    for name, t in report["timelines"].items():
        lines.append(f"  timeline {name}: {t['events']} events over "
                     f"{t['requests']} request(s), "
                     f"{t['incarnations']} incarnation(s)")
    for name, t in report["traces"].items():
        lines.append(f"  trace {name}: {_fmt(t.get('events'))} Chrome "
                     f"events" + (" [UNPARSEABLE]" if t.get("error")
                                  else ""))
    for b in report["postmortems"]:
        if b.get("error"):
            lines.append(f"  postmortem {b['file']}: UNPARSEABLE")
        else:
            lines.append(
                f"  postmortem {b['file']}: {b['trigger']} @tick "
                f"{_fmt(b['tick'])} ({b['cause']}), "
                f"{b['flight_rows']} flight rows, "
                f"{b['requests']} request states")
    if report["epochs"]:
        le = report["last_epoch"]
        lines.append(
            f"  training: {report['epochs']} epoch record(s), last: "
            f"step p50 {_fmt(le.get('step_time_ms_p50'))} ms"
            + (f", bubble model {_fmt(le.get('bubble_fraction'))}"
               if le.get("bubble_fraction") is not None else "")
            + (f" measured {_fmt(le.get('bubble_fraction_measured'))}"
               f" drift {_fmt(le.get('bubble_drift'))}"
               if le.get("bubble_drift") is not None else ""))
    sent = report.get("sentinel")
    if sent:
        ok = "OK" if sent["anomalies"] == sent["rollbacks"] == 0 else \
            "SELF-HEALED"
        lines.append(
            f"  self-healing: {sent['anomalies']} anomal"
            f"{'y' if sent['anomalies'] == 1 else 'ies'} {sent['by_kind']}"
            f", {sent['rollbacks']} rollback(s), "
            f"{sent['quarantined_batches']} quarantined batch(es), ring "
            f"{sent['snapshot_ring_bytes']} bytes [{ok}]")
        for e in sent["events"]:
            lines.append(
                f"    anomaly @step {e.get('step')} [{e.get('kind')}] "
                f"epoch {e.get('epoch')} batch {e.get('batch')} value "
                f"{_fmt(e.get('value'))} -> rollback + quarantine")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m simple_distributed_machine_learning_tpu."
             "telemetry.report",
        description="Render one run summary from a telemetry directory "
                    "(metrics.jsonl + journal + trace + post-mortems).")
    ap.add_argument("--dir", required=True,
                    help="the run's --telemetry-dir")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object instead of "
                         "the human rendering")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.dir):
        print(f"report: no such directory: {args.dir}", file=sys.stderr)
        return 2
    report = collect(args.dir)
    # "reportable" = ANY artifact family present — a crash can die before
    # metrics.jsonl exists while the trace/timeline/bundles (exactly the
    # forensic case) are already on disk
    if (report["serve"] is None and not report["scenarios"]
            and not report["epochs"] and not report["journals"]
            and not report["timelines"] and not report["traces"]
            and not report["postmortems"]):
        print(f"report: nothing reportable under {args.dir} "
              f"(no metrics.jsonl records, journals or traces)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":                      # pragma: no cover - CLI
    sys.exit(main())
