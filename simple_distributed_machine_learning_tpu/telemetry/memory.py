"""Memory telemetry: live-array byte totals and per-device memory stats.

Two complementary sources, both sampled per epoch (they walk every live
buffer / query the runtime — not per-step material):

- ``jax.live_arrays()`` — every ``jax.Array`` the process still references,
  summed by ``nbytes``. This is the *program's* footprint (params, optimizer
  state, pinned input pools) and works on every backend including the
  virtual-CPU test meshes.
- ``device.memory_stats()`` — the *runtime allocator's* view (``bytes_in_use``,
  ``peak_bytes_in_use``, ...) where the backend exposes one (TPU/GPU do; CPU
  returns nothing) — the number an OOM postmortem needs.
"""

from __future__ import annotations

import jax


def live_array_bytes() -> int:
    """Total bytes of every live ``jax.Array`` in the process."""
    total = 0
    try:
        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 - introspection is strictly best-effort
        return 0
    for a in arrays:
        try:
            total += int(a.nbytes)
        except Exception:  # noqa: BLE001 - deleted/donated buffers mid-walk
            continue
    return total


def device_memory_stats() -> dict[str, dict]:
    """``device id -> memory_stats()`` for devices that report any.

    Values are left as the backend reports them (ints); backends without an
    allocator report (XLA:CPU) simply contribute nothing.
    """
    out: dict[str, dict] = {}
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 - plugin-dependent surface
            stats = None
        if stats:
            out[str(d.id)] = {k: int(v) for k, v in stats.items()
                              if isinstance(v, (int, float))}
    return out


def sample(registry=None) -> dict:
    """One memory sample: returns the epoch-record block and mirrors it into
    ``registry`` gauges (``live_array_bytes``; ``device_bytes_in_use`` and
    ``device_peak_bytes_in_use`` labeled per device) when one is given."""
    live = live_array_bytes()
    per_dev = device_memory_stats()
    if registry is not None:
        registry.gauge("live_array_bytes").set(live)
        for dev, stats in per_dev.items():
            for key, gname in (("bytes_in_use", "device_bytes_in_use"),
                               ("peak_bytes_in_use",
                                "device_peak_bytes_in_use")):
                if key in stats:
                    registry.gauge(gname, labels={"device": dev}) \
                        .set(stats[key])
    rec = {"live_array_bytes": live}
    if per_dev:
        rec["device_memory"] = per_dev
    return rec
