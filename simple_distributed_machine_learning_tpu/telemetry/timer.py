"""Step timing: compile-vs-steady split, latency quantiles, throughput.

On an async-dispatch runtime, ``time.perf_counter()`` around a step call
times the ENQUEUE, not the execution — and the first executed step buries
trace+compile inside its wall time. :class:`StepTimer` owns both problems:

- a *window* is the wall-clock interval between two device fences
  (``jax.block_until_ready`` on something the step returned), covering
  ``steps`` dispatched steps — the only host-side measurement that equals
  device time;
- the FIRST window ever recorded is the compile window (trace + XLA compile
  + first step) and is kept out of the steady-state histogram, exactly like
  ``Trainer.train_epoch``'s first-batch ``block_until_ready`` discipline;
- steady windows feed a weighted histogram of per-step latency
  (p50/p95/max), plus running examples/sec and tokens/sec over the steady
  time only.

``compiled_cost_stats`` is the optional ``jax.stages`` sibling: static
FLOPs/bytes of the compiled executable, when the backend exposes a cost
model. It AOT-compiles (not served from the jit cache on this jax line), so
it is opt-in, never on a hot path.
"""

from __future__ import annotations

from simple_distributed_machine_learning_tpu.telemetry.registry import (
    Histogram,
    MetricsRegistry,
)


class StepTimer:
    """Accumulates fenced timing windows; see module docstring.

    ``registry``: when given, the per-step latency histogram is registered
    there as ``step_time_ms`` (so it rides every snapshot / Prometheus
    export); otherwise a private histogram is used.
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 name: str = "step_time_ms") -> None:
        self.compile_time_s: float | None = None
        self._hist = (registry.histogram(name) if registry is not None
                      else Histogram(name))
        self._steady_s = 0.0
        self._examples = 0.0
        self._tokens = 0.0

    def record_window(self, seconds: float, steps: int = 1,
                      examples: float = 0, tokens: float = 0) -> None:
        """One fence-to-fence interval covering ``steps`` dispatched steps.

        The first window ever recorded is taken as the compile window and
        excluded from the steady statistics.
        """
        if steps < 1:
            return
        if self.compile_time_s is None:
            self.compile_time_s = float(seconds)
            return
        self._hist.observe(seconds / steps * 1e3, n=steps)
        self._steady_s += float(seconds)
        self._examples += examples
        self._tokens += tokens

    # -- steady-state statistics ------------------------------------------

    @property
    def steps(self) -> int:
        return int(self._hist.count)

    @property
    def examples_per_sec(self) -> float:
        return self._examples / self._steady_s if self._steady_s > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self._tokens / self._steady_s if self._steady_s > 0 else 0.0

    def quantile_ms(self, q: float) -> float | None:
        return self._hist.quantile(q)

    def summary(self) -> dict:
        """The metric record block every consumer (trainer epoch emission,
        bench rows) embeds; ms values rounded to keep JSONL lines readable."""
        r3 = (lambda v: None if v is None else round(v, 3))
        return {
            "compile_time_s": r3(self.compile_time_s),
            "steps": self.steps,
            "step_time_ms_p50": r3(self._hist.quantile(0.5)),
            "step_time_ms_p95": r3(self._hist.quantile(0.95)),
            "step_time_ms_max": r3(self._hist.max),
            "examples_per_sec": round(self.examples_per_sec, 1),
            "tokens_per_sec": (round(self.tokens_per_sec, 1)
                               if self._tokens else None),
        }


def compiled_cost_stats(jitted_fn, *abstract_args, **abstract_kwargs
                        ) -> dict | None:
    """Static cost stats of the compiled executable via ``jax.stages``.

    Returns ``{"flops": ..., "bytes_accessed": ...}`` (keys present only when
    the backend's cost model reports them), or ``None`` when anything in the
    lower/compile/cost path is unavailable — an optional signal, never a
    gate. Note this AOT-compiles the function for the given abstract shapes;
    on this jax line that compilation is NOT shared with the jit cache, so
    call it off the hot path (or not at all on large models).
    """
    try:
        compiled = jitted_fn.lower(*abstract_args, **abstract_kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not isinstance(cost, dict):
            return None
        out = {}
        if "flops" in cost:
            out["flops"] = float(cost["flops"])
        for k in ("bytes accessed", "bytes_accessed"):
            if k in cost:
                out["bytes_accessed"] = float(cost[k])
                break
        return out or None
    except Exception:  # noqa: BLE001 - strictly best-effort introspection
        return None
