"""Request-scoped serve tracing: async span timelines keyed by rid.

Aggregate serving telemetry (``serve/metrics.py``) answers "how is the
fleet doing"; this module answers "where did *this request's* time go" —
submit/journal, queue wait, each prefill chunk, every decode/speculative
tick (tokens emitted, spec accept counts), preemption and resume, deadline
or backpressure shedding, crash re-admission, completion. Two artifacts per
run, one recorder:

- a Chrome-trace JSON (``serve_trace.json``) of *async* begin/end events
  (:meth:`Tracer.async_begin`/``async_end``, ``b``/``e`` phases) keyed by
  the request id, so arbitrarily overlapping request timelines render as
  parallel tracks in Perfetto instead of nesting wrongly;
- a per-request JSONL timeline (``request_timeline.jsonl``): one line per
  event, ``{"ev": ..., "rid": ..., "t": ..., "inc": ...}`` — the joinable,
  greppable form the report CLI (``python -m ...telemetry.report``) and
  post-mortem tooling consume.

**The rid is the trace id.** The journal assigns rids once per request and
recovery preserves them, so spans JOIN across supervisor restarts (the
recorder outlives the engine: the crash ends the open sub-span with
``crashed``, re-admission opens a fresh ``queue`` span under the same id,
and ``inc`` — the engine incarnation — tells the two apart) and across
cold restarts (the timeline file opens in append mode when
``fresh=False``; a recovered rid's new events land after its previous
process's, same key).

**The recorder never reads a clock.** Every event is stamped with a
timestamp the engine ALREADY read for its own accounting (TTFT endpoints,
chunk timing, retirement). Under ``resilience/scenarios.py``'s
``VirtualClock`` — where every read advances simulated time — that is what
keeps the exact-pinned scenario numbers and byte-identical reports
unchanged whether tracing is on or off; it is also why tracing-off costs
literally nothing on the hot path (one ``is None`` test per site).
Events with no clock read of their own (paged admission, preemption,
crash) are stamped with the engine's *most recent* read
(``InferenceEngine._now``) — at-most-one-tick-stale by construction.
"""

from __future__ import annotations

import json
import os

from simple_distributed_machine_learning_tpu.telemetry.tracing import Tracer

TRACE_FILE = "serve_trace.json"
TIMELINE_FILE = "request_timeline.jsonl"


class ServeTrace:
    """One serving run's request-scoped trace recorder; see module
    docstring. Attach via ``InferenceEngine(trace=...)`` or
    ``ServeSupervisor(trace=...)`` (the supervisor re-attaches it to every
    rebuilt engine, which is what joins spans across restarts).

    ``outdir=None`` keeps everything in memory (tests);
    ``fresh=False`` appends to an existing timeline file — the cold-restart
    join — instead of truncating it.
    """

    def __init__(self, outdir: str | None = None, *, fresh: bool = True,
                 suffix: str = "",
                 process_name: str = "sdml-serve") -> None:
        # pid pinned to 0: a virtual-clock trace must be byte-identical
        # across runs AND machines, so no real pid may leak into it
        self.tracer = Tracer(process_name=process_name, pid=0)
        self.outdir = outdir
        # per-run artifact names: `suffix` keeps several traced runs (the
        # scenario catalog) apart inside one telemetry dir
        self.trace_file = TRACE_FILE.replace(".json", f"{suffix}.json")
        self.timeline_file = TIMELINE_FILE.replace(".jsonl",
                                                   f"{suffix}.jsonl")
        self.incarnation = 0
        self.n_events = 0
        self._rows: list[dict] = []
        self._phase: dict[int, str] = {}     # rid -> open sub-span name
        self._open: set[int] = set()         # rids with an open request span
        self._tl = None
        if outdir:
            os.makedirs(outdir, exist_ok=True)
            path = os.path.join(outdir, self.timeline_file)
            self._tl = open(path, "w" if fresh else "a")

    # -- event plumbing ----------------------------------------------------

    def _row(self, ev: str, rid, t: float, **fields) -> None:
        row = {"ev": ev, "rid": rid, "t": round(float(t), 6),
               "inc": self.incarnation, **fields}
        self.n_events += 1
        if self._tl is not None:
            # streaming mode: the file IS the timeline — rows are not also
            # retained in memory, so a long-running serve loop's footprint
            # stays flat (the Chrome tracer's event list is the one
            # unavoidable accumulation: its single-file format needs every
            # event at write time)
            self._tl.write(json.dumps(row, separators=(",", ":")) + "\n")
        else:
            self._rows.append(row)

    def _begin(self, rid: int, name: str, t: float, **attrs) -> None:
        self.tracer.async_begin(name, rid, ts_us=t * 1e6, cat="req",
                                inc=self.incarnation, **attrs)

    def _end(self, rid: int, name: str, t: float, **attrs) -> None:
        self.tracer.async_end(name, rid, ts_us=t * 1e6, cat="req",
                              inc=self.incarnation, **attrs)

    def _close_phase(self, rid: int, t: float, **attrs) -> None:
        """End ``rid``'s open sub-span, if any — the no-orphan-ends
        invariant: an ``e`` event exists only where a ``b`` preceded it."""
        phase = self._phase.pop(rid, None)
        if phase is not None:
            self._end(rid, phase, t, **attrs)

    def _open_phase(self, rid: int, name: str, t: float, **attrs) -> None:
        self._close_phase(rid, t)
        self._phase[rid] = name
        self._begin(rid, name, t, **attrs)

    # -- engine-driven events ---------------------------------------------

    def on_submit(self, r, t: float) -> None:
        """A request entered the system (possibly journaled first): open
        its request span and its ``queue`` sub-span at the submit/arrival
        timestamp."""
        self._open.add(r.rid)
        self._begin(r.rid, "request", t, cls=r.cls, priority=r.priority,
                    prompt_len=int(r.prompt.shape[0]),
                    max_new=r.max_new_tokens)
        self._open_phase(r.rid, "queue", t)
        self._row("submit", r.rid, t, cls=r.cls,
                  prompt_len=int(r.prompt.shape[0]))

    def on_gate(self, r, t: float) -> None:
        """Boarding is gated on an in-flight host->HBM prefetch upload
        covering this request's prefix (``PagedKVPool.prefetch_blocked``):
        the queue wait ends here and the ``prefetch`` wait begins — the
        split that lets the attribution fold (``telemetry/attribution.py``)
        separate "waiting for a slot" from "waiting for the upload".
        Emitted once per blocked episode, stamped with the engine's most
        recent clock read (no read of its own)."""
        self._open_phase(r.rid, "prefetch", t)
        self._row("gate", r.rid, t)

    def on_admit(self, r, t: float, slot: int) -> None:
        """Boarded a slot: queue wait ends, prefill begins. Paged admission
        performs no clock read of its own, so ``t`` is the engine's most
        recent read (at most one tick stale — documented imprecision, not
        a perturbation)."""
        self._open_phase(r.rid, "prefill", t, slot=slot)
        self._row("admit", r.rid, t, slot=slot)

    def on_prefill_chunk(self, r, t0: float, t1: float, p0: int,
                         n: int) -> None:
        self.tracer.async_instant("prefill_chunk", r.rid, ts_us=t1 * 1e6,
                                  cat="req", p0=p0, n=n)
        self._row("prefill_chunk", r.rid, t1, p0=p0, n=n,
                  ms=round((t1 - t0) * 1e3, 3))

    def on_first_token(self, r, t: float) -> None:
        """The TTFT endpoint: prefill ends, decode begins."""
        ttft = r.ttft_s
        self._open_phase(r.rid, "decode", t)
        self._row("first_token", r.rid, t,
                  ttft_ms=None if ttft is None else round(ttft * 1e3, 3))

    def on_resume(self, r, t: float) -> None:
        """A preempted/recovered request reseated on its stored newest
        token — K/V rebuilt, decode continues."""
        self._open_phase(r.rid, "decode", t, resumed=True)
        self._row("resume", r.rid, t, tokens=len(r.tokens))

    def on_tick_tokens(self, r, t: float, n: int, proposed: int = 0,
                       accepted: int = 0) -> None:
        """One decode/speculative tick's emissions for one request."""
        attrs = {"tokens": n}
        if proposed:
            attrs.update(proposed=proposed, accepted=accepted)
        self.tracer.async_instant("tick", r.rid, ts_us=t * 1e6, cat="req",
                                  **attrs)
        self._row("tick", r.rid, t, **attrs)

    def on_preempt(self, r, t: float) -> None:
        self._open_phase(r.rid, "queue", t, preempted=True)
        self._row("preempt", r.rid, t, tokens=len(r.tokens))

    def on_finish(self, r, t: float, reason: str) -> None:
        self._close_phase(r.rid, t)
        if r.rid in self._open:
            self._open.discard(r.rid)
            self._end(r.rid, "request", t, reason=reason,
                      tokens=len(r.tokens))
        self._row("done", r.rid, t, reason=reason, tokens=len(r.tokens))

    def on_shed(self, r, t: float, reason: str) -> None:
        """A structured rejection (deadline / backpressure / class): the
        request span closes with the shed reason; an admission-time shed
        that never opened a span just logs the row."""
        self._close_phase(r.rid, t)
        if r.rid in self._open:
            self._open.discard(r.rid)
            self._end(r.rid, "request", t, shed=reason)
        self._row("shed", r.rid, t, reason=reason)

    # -- supervisor-driven events -----------------------------------------

    def on_crash(self, t: float, rids, cause: str) -> None:
        """The engine died: every in-flight request's open sub-span ends
        NOW with ``crashed`` (no orphan begins survive the incarnation),
        the request spans stay open — they join across the rebuild."""
        for rid in sorted(rids):
            self._close_phase(rid, t, crashed=True)
            self._row("crash", rid, t, cause=cause)

    def on_restart(self, t: float, n: int, degraded: bool,
                   cause: str) -> None:
        self.incarnation = int(n)
        self.tracer.async_instant("restart", "supervisor", ts_us=t * 1e6,
                                  cat="supervisor", n=n, degraded=degraded,
                                  cause=cause)
        self._row("restart", None, t, n=n, degraded=degraded, cause=cause)

    def on_migrate(self, r, t: float, src: int, dst: int) -> None:
        """Cross-replica migration (``serve/fleet.py``): ``r`` left dead
        replica ``src`` and is being adopted by replica ``dst``. The rid
        is fleet-unique, so the same recorder — attached to EVERY
        replica's supervisor — joins the request's spans across replicas
        exactly as it joins them across one supervisor's restarts; the
        adopting engine's ``restore`` fires ``on_readmit`` right after
        this row."""
        self.tracer.async_instant("migrate", r.rid, ts_us=t * 1e6,
                                  cat="req", src=src, dst=dst)
        self._row("migrate", r.rid, t, src=src, dst=dst,
                  tokens=len(r.tokens))

    def on_readmit(self, r, t: float) -> None:
        """Journal recovery re-enqueued ``r`` into the rebuilt engine. On a
        cold restart this recorder never saw the submit, so the request
        span opens here (``recovered``) — pairing stays well-formed within
        every trace file."""
        if r.rid not in self._open:
            self._open.add(r.rid)
            self._begin(r.rid, "request", t, cls=r.cls,
                        priority=r.priority, recovered=True,
                        prompt_len=int(r.prompt.shape[0]),
                        max_new=r.max_new_tokens)
        self._open_phase(r.rid, "queue", t, readmitted=True)
        self._row("readmit", r.rid, t, tokens=len(r.tokens))

    # -- artifacts ---------------------------------------------------------

    @property
    def rows(self) -> list[dict]:
        """The timeline rows: read back from the streamed file when one
        exists (memory holds nothing in streaming mode), else the
        in-memory list."""
        if self._tl is None:
            return list(self._rows)
        if not self._tl.closed:
            self._tl.flush()
        path = os.path.join(self.outdir, self.timeline_file)
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    def to_chrome_trace(self) -> dict:
        return self.tracer.to_chrome_trace()

    def flush(self) -> None:
        """Rewrite the Chrome trace and flush the timeline stream."""
        if self._tl is not None and not self._tl.closed:
            self._tl.flush()
        if self.outdir:
            self.tracer.write(os.path.join(self.outdir, self.trace_file))

    def close(self) -> None:
        self.flush()
        if self._tl is not None and not self._tl.closed:
            self._tl.close()
