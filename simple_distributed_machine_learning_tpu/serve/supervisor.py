"""The serve supervisor: crash-restartable serving with overload control.

PR 7 made *training* elastic; this module gives the inference engine the
same production shape.  :class:`ServeSupervisor` wraps an
:class:`~.engine.InferenceEngine` behind the engine's own duck-typed
surface (``submit``/``step``/``drain``/``busy``/``requests``), adding the
three things a single-process engine lacks:

**Crash recovery (RUNNING → RECOVERING → RUNNING | DEGRADED).**  Every
submission and every emitted token is journaled (``serve/journal.py``,
fsync'd, with the request's live PRNG key state riding on each token
record).  A recoverable engine failure — an injected ``engine-crash`` /
``wedged-device`` / ``host-kill`` at the ``serve.tick`` or ``serve.admit``
sites, or anything else in :data:`RECOVERABLE` leaking out of a tick —
discards the engine wholesale, rebuilds a fresh one through the caller's
``factory(degraded)`` and re-admits every in-flight request *from the
journal alone* through the PR-7 preempt/resume machinery: re-admission
prefills ``resume_seq = prompt + tokens[:-1]`` with the sample and key
advance discarded, then reseats on the last journaled token with the
journaled key state — so a request's full token stream equals the
uninterrupted run's, across any number of restarts (double crashes, i.e.
a crash during recovery, included).  ``max_restarts`` bounds the loop
(:class:`~..resilience.supervisor.RestartBudgetExceeded`), and
``degrade_after`` restarts flips later rebuilds to the DEGRADED layout —
:func:`engine_factory`'s rule: speculation off, tensor parallelism off,
dense slot rows (the same transform ``analysis.programs.degraded_spec``
keeps lint-clean in the program registry).

**Deadlines.**  ``submit(..., ttft_deadline_s=, deadline_s=)`` (or the
supervisor-wide defaults) bound time-to-first-token and total latency.
Expired requests are shed at tick boundaries with a structured rejection
(``state = SHED``, ``finish_reason = "deadline"``) and their slot/block
budget refunded the same release path retirement uses — an expired
request never occupies capacity a live one could use.

**Overload control.**  :class:`OverloadPolicy` gates admission before the
engine sees a request: per-class token buckets (``class_rates``) police
each tenant's arrival rate, ``max_queue_depth`` bounds the queue (a
higher-priority arrival sheds the lowest-priority newest queued victim
first; otherwise the arrival itself is shed), and sustained overload
(queue depth past ``degrade_queue_depth``, with hysteresis) enters the
load-degraded mode where best-effort traffic (priority ≤
``degraded_priority_floor``) is refused outright — graceful degradation
before any SLO class starves.  Every shed lands in
``serve_shed_total{reason=deadline|backpressure|class}``.

Delivery semantics across a crash: the token LIST on a handle is
exactly-once (recovery truncates to the journaled prefix and the decode
re-emits the identical tokens); the ``on_token`` callback is at-least-once
at crash boundaries (a token emitted between the journal write and the
client ack replays).  Sampled SPECULATIVE streams add one caveat: a
multi-token speculative tick journals under the tick's single key state,
so their cold-restart recovery is tick-atomic (``journal.py::log_token``'s
caveat note) — every in-process recovery and every greedy stream is
unconditionally bit-exact.
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from simple_distributed_machine_learning_tpu.resilience.faults import (
    DeviceWedged,
    EngineCrash,
    HostLost,
)
from simple_distributed_machine_learning_tpu.serve.journal import (
    RequestJournal,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    SHED,
    Request,
    validate_request,
)

# supervisor states (the machine in the module docstring / ARCHITECTURE.md)
RUNNING = "running"
RECOVERING = "recovering"
DEGRADED = "degraded"
FAILED = "failed"

#: engine failures the supervisor restarts through — the engine (pool
#: buffers + host bookkeeping) is rebuilt from scratch and in-flight
#: requests recover from the journal.  Anything else is a bug in the
#: serving code and propagates un-retried.
RECOVERABLE = (EngineCrash, DeviceWedged, HostLost)


@dataclasses.dataclass(frozen=True)
class OverloadPolicy:
    """Admission-control knobs; ``OverloadPolicy()`` disables them all.

    ``class_rates`` maps a traffic-class name to a ``(rate_per_s, burst)``
    token bucket — submissions beyond the bucket shed with reason
    ``"class"``.  ``max_queue_depth`` bounds the scheduler queue: at the
    bound, an arrival strictly higher-priority than some queued request
    sheds the lowest-priority newest-queued victim (reason
    ``"backpressure"``) and boards; otherwise the arrival itself sheds.
    ``degrade_queue_depth``/``recover_queue_depth`` are the load-degraded
    hysteresis: past the high watermark, requests at priority ≤
    ``degraded_priority_floor`` are refused (reason ``"class"``) until the
    queue drains to the low watermark."""

    max_queue_depth: int | None = None
    class_rates: dict | None = None
    degrade_queue_depth: int | None = None
    recover_queue_depth: int = 0
    degraded_priority_floor: int = 0

    def __post_init__(self):
        if self.class_rates is not None:
            # defensive copy, normalized to plain tuples: ONE policy
            # instance is routinely shared by N supervisors (the fleet's
            # replica factory), so the stored mapping must not alias a
            # caller dict whose later mutation would silently retune — or
            # couple — every replica's admission control. Each supervisor
            # still keeps its own PER-INSTANCE bucket fills (_buckets);
            # tests/test_fleet.py pins that one replica's debit never
            # appears in another's.
            object.__setattr__(
                self, "class_rates",
                {cls: (float(rb[0]), float(rb[1]))
                 for cls, rb in self.class_rates.items()})
        if self.max_queue_depth is not None and self.max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{self.max_queue_depth}")
        if self.degrade_queue_depth is not None:
            if self.degrade_queue_depth < 1:
                raise ValueError(f"degrade_queue_depth must be >= 1, got "
                                 f"{self.degrade_queue_depth}")
            if self.recover_queue_depth >= self.degrade_queue_depth:
                raise ValueError(
                    f"recover_queue_depth {self.recover_queue_depth} must "
                    f"sit below degrade_queue_depth "
                    f"{self.degrade_queue_depth} (hysteresis, not a "
                    f"flapping threshold)")
        for cls, rb in (self.class_rates or {}).items():
            rate, burst = rb
            if rate <= 0 or burst < 1:
                raise ValueError(
                    f"class {cls!r}: token bucket needs rate > 0 and "
                    f"burst >= 1, got ({rate}, {burst})")


def engine_factory(stages, cfg, *, metrics=None, clock=time.monotonic,
                   scheduler=None, mesh=None, draft_stages=None,
                   draft_cfg=None, spec_k: int = 0,
                   adapter_rank: int = 0, adapter_host: dict | None = None,
                   **kw):
    """The standard ``factory(degraded) -> InferenceEngine`` closure.

    Non-degraded builds get the full deployment (paged knobs, TP mesh,
    speculative draft) exactly as passed; ``degraded=True`` applies the
    fallback rule — ``spec_k → 0``, ``tp → 1``, dense slot rows — the
    layout ``analysis.programs.degraded_spec`` mirrors so the program
    registry proves the fallback lint-clean before any crash needs it.
    The fallback stays bit-exact for everything except *sampled* requests
    that were being served speculatively (dense vs paged vs plain-decode
    streams all equal the solo decode; sampled speculative streams are
    deterministic but consume the key streams differently).

    ``adapter_rank > 0`` turns on multi-tenant LoRA serving: every build
    (degraded ones included — the fallback drops layout/speed features,
    never tenants) gets a FRESH :class:`~.adapters.AdapterStore` over one
    SHARED ``adapter_host`` dict, so registered adapters survive crash
    rebuilds while device residency honestly resets with the engine.

    ``scheduler`` must be a CLASS/factory (each rebuilt engine constructs
    its own instance over its own pool); ``metrics``/``clock`` are shared
    across rebuilds so counters and timelines stay continuous.
    """
    from simple_distributed_machine_learning_tpu.serve.engine import (
        InferenceEngine,
    )
    if adapter_rank > 0 and adapter_host is None:
        adapter_host = {}        # one dict across every rebuild

    def _adapter_kw(n_slots: int) -> dict:
        if adapter_rank <= 0:
            return {}
        from simple_distributed_machine_learning_tpu.serve.adapters import (
            AdapterStore,
        )
        return {"adapters": AdapterStore(cfg, adapter_rank, n_slots,
                                         host=adapter_host)}

    def factory(degraded: bool) -> InferenceEngine:
        n_slots = kw.get("n_slots", 4)
        if not degraded:
            return InferenceEngine(
                stages, cfg, metrics=metrics, clock=clock,
                scheduler=scheduler, mesh=mesh, draft_stages=draft_stages,
                draft_cfg=draft_cfg, spec_k=spec_k,
                **_adapter_kw(n_slots), **kw)
        dcfg = cfg
        if getattr(cfg, "n_tensor_parallel", 1) > 1:
            dcfg = dataclasses.replace(cfg, n_tensor_parallel=1)
        dkw = {k: v for k, v in kw.items()
               if k not in ("block_size", "n_blocks", "prefill_chunk",
                            "kv_layout", "attn_kernel",
                            "host_cache_blocks", "prefetch_ticks")}
        from simple_distributed_machine_learning_tpu.models.gpt import (
            _is_quantized_dtype,
        )
        if _is_quantized_dtype(dkw.get("cache_dtype")):
            # quantized blocks (and the fused kernel dropped above) are
            # paged-pool features; the dense fallback widens to f32 —
            # same rule degraded_spec mirrors for the lint gate
            dkw["cache_dtype"] = None
        return InferenceEngine(stages, dcfg, kv_layout="dense",
                               metrics=metrics, clock=clock,
                               scheduler=scheduler,
                               **_adapter_kw(n_slots), **dkw)

    return factory


class ServeSupervisor:
    """Crash-restartable, deadline- and overload-aware serving; see the
    module docstring.  Duck-types the engine surface the simulator and the
    scenario runner drive (``submit``/``step``/``drain``/``busy``/
    ``requests``/``metrics``/``cfg``/``_clock``)."""

    def __init__(self, factory, journal, *, metrics=None,
                 clock=time.monotonic, max_restarts: int = 3,
                 degrade_after: int | None = None,
                 overload: OverloadPolicy | None = None,
                 default_ttft_deadline_s: float | None = None,
                 default_deadline_s: float | None = None,
                 trace=None, flight=None, postmortem_dir: str | None = None,
                 postmortem_tail: int = 64, shed_burst: int = 4,
                 postmortem_tag: str = "", slo=None) -> None:
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got "
                             f"{max_restarts}")
        if degrade_after is not None and degrade_after < 1:
            raise ValueError(f"degrade_after must be >= 1 restarts, got "
                             f"{degrade_after}")
        if shed_burst < 1:
            raise ValueError(f"shed_burst must be >= 1, got {shed_burst}")
        self.factory = factory
        self.journal = (RequestJournal(journal) if isinstance(journal, str)
                        else journal)
        self.metrics = metrics
        self._clock = clock
        self.max_restarts = int(max_restarts)
        self.degrade_after = degrade_after
        self.overload = overload if overload is not None else OverloadPolicy()
        self.default_ttft_deadline_s = default_ttft_deadline_s
        self.default_deadline_s = default_deadline_s
        # observability (ISSUE 12): the request-scoped trace recorder
        # (re-attached to every rebuilt engine, which is what joins spans
        # across restarts), the tick flight recorder, and the post-mortem
        # bundle sink. All off by default; the flight recorder is created
        # implicitly when bundles are requested (a bundle without flight
        # rows is a crash report with no flight data).
        self.trace = trace
        self.postmortem_dir = postmortem_dir
        self.postmortem_tail = int(postmortem_tail)
        # bundle filename infix — the FLEET sets "-r<idx>" per replica so
        # N supervisors sharing one postmortem_dir never overwrite each
        # other's postmortem-000-* names
        self.postmortem_tag = postmortem_tag
        self.shed_burst = int(shed_burst)
        if flight is None and postmortem_dir is not None:
            from simple_distributed_machine_learning_tpu.serve.flight import (
                FlightRecorder,
            )
            flight = FlightRecorder()
        self.flight = flight
        # streaming SLO engine (telemetry/slo.py): evaluated once per
        # supervised tick AT self.tick (never from a clock), so alert
        # transitions are exact-pinnable under the virtual clock. Under a
        # fleet the FLEET owns evaluation (one engine across replicas,
        # evaluated at fleet.tick) and clears _drive_slo on every replica.
        self.slo = slo
        self._drive_slo = True
        if slo is not None and metrics is not None:
            metrics.bind_slo(slo)
        self.postmortems: list[str] = []     # bundle paths, write order
        self._sheds_since_step = 0
        # disaggregated-fleet role ("prefill" | "decode"; None outside a
        # disaggregated fleet) — set by ServeFleet, stamped onto every
        # flight-recorder row so post-mortems localize WHICH pool saturated
        self.pool_role: str | None = None
        #: monotonic tick counter — unlike ``engine._tick_count`` it
        #: survives engine rebuilds, and it is the ``tick`` every journal
        #: record and flight-recorder row carries (the forensic join key)
        self.tick = 0
        self.restarts = 0
        self.degraded = False        # fault-driven: rebuilds use the fallback
        self.load_degraded = False   # overload-driven: best-effort lockout
        self.state = RUNNING
        self.requests: dict[int, Request] = {}
        self._open: set[int] = set()           # submitted, not DONE/SHED
        self._user_cb: dict[int, object] = {}  # rid -> caller's on_token
        self._buckets: dict[str, tuple[float, float]] = {}
        self.engine = factory(False)
        self._attach_engine(prev_now=0.0)
        # cold start: a previous process's journal recovers here — its
        # completed streams become readable handles, its in-flight requests
        # re-admit and continue bit-exact (no restart consumed: the budget
        # guards THIS process's engine, not history)
        snapshots = self.journal.recovered_state()
        if snapshots:
            self._reseat(snapshots, note_recovered=True)

    def _attach_engine(self, prev_now: float) -> None:
        """Wire the (re)built engine into the shared observability state:
        the trace recorder outlives engines — that is what joins a
        request's spans across incarnations — and the new engine's
        last-read-clock seed carries over so post-crash trace stamps stay
        monotonic (never a fresh clock read)."""
        if self.trace is not None:
            self.engine.trace = self.trace
        self.engine._now = max(self.engine._now, prev_now)

    # -- the engine surface -------------------------------------------------

    @property
    def busy(self) -> bool:
        return self.engine.busy

    @property
    def cfg(self):
        return self.engine.cfg

    @property
    def scheduler(self):
        return self.engine.scheduler

    @property
    def pool(self):
        return self.engine.pool

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int | None = None, top_p: float | None = None,
               eos_id: int | None = None, seed: int | None = None,
               on_token=None, arrival_time: float | None = None,
               cls: str | None = None, priority: int = 0,
               ttft_deadline_s: float | None = None,
               deadline_s: float | None = None,
               adapter: str | None = None) -> Request:
        """Admission-controlled, journaled submit.  The returned handle may
        already be ``SHED`` (a structured rejection — the request never
        reached the engine); otherwise the submission is journaled BEFORE
        the engine sees it, so even a crash inside admission recovers it."""
        now = self._clock() if arrival_time is None else arrival_time
        if ttft_deadline_s is None:
            ttft_deadline_s = self.default_ttft_deadline_s
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        prompt = np.asarray(prompt, np.int32)
        # validate BEFORE journaling: a rejected submission must not leave
        # a journal entry recovery would forever fail to re-admit (the
        # adapter check included — an unregistered tenant must fail here,
        # not as a poisoned `adp` record)
        validate_request(prompt, max_new_tokens, temperature, top_k, top_p,
                         self.engine.cfg.vocab, self.engine.max_len)
        self.engine._check_adapter(adapter)
        rid = self.engine._next_rid      # the rid engine.submit will assign
        seed = rid if seed is None else seed
        reason = self._admission_check(cls, priority, now)
        if reason is not None:
            return self._shed_at_admission(
                rid, prompt, max_new_tokens, temperature, top_k, top_p,
                eos_id, seed, cls, priority, ttft_deadline_s, deadline_s,
                reason, now, adapter=adapter)
        self._user_cb[rid] = on_token
        self.journal.log_submit(
            rid=rid, prompt=prompt, max_new=max_new_tokens,
            temp=temperature, top_k=top_k, top_p=top_p, eos=eos_id,
            seed=seed, cls=cls, prio=priority, ttft_dl=ttft_deadline_s,
            dl=deadline_s, t=now, tick=self.tick, adapter=adapter)
        try:
            r = self.engine.submit(
                prompt, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, seed=seed,
                on_token=self._on_token, arrival_time=now, cls=cls,
                priority=priority, ttft_deadline_s=ttft_deadline_s,
                deadline_s=deadline_s, adapter=adapter)
        except RECOVERABLE as e:
            # the serve.admit crash: the journal already carries this
            # submission, so recovery rebuilds and re-admits it
            self._recover(e)
            return self.requests[rid]
        assert r.rid == rid, (r.rid, rid)
        self.requests[rid] = r
        self._open.add(rid)
        return r

    def register_adapter(self, name: str, weights: dict) -> None:
        """Add or hot-swap a named LoRA adapter (host-side; the next
        admission of the name uploads it at a tick boundary). Registration
        lands in the factory's SHARED host dict, so it survives crash
        rebuilds — a recovered request re-admits onto the same tenant."""
        store = getattr(self.engine, "_adapters", None)
        if store is None:
            raise ValueError(
                "this supervisor's engine was built without an "
                "AdapterStore — pass adapter_rank= to engine_factory")
        store.register(name, weights)

    def step(self) -> int:
        """One supervised tick: deadline shedding, then the engine tick
        (recoverable failures recover in place), then completion acks.
        Each call advances the MONOTONIC :attr:`tick` (journal records and
        flight-recorder rows both carry it), records one flight snapshot,
        and dumps a post-mortem bundle when this tick shed a burst."""
        self.tick += 1
        self._shed_expired()
        try:
            emitted = self.engine.step()
        except RECOVERABLE as e:
            self._recover(e)
            emitted = 0
        self._ack_done()
        self._update_load_degraded()   # a draining backlog lifts the mode
        #                                even if no further arrival probes it
        if self.metrics is not None:
            self.metrics.set_journal_bytes(self.journal.bytes)
        if self.slo is not None and self._drive_slo:
            # evaluate BEFORE the flight snapshot so the row at tick T
            # carries the alert set as of the evaluation at T (the
            # bundle/journal tick-join contract)
            self.slo.evaluate(self.tick)
        if self.flight is not None:
            self.flight.snap(self.engine, self.tick, emitted,
                             state=self.state, restarts=self.restarts,
                             degraded=self.degraded,
                             load_degraded=self.load_degraded,
                             **({} if self.pool_role is None
                                else {"pool_role": self.pool_role}),
                             **({} if self.slo is None
                                else {"active_alerts":
                                      self.slo.active_alerts()}))
        if self._sheds_since_step >= self.shed_burst:
            self._dump_postmortem(
                "shed_burst", f"{self._sheds_since_step} sheds in one tick")
        self._sheds_since_step = 0
        return emitted

    def drain(self, max_ticks: int | None = None) -> list[Request]:
        from simple_distributed_machine_learning_tpu.serve.engine import (
            DrainTimeout,
        )
        ticks = 0
        while self.busy:
            if max_ticks is not None and ticks >= max_ticks:
                exc = DrainTimeout(max_ticks, [
                    r for r in self.requests.values()
                    if r.state in (QUEUED, ACTIVE)])
                # the wedged-drain forensics: what was still queued/active,
                # what the last N ticks looked like, what the journal last
                # saw — dumped BEFORE the raise so the bundle exists even
                # when the caller dies on the exception
                self._dump_postmortem("drain_timeout", str(exc))
                raise exc
            self.step()
            ticks += 1
        return [r for r in self.requests.values() if r.state == DONE]

    def close(self) -> None:
        self.journal.close()
        if self.trace is not None:
            self.trace.flush()

    # -- post-mortem bundles ------------------------------------------------

    def _dump_postmortem(self, trigger: str, cause: str) -> str | None:
        """Write one post-mortem bundle (``serve/flight.py::write_bundle``)
        into ``postmortem_dir``: last-N flight rows + every request's state
        + a metrics snapshot + the journal tail, joined on rid and the
        monotonic tick. No-op without a configured directory."""
        if self.postmortem_dir is None:
            return None
        from simple_distributed_machine_learning_tpu.serve.flight import (
            BUNDLE_PREFIX,
            write_bundle,
        )
        path = os.path.join(
            self.postmortem_dir,
            f"{BUNDLE_PREFIX}{self.postmortem_tag}"
            f"-{len(self.postmortems):03d}-{trigger}.json")
        write_bundle(
            path, trigger=trigger, cause=cause, tick=self.tick,
            flight=self.flight, requests=self.requests,
            registry=(self.metrics.registry
                      if self.metrics is not None else None),
            journal_tail=self.journal.tail(self.postmortem_tail),
            restarts=self.restarts, degraded=self.degraded,
            state=self.state,
            **({} if self.slo is None
               else {"active_alerts": self.slo.active_alerts()}))
        self.postmortems.append(path)
        return path

    # -- overload control ---------------------------------------------------

    def _admission_check(self, cls, priority: int, now: float) -> str | None:
        """The shed reason for this arrival, or None to admit.  May itself
        shed a queued lower-priority victim to make room.  The class
        bucket is PEEKED first but debited only once every other gate
        passed — an arrival shed for backpressure must not charge its
        class for capacity it never used."""
        ov = self.overload
        self._update_load_degraded()
        if self.load_degraded and priority <= ov.degraded_priority_floor:
            return "class"
        if not self._bucket_peek(cls, now):
            return "class"
        if (ov.max_queue_depth is not None
                and self.engine.scheduler.queue_depth >= ov.max_queue_depth):
            victim = self._backpressure_victim(priority)
            if victim is None:
                return "backpressure"
            self._shed_live(victim, "backpressure")
        self._bucket_debit(cls)
        return None

    def _update_load_degraded(self) -> None:
        """The load-degraded hysteresis, from the CURRENT queue depth —
        called at admission AND every tick, so the mode cannot latch on
        after the backlog drains just because arrivals stopped."""
        ov = self.overload
        if ov.degrade_queue_depth is None:
            return
        qd = self.engine.scheduler.queue_depth
        if not self.load_degraded and qd >= ov.degrade_queue_depth:
            self.load_degraded = True
            self._note_degraded()
        elif self.load_degraded and qd <= ov.recover_queue_depth:
            self.load_degraded = False
            self._note_degraded()

    def _bucket_peek(self, cls, now: float) -> bool:
        """Refill the class's bucket to ``now`` and report affordability
        WITHOUT consuming — the refill is monotone so storing it early is
        harmless, the debit is not."""
        rates = self.overload.class_rates
        if not rates or cls not in rates:
            return True
        rate, burst = rates[cls]
        tokens, last = self._buckets.get(cls, (float(burst), now))
        tokens = min(float(burst), tokens + max(0.0, now - last) * rate)
        self._buckets[cls] = (tokens, now)
        return tokens >= 1.0

    def _bucket_debit(self, cls) -> None:
        rates = self.overload.class_rates
        if not rates or cls not in rates:
            return
        tokens, last = self._buckets[cls]
        self._buckets[cls] = (tokens - 1.0, last)

    def _backpressure_victim(self, priority: int) -> Request | None:
        """Lowest-priority, newest-queued request STRICTLY below the
        arrival's priority — the cheapest work to discard for room."""
        best = None
        for r in self.engine.scheduler.queue:
            if r.priority >= priority:
                continue
            if best is None or (r.priority, -r.rid) < (best.priority,
                                                       -best.rid):
                best = r
        return best

    def _shed_expired(self) -> None:
        """Deadline enforcement at the tick boundary: TTFT deadlines bind
        until the first token, total deadlines bind until completion.
        Shedding refunds the slot/block budget immediately (engine.cancel
        routes through the same release path as retirement)."""
        if not any(
                self.requests[rid].deadline_s is not None
                or self.requests[rid].ttft_deadline_s is not None
                for rid in self._open):
            return
        now = self._clock()
        for rid in sorted(self._open):
            r = self.requests[rid]
            if r.state not in (QUEUED, ACTIVE):
                continue
            expired = (
                (r.deadline_s is not None
                 and now - r.submit_time >= r.deadline_s)
                or (r.ttft_deadline_s is not None
                    and r.first_token_time is None
                    and now - r.submit_time >= r.ttft_deadline_s))
            if expired:
                self._shed_live(r, "deadline")

    def _shed_live(self, r: Request, reason: str) -> None:
        self.engine.cancel(r.rid, reason)     # emits the trace shed event
        self.journal.log_shed(rid=r.rid, reason=reason, t=r.done_time,
                              tick=self.tick)
        self._open.discard(r.rid)
        self._user_cb.pop(r.rid, None)
        self._sheds_since_step += 1
        if self.metrics is not None:
            self.metrics.on_shed(reason, cls=r.cls)

    def _shed_at_admission(self, rid, prompt, max_new, temperature, top_k,
                           top_p, eos_id, seed, cls, priority, ttft_dl, dl,
                           reason: str, now: float,
                           adapter: str | None = None) -> Request:
        """A structured rejection: the handle exists (state SHED, the
        reason in ``finish_reason``) but the engine never saw the request.
        The rid is consumed so the journal's id space stays unique, and
        both records land so a cold recovery accounts for it."""
        assert rid == self.engine._next_rid
        self.engine._next_rid = rid + 1
        r = Request(rid=rid, prompt=prompt, max_new_tokens=max_new,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_id=eos_id, seed=seed, cls=cls, priority=priority,
                    ttft_deadline_s=ttft_dl, deadline_s=dl, adapter=adapter)
        r.submit_time = now
        r.done_time = now
        r.state = SHED
        r.finish_reason = reason
        self.journal.log_submit(
            rid=rid, prompt=prompt, max_new=max_new, temp=temperature,
            top_k=top_k, top_p=top_p, eos=eos_id, seed=seed, cls=cls,
            prio=priority, ttft_dl=ttft_dl, dl=dl, t=now, tick=self.tick,
            adapter=adapter)
        self.journal.log_shed(rid=rid, reason=reason, t=now, tick=self.tick)
        self.requests[rid] = r
        self._sheds_since_step += 1
        if self.metrics is not None:
            self.metrics.on_submit()
            self.metrics.on_shed(reason, cls=cls)
        if self.trace is not None:
            # the engine never saw this request: open AND close its span
            # here so the timeline still accounts for the rejection
            self.trace.on_submit(r, now)
            self.trace.on_shed(r, now, reason)
        return r

    # -- cross-replica migration (serve/fleet.py) ----------------------------

    def adopt(self, request: Request, on_token=None,
              reason: str = "failure") -> Request:
        """Adopt a request migrated from ANOTHER replica.

        Two callers, one move: failure migration (the source replica's
        host died; ``reason="failure"``, the default) and the
        disaggregated fleet's planned prefill->decode handoff
        (``reason="handoff"`` — the source released the request with
        :meth:`release`). The full snapshot is journaled here FIRST (one
        ``snap`` record carrying the cause under its ``why`` key,
        ``journal.py::log_snapshot``) so THIS replica's journal alone
        recovers the adoptee — a later crash of this replica, or a second
        replica loss on top of the first, replays it exactly like a native
        submission. An in-flight snapshot then re-admits through
        ``engine.restore`` (the same preempt/resume path crash recovery
        uses, so the continued decode stays bit-exact); a DONE/SHED
        snapshot is adopted as a readable handle only. ``on_token`` is the
        CALLER's streaming callback (the source replica's wiring died —
        or was released — with it)."""
        if request.rid in self.requests:
            raise ValueError(
                f"request {request.rid} already lives in this replica — "
                f"adopt() is for migrated rids, which are fleet-unique")
        if request.state not in (QUEUED, DONE, SHED):
            raise ValueError(
                f"request {request.rid} is {request.state!r} — migration "
                f"adopts journal snapshots (queued/done/shed), never a "
                f"live engine's state")
        request.snap_reason = reason
        self.journal.log_snapshot(request, tick=self.tick, reason=reason)
        self.requests[request.rid] = request
        if request.state == QUEUED:
            request.on_token = self._on_token
            self._user_cb[request.rid] = on_token
            self.engine.restore(request)
            self._open.add(request.rid)
        else:
            # finished exactly at the loss boundary: keep the rid space
            # clear of it (restore() was never called to bump it)
            self.engine._next_rid = max(self.engine._next_rid,
                                        request.rid + 1)
        return request

    def release(self, rid: int, dst=None, seal: bool = True) -> Request:
        """Hand a LIVE request out of this replica — the source half of
        the disaggregated fleet's prefill->decode handoff (the adopting
        replica runs :meth:`adopt` with ``reason="handoff"``).

        An ACTIVE request's slot and K/V blocks free immediately (the
        preemption release path, so the handle carries its emitted tokens
        and untouched key stream — re-admission on the destination
        recomputes ``resume_seq`` and continues bit-exact); a QUEUED one
        just leaves the queue. A ``handoff`` journal record marks the rid
        as moved (``journal.py``): recovery of THIS journal drops it, so
        losing this replica later can never double-serve the request.
        Returns the handle (state QUEUED) for the destination to adopt.

        ``seal=False`` defers the terminal ``handoff`` record to a later
        :meth:`seal_handoff` — the copy-then-tombstone ordering the fleet
        uses: journaling the tombstone here, BEFORE the destination's
        ``adopt`` snap lands, opens a window where the rid lives in NO
        journal, so a crash between the two appends loses the request
        (the model checker's ``protocol.lost-request`` counterexample,
        analysis/protocol.py::LEGACY_ORDER)."""
        r = self.requests.get(rid)
        if r is None:
            raise ValueError(f"request {rid} does not live in this replica")
        if r.state not in (QUEUED, ACTIVE):
            raise ValueError(
                f"request {rid} is {r.state!r} — only live "
                f"(queued/active) requests hand off")
        if r.state == ACTIVE:
            # the preempt release path WITHOUT the preemption accounting
            # (a planned handoff is not SLO-protective eviction): slot and
            # blocks free now, state back to QUEUED with tokens intact
            try:
                self.engine._prefilling.remove(rid)   # may be mid-prefill
            except ValueError:
                pass
            self.engine.pool.unbind_seq(r.slot)
            self.engine.pool.release(r.slot)
            r.slot = None
            r.prefill_pos = None
            r.state = QUEUED
        else:
            # identity scan, not deque.remove (Request.__eq__ compares
            # prompt arrays — engine.cancel's same caveat)
            for i, q in enumerate(self.engine.scheduler.queue):
                if q is r:
                    del self.engine.scheduler.queue[i]
                    break
            else:               # pragma: no cover - state-machine guard
                raise RuntimeError(
                    f"queued request {rid} missing from the scheduler "
                    f"queue — lifecycle bookkeeping corrupted")
        del self.engine.requests[rid]
        self.engine._last_emit.pop(rid, None)
        del self.requests[rid]
        self._user_cb.pop(rid, None)
        self._open.discard(rid)
        r.on_token = None        # the destination's adopt() rewires it
        if seal:
            self.journal.log_handoff(rid=rid, dst=dst, tick=self.tick)
        return r

    def seal_handoff(self, rid: int, dst=None) -> None:
        """Journal the terminal ``handoff`` tombstone for a rid this
        replica already released with ``seal=False`` — called by the fleet
        AFTER the destination's ``adopt`` journaled its snap, so at every
        crash point the rid is recoverable from at least one journal (and
        from at most one once this lands)."""
        if rid in self.requests:
            raise ValueError(
                f"request {rid} still lives in this replica — seal only "
                f"what release() already detached")
        self.journal.log_handoff(rid=rid, dst=dst, tick=self.tick)

    # -- crash recovery -----------------------------------------------------

    def _on_token(self, request: Request, token: int) -> None:
        """Every engine token flows through here: journal first (the
        durability point), then the caller's callback — 'journaled but not
        acked' is the recoverable order, the reverse would lose tokens."""
        self.journal.log_token(request, token, tick=self.tick)
        cb = self._user_cb.get(request.rid)
        if cb is not None:
            cb(request, token)

    def _ack_done(self) -> None:
        for rid in list(self._open):
            r = self.requests[rid]
            if r.state == DONE:
                self.journal.log_done(rid=rid, reason=r.finish_reason,
                                      t=r.done_time, tick=self.tick)
                self._open.discard(rid)
                self._user_cb.pop(rid, None)

    def _note_degraded(self) -> None:
        if self.metrics is not None:
            self.metrics.set_degraded(self.degraded or self.load_degraded)
        if self.state in (RUNNING, DEGRADED):
            self.state = (DEGRADED if (self.degraded or self.load_degraded)
                          else RUNNING)

    def _recover(self, exc: BaseException) -> None:
        """RECOVERING: count the restart against the budget, rebuild the
        engine (degraded once past ``degrade_after``) and re-admit every
        in-flight request from the journal alone."""
        from simple_distributed_machine_learning_tpu.resilience.supervisor import (  # noqa: E501
            RestartBudgetExceeded,
        )
        self.state = RECOVERING
        self.restarts += 1
        # the dead engine's last clock reading: every crash-boundary trace
        # stamp (and the rebuilt engine's seed) uses it — recovery must
        # not read the clock, or virtual-clock pins would move
        prev_now = self.engine._now
        if self.restarts > self.max_restarts:
            self.state = FAILED
            self._dump_postmortem("restart_budget",
                                  f"{type(exc).__name__}: {exc}")
            raise RestartBudgetExceeded(
                f"{self.restarts} engine failures exceed the max_restarts="
                f"{self.max_restarts} budget; last: "
                f"{type(exc).__name__}: {exc}") from exc
        if (self.degrade_after is not None and not self.degraded
                and self.restarts >= self.degrade_after):
            self.degraded = True
        if self.metrics is not None:
            self.metrics.on_restart()
        self.journal.log_restart(self.restarts, self.degraded,
                                 type(exc).__name__, tick=self.tick)
        if self.trace is not None:
            self.trace.on_crash(
                prev_now,
                [rid for rid in self._open
                 if self.requests[rid].state in (QUEUED, ACTIVE)],
                type(exc).__name__)
        # the moment-of-failure forensics, BEFORE anything is rebuilt:
        # the dead incarnation's flight rows, its request states, the
        # journal tail — what a post-mortem actually reads
        self._dump_postmortem("restart",
                              f"{type(exc).__name__}: {exc}")
        # journal-ONLY reconstruction: nothing of the dead engine's memory
        # is trusted — exactly the host-kill discipline the trainer has
        snapshots = self.journal.recovered_state()
        self.engine = self.factory(self.degraded)
        self._attach_engine(prev_now=prev_now)
        if self.trace is not None:
            self.trace.on_restart(prev_now, self.restarts, self.degraded,
                                  type(exc).__name__)
        self._reseat(snapshots, note_recovered=True)
        self.state = RUNNING
        self._note_degraded()    # RUNNING -> DEGRADED when a mode is on

    def _reseat(self, snapshots: dict[int, Request],
                note_recovered: bool) -> None:
        """Apply journal snapshots to the live handles (or adopt the
        snapshots as handles on a cold start) and re-admit the in-flight
        ones into ``self.engine`` in rid order — FCFS arrival order
        survives the restart."""
        if snapshots:
            # the rebuilt engine's rid space must clear EVERY journaled rid
            # (done/shed ones included — restore() only bumps past the
            # re-admitted), or a fresh submission would reuse a dead rid
            self.engine._next_rid = max(self.engine._next_rid,
                                        max(snapshots) + 1)
        inflight = []
        for rid in sorted(snapshots):
            snap = snapshots[rid]
            r = self.requests.get(rid)
            if r is None:
                r = snap                     # cold start / mid-submit crash
                self.requests[rid] = r
            else:
                self._apply_snapshot(r, snap)
            if r.state == QUEUED:
                inflight.append(r)
            elif rid in self._open:
                # finished/shed exactly at the crash boundary: the stream
                # is already complete and identical — ack it now
                if r.state == DONE:
                    self.journal.log_done(rid=rid, reason=r.finish_reason,
                                          t=r.done_time, tick=self.tick)
                self._open.discard(rid)
                self._user_cb.pop(rid, None)
        for r in inflight:
            r.on_token = self._on_token
            self.engine.restore(r)
            self._open.add(r.rid)
        if note_recovered and inflight and self.metrics is not None:
            self.metrics.on_recovered(len(inflight))

    @staticmethod
    def _apply_snapshot(r: Request, snap: Request) -> None:
        """Overwrite a live handle's decode state with the journal's —
        object identity is preserved (the caller's handle stays live), the
        STATE is the journal's: tokens truncate to the journaled prefix
        (the decode re-emits the identical tail), key streams rewind to
        the last durable token's."""
        r.tokens[:] = snap.tokens
        r.key_data = snap.key_data
        r.draft_key_data = snap.draft_key_data
        r.submit_time = snap.submit_time
        r.first_token_time = snap.first_token_time
        r.slot = None
        r.prefill_pos = None
        r.state = snap.state
        r.finish_reason = snap.finish_reason
        if snap.done_time is not None:
            r.done_time = snap.done_time
