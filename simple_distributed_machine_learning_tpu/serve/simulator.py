"""Open-loop traffic simulator: seeded Poisson arrivals driving the engine.

*Open-loop* means arrivals do not wait for the system — requests arrive on
their own clock (exponential inter-arrival gaps at ``rate`` req/s, seeded,
so a run is reproducible) whether or not slots are free. That is the load
shape that actually stresses a serving stack: above slot capacity the queue
grows and TTFT absorbs the wait, which is exactly what the offered-load
sweep in ``bench.py --serve`` charts.

The simulated workload is a seeded mix of prompt lengths and per-request
sampling configs (greedy and temperature/top-k). Because continuous batching
is a scheduling optimization and not a math change, each request's tokens
are a pure function of its own (prompt, sampling params, seed) — so the
simulator's outputs are deterministic even though wall-clock timing decides
the admission interleave (pinned in tests/test_serve.py).

``cli.py --serve-sim N`` is the command-line surface; ``simulate`` is the
library entry bench rows call directly.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from simple_distributed_machine_learning_tpu.serve.engine import (
    InferenceEngine,
)
from simple_distributed_machine_learning_tpu.serve.request import DONE, SHED


@dataclasses.dataclass(frozen=True)
class TrafficClass:
    """One tenant/priority class of a multi-class workload.

    ``weight`` is the class's share of arrivals (normalized over the
    config's classes); ``priority`` feeds the engine's scheduler (higher
    boards first; ``PriorityScheduler`` may preempt lower to protect it).
    ``ttft_slo_ms``/``tpot_slo_ms`` are the class's latency targets — the
    scenario runner computes attainment against them from the telemetry
    registry (``resilience/scenarios.py``). ``max_new_tokens``/
    ``prompt_lens`` override the SimConfig-wide workload mix per class
    (batch tenants decode long, interactive ones short).

    ``ttft_deadline_ms``/``deadline_ms`` are HARD per-request deadlines
    (distinct from the SLO *targets* above, which only grade a run): each
    submission carries them, and a supervised engine
    (``serve/supervisor.py``) SHEDS a request that exceeds one, refunding
    its budget. An unsupervised engine stores but never enforces them —
    the no-deadline baseline the overload scenarios compare against.
    """

    name: str
    weight: float = 1.0
    priority: int = 0
    ttft_slo_ms: float | None = None
    tpot_slo_ms: float | None = None
    max_new_tokens: int | None = None
    prompt_lens: tuple | None = None
    ttft_deadline_ms: float | None = None
    deadline_ms: float | None = None
    # opt out of the SimConfig-wide shared system prompt: background
    # classes whose prompts deliberately do NOT carry the hot prefix (the
    # offload-churn scenario uses one to push the idle prefix out of HBM
    # so the host tier's demote/prefetch cycle actually exercises)
    shared_prefix: bool = True
    # multi-tenant LoRA serving (ISSUE 20): every request of this class
    # decodes through the named adapter (registered on the target before
    # traffic starts — resilience/scenarios.py does this); None = the
    # shared base model
    adapter: str | None = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("traffic class name must be non-empty")
        if self.weight <= 0:
            raise ValueError(f"class {self.name!r}: weight must be > 0, "
                             f"got {self.weight}")


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """One traffic run: ``n_requests`` arrivals at mean ``rate`` req/s.

    ``arrival`` picks the pattern (all seeded, all open-loop):

    - ``"poisson"`` — homogeneous Poisson (the PR-5 default; byte-identical
      rng stream to the original single-class simulator, so existing pins
      hold);
    - ``"bursty"`` — on/off modulated Poisson: ``burst_factor`` x the mean
      rate for ``burst_duty`` of every ``period_s`` cycle, a floored trough
      in between (load spikes — the shape that breaks FCFS TTFT);
    - ``"diurnal"`` — sinusoidally modulated Poisson with amplitude
      ``diurnal_amplitude`` over ``period_s`` (the day/night cycle,
      compressed).

    ``classes`` switches on the multi-tenant workload: each request is
    assigned a :class:`TrafficClass` by seeded weighted choice and submits
    with that class's name/priority (per-class SLOs live on the class).
    Empty = the legacy single-class mix.
    """

    n_requests: int = 16
    rate: float = 8.0
    seed: int = 0
    # arrival pattern (see class docstring)
    arrival: str = "poisson"
    burst_factor: float = 5.0
    burst_duty: float = 0.25
    period_s: float = 1.0
    diurnal_amplitude: float = 0.8
    # multi-tenant classes; () = single-class legacy workload
    classes: tuple = ()
    # workload mix: prompt lengths cycle through these buckets (each bucket
    # is one compiled prefill shape), max_new_tokens per request
    prompt_lens: tuple = (4, 8, 12)
    max_new_tokens: int = 16
    # sampling mix: this fraction of requests sample at `temperature` with
    # `top_k` (rest decode greedy); every request gets an independent seed
    sampled_fraction: float = 0.5
    temperature: float = 0.8
    top_k: int | None = 8
    eos_id: int | None = None
    # shared system prompt: prepend ONE seeded common prefix of this many
    # tokens to every request's prompt (total length = prefix + bucket).
    # The multi-million-user case the paged pool's prefix sharing targets:
    # all requests reference the same physical K/V blocks for the prefix
    # until they diverge (copy-on-write), so both the prefix's memory and
    # its prefill compute are paid roughly once.
    shared_prefix_len: int = 0

    def __post_init__(self):
        if self.n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got "
                             f"{self.n_requests}")
        if self.rate <= 0:
            raise ValueError(f"rate must be > 0 req/s, got {self.rate}")
        if not self.prompt_lens:
            raise ValueError("prompt_lens must be non-empty")
        if self.shared_prefix_len < 0:
            raise ValueError(f"shared_prefix_len must be >= 0, got "
                             f"{self.shared_prefix_len}")
        if self.arrival not in ("poisson", "bursty", "diurnal"):
            raise ValueError(
                f"arrival must be poisson|bursty|diurnal, got "
                f"{self.arrival!r}")
        if self.burst_factor < 1:
            raise ValueError(f"burst_factor must be >= 1, got "
                             f"{self.burst_factor}")
        if not 0 < self.burst_duty < 1:
            raise ValueError(f"burst_duty must be in (0, 1), got "
                             f"{self.burst_duty}")
        if self.period_s <= 0:
            raise ValueError(f"period_s must be > 0, got {self.period_s}")
        if not 0 <= self.diurnal_amplitude < 1:
            raise ValueError(f"diurnal_amplitude must be in [0, 1), got "
                             f"{self.diurnal_amplitude}")
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate traffic class names: {names}")

    @classmethod
    def from_duration(cls, rate: float, duration_s: float, **kw
                      ) -> "SimConfig":
        """Duration form of the open-loop trace: ``rate`` req/s sustained
        for ``duration_s`` seconds (expected arrivals, at least one)."""
        if duration_s <= 0:
            raise ValueError(f"duration_s must be > 0, got {duration_s}")
        return cls(n_requests=max(1, round(rate * duration_s)), rate=rate,
                   **kw)


def _rate_fn(sim: SimConfig):
    """The arrival-rate profile ``rate(t)`` and its ceiling (for thinning).

    Bursty: ``burst_factor * rate`` inside the first ``burst_duty`` of every
    ``period_s`` cycle; in between, a trough that keeps the long-run mean at
    ``rate`` where feasible (floored at 5% of the mean so the process never
    fully stops). Diurnal: ``rate * (1 + amplitude * sin(2*pi*t/period))``.
    """
    rate, period = sim.rate, sim.period_s
    if sim.arrival == "bursty":
        duty, factor = sim.burst_duty, sim.burst_factor
        trough = max(rate * (1 - duty * factor) / (1 - duty), 0.05 * rate)
        peak = factor * rate

        def fn(t):
            return peak if (t % period) < duty * period else trough
        return fn, peak
    if sim.arrival == "diurnal":
        amp = sim.diurnal_amplitude

        def fn(t):
            return rate * (1.0 + amp * np.sin(2.0 * np.pi * t / period))
        return fn, rate * (1.0 + amp)
    return (lambda t: rate), rate


def _arrival_times(sim: SimConfig, rng) -> np.ndarray:
    """Seeded arrival timestamps for the configured pattern. The poisson
    branch draws exactly what the PR-5 simulator drew (one vectorized
    exponential), so single-class poisson workloads stay byte-identical
    across this extension; modulated patterns are generated by thinning
    (an inhomogeneous Poisson process, still fully seeded)."""
    if sim.arrival == "poisson":
        return np.cumsum(rng.exponential(1.0 / sim.rate, sim.n_requests))
    rate_fn, rate_max = _rate_fn(sim)
    times = np.empty(sim.n_requests)
    t, i = 0.0, 0
    while i < sim.n_requests:
        t += rng.exponential(1.0 / rate_max)
        if rng.random() * rate_max <= rate_fn(t):
            times[i] = t
            i += 1
    return times


def build_workload(sim: SimConfig, vocab: int) -> tuple[np.ndarray, list]:
    """Seeded ``(arrival_times [N], request_specs)``: the whole run's
    traffic, reproducible from ``sim.seed`` alone. Specs are ``submit``
    kwargs; request ``i``'s sampling seed is derived from ``(sim.seed, i)``
    so two runs of the same config produce the same per-request tokens
    regardless of arrival pattern or class mix."""
    rng = np.random.default_rng(sim.seed)
    arrivals = _arrival_times(sim, rng)
    prefix = rng.integers(0, vocab, sim.shared_prefix_len).astype(np.int32)
    weights = None
    if sim.classes:
        w = np.asarray([c.weight for c in sim.classes], np.float64)
        weights = w / w.sum()
    specs = []
    for i in range(sim.n_requests):
        cls = (sim.classes[int(rng.choice(len(sim.classes), p=weights))]
               if sim.classes else None)
        lens = (cls.prompt_lens if cls is not None and cls.prompt_lens
                else sim.prompt_lens)
        t0 = int(lens[i % len(lens)])
        body = rng.integers(0, vocab, t0).astype(np.int32)
        prompt = (np.concatenate([prefix, body])
                  if cls is None or cls.shared_prefix else body)
        sampled = rng.random() < sim.sampled_fraction
        spec = dict(
            prompt=prompt,
            max_new_tokens=(cls.max_new_tokens
                            if cls is not None and cls.max_new_tokens
                            else sim.max_new_tokens),
            temperature=sim.temperature if sampled else 0.0,
            top_k=sim.top_k if sampled else None,
            eos_id=sim.eos_id,
            seed=sim.seed * 100003 + i,
        )
        if cls is not None:
            spec["cls"] = cls.name
            spec["priority"] = cls.priority
            if cls.adapter is not None:
                spec["adapter"] = cls.adapter
            if cls.ttft_deadline_ms is not None:
                spec["ttft_deadline_s"] = cls.ttft_deadline_ms / 1e3
            if cls.deadline_ms is not None:
                spec["deadline_s"] = cls.deadline_ms / 1e3
        specs.append(spec)
    return arrivals, specs


def simulate(engine: InferenceEngine, sim: SimConfig,
             clock=None, sleep=time.sleep, should_stop=None) -> dict:
    """Run the open-loop trace through ``engine``; returns the report dict
    (pure JSON-serializable — the live request handles stay reachable via
    ``engine.requests``, keyed by rid in submit order).

    ``engine`` may equally be a :class:`~.supervisor.ServeSupervisor` —
    it duck-types the same surface; supervised runs additionally report
    shed requests (structured rejections) under ``"shed"``.

    ``clock`` defaults to the ENGINE's clock so arrival timestamps (which
    become ``submit_time`` for TTFT) and the engine's first-token stamps
    share one origin; override only with a clock the engine was also
    constructed with.

    ``should_stop`` is the graceful-shutdown hook (``cli.py --serve-sim``'s
    SIGTERM/SIGINT handler): once it returns truthy, admission stops —
    remaining arrivals are never submitted — and the loop DRAINS every
    in-flight request before returning (``report["stopped"]`` is True,
    ``report["submitted"]`` counts what actually entered the engine).

    The loop: submit every request whose arrival time has passed, tick the
    engine while anything is in flight, sleep (briefly) only when idle
    before the next arrival. Latency metrics are real wall-clock — TTFT
    includes genuine queue wait when offered load exceeds slot capacity.
    """
    clock = engine._clock if clock is None else clock
    arrivals, specs = build_workload(sim, engine.cfg.vocab)
    handles = []
    start = clock()
    i = 0
    stopped = False
    while i < sim.n_requests or engine.busy:
        if not stopped and should_stop is not None and should_stop():
            stopped = True
        if stopped:
            # graceful shutdown: no new admissions, drain what's in flight
            if not engine.busy:
                break
            engine.step()
            continue
        t = clock() - start
        while i < sim.n_requests and arrivals[i] <= t:
            # submit_time = the ARRIVAL timestamp, not "now": wait accrued
            # while the loop was inside a tick belongs to this TTFT
            handles.append(engine.submit(
                **specs[i], arrival_time=start + float(arrivals[i])))
            i += 1
        if engine.busy:
            engine.step()
        elif i < sim.n_requests:
            sleep(min(max(arrivals[i] - (clock() - start), 0.0), 0.05))
    wall_s = clock() - start
    completed = sum(1 for h in handles if h.state == DONE)
    shed = sum(1 for h in handles if h.state == SHED)
    report = {
        "n_requests": sim.n_requests,
        "rate": sim.rate,
        "submitted": len(handles),
        "completed": completed,
        "shed": shed,
        "all_completed": completed == sim.n_requests,
        "stopped": stopped,
        "wall_s": round(wall_s, 3),
        "requests": [
            {"rid": h.rid, "prompt_len": int(h.prompt.shape[0]),
             "n_tokens": len(h.tokens), "finish_reason": h.finish_reason,
             "ttft_s": None if h.ttft_s is None else round(h.ttft_s, 4),
             "tpot_s": None if h.tpot_s is None else round(h.tpot_s, 5),
             **({"cls": h.cls, "priority": h.priority,
                 "n_preempted": h.n_preempted} if h.cls is not None else {})}
            for h in handles],
    }
    if engine.metrics is not None:
        report.update(engine.metrics.summary())
    return report
