"""The tick flight recorder and post-mortem bundles: crash forensics.

When the chaos-serve drill (or a real engine) wedges, the aggregate
histograms say *that* things went wrong; what debugging needs is the engine
state *at the moment of failure*. :class:`FlightRecorder` is a bounded ring
buffer of per-tick engine snapshots — slot occupancy, queue depth and
per-class queue composition, paged block stats (including
``serve_kv_bytes_resident``), prefill backlog, and the supervisor's
restart/degraded state — cheap host-side dicts, no device sync, recorded
once per tick by whichever layer drives ``step()``.

:func:`write_bundle` dumps a post-mortem bundle: the last-N flight rows
plus every live request's state, a metrics-registry snapshot and the
journal tail, as one JSON file (atomic rename). The serve supervisor
(``serve/supervisor.py``) writes one on every engine restart, on a
``DrainTimeout``, and on a shed burst — the forensics a router/autoscaler
operator opens first.

Determinism note: bundles carry TICK indices and engine-clock timestamps
already read, never a fresh clock read — writing one from a virtual-clock
scenario cannot perturb the pinned numbers.
"""

from __future__ import annotations

import collections
import json
import os

DEFAULT_CAPACITY = 256
BUNDLE_PREFIX = "postmortem"


class FlightRecorder:
    """Bounded ring of per-tick snapshot rows (oldest evicted first)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=self.capacity)
        self.ticks_recorded = 0

    def record(self, row: dict) -> None:
        self.ticks_recorded += 1
        self._ring.append(row)

    def rows(self) -> list[dict]:
        """Oldest-first snapshot list (at most ``capacity`` rows)."""
        return list(self._ring)

    def snap(self, engine, tick: int, emitted: int, **extra) -> dict:
        """Build and record one tick's snapshot row from engine state.

        ``tick`` is the MONOTONIC tick (the supervisor's counter, which
        survives engine rebuilds — the same value journal records carry,
        so bundle rows and journal lines join exactly); ``extra`` is the
        caller's state block (supervisor restarts/degraded/state)."""
        queue_cls = collections.Counter(
            r.cls for r in engine.scheduler.queue if r.cls is not None)
        row = {
            "tick": int(tick),
            "engine_tick": int(engine._tick_count),
            "emitted": int(emitted),
            "queue_depth": int(engine.scheduler.queue_depth),
            "queue_by_class": dict(sorted(queue_cls.items())),
            "slots_active": int(engine.pool.n_active),
            "slots_total": int(engine.pool.n_slots),
            "prefill_backlog": len(engine._prefilling),
        }
        if engine.kv_layout == "paged":
            row["blocks"] = engine.pool.stats()
        row.update(extra)
        self.record(row)
        return row


def request_states(requests) -> list[dict]:
    """JSON-serializable state of every request handle — what was live,
    what was done, what was mid-prefill — for the bundle's active-request
    block."""
    out = []
    for rid in sorted(requests):
        r = requests[rid]
        out.append({
            "rid": rid, "state": r.state, "cls": r.cls,
            "priority": r.priority,
            "prompt_len": int(r.prompt.shape[0]),
            "max_new_tokens": int(r.max_new_tokens),
            "tokens_emitted": len(r.tokens),
            "slot": r.slot, "prefill_pos": r.prefill_pos,
            "n_preempted": r.n_preempted,
            "finish_reason": r.finish_reason,
        })
    return out


def write_bundle(path: str, *, trigger: str, cause: str, tick: int,
                 flight: FlightRecorder | None, requests,
                 registry=None, journal_tail=None, **extra) -> str:
    """Write one post-mortem bundle JSON to ``path`` (atomic rename so a
    reader never sees a torn file); returns the path.

    ``trigger`` is why (``restart`` | ``drain_timeout`` | ``shed_burst``),
    ``cause`` the precipitating exception/type, ``tick`` the monotonic
    tick the trigger fired on. ``flight`` contributes its last-N rows,
    ``requests`` the per-request states, ``registry`` (a
    ``MetricsRegistry``) its snapshot, ``journal_tail`` the last journal
    events — everything a post-mortem reads side by side, joined on rid
    and tick."""
    bundle = {
        "kind": "postmortem",
        "trigger": trigger,
        "cause": cause,
        "tick": int(tick),
        "flight": flight.rows() if flight is not None else [],
        "flight_ticks_recorded": (flight.ticks_recorded
                                  if flight is not None else 0),
        "requests": request_states(requests),
        **extra,
    }
    if registry is not None:
        bundle["metrics"] = registry.snapshot()
    if journal_tail is not None:
        bundle["journal_tail"] = list(journal_tail)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(bundle, f)
    os.replace(tmp, path)
    return path
