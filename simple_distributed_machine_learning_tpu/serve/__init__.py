"""Continuous-batching inference serving (the north star's traffic layer).

Every decoder below ``serve/`` (``models/gpt.py`` cached, ``models/beam.py``,
``models/pp_decode.py``) is one-shot: one prompt batch in, all tokens out.
Production TPU serving is dominated by *continuous batching* — admitting and
retiring sequences mid-flight inside one compiled step — and by TTFT/TPOT
latency accounting (PAPERS.md: "Fine-Tuning and Serving Gemma on Cloud TPU").
This package is that layer, on top of the existing KV-cache model ops,
checkpoint restore, and the telemetry registry:

- :mod:`.slots` — the KV-cache pools: :class:`KVCachePool` (dense
  ``n_slots`` static-shape rows, the PR-5 baseline) and
  :class:`PagedKVPool` (block-table paged pool with refcounted blocks,
  prefix sharing via a registered-prompt registry, copy-on-write before
  divergent writes, and reservation-backed on-demand allocation — the
  layout that makes concurrency a function of actual tokens resident, not
  worst-case rows), both on the invariant-guarded free-list discipline;
- :mod:`.request` — the request object: prompt, per-request sampling params
  (greedy / top-k / top-p with an independent seeded key stream),
  ``max_new_tokens`` / EOS termination, and latency timestamps;
- :mod:`.scheduler` — FCFS continuous-batching scheduler: admits from the
  queue into free slots, retires on EOS or token budget, freeing slots
  immediately so waiting requests board mid-flight;
- :mod:`.engine` — :class:`InferenceEngine`: ``submit() -> handle``,
  ``step()`` (one tick: admit, at most one prefill CHUNK, then ONE batched
  decode program regardless of occupancy — chunked prefill keeps a long
  prompt from freezing in-flight decodes), ``drain()``, streaming
  per-token callbacks; ``kv_layout="paged"|"dense"`` picks the pool;
- :mod:`.simulator` — open-loop traffic simulator: seeded Poisson arrivals
  at a configurable rate driving the engine (``cli.py --serve-sim``);
- :mod:`.metrics` — serving telemetry on the PR-4 ``MetricsRegistry``:
  queue-depth / slot-occupancy gauges, TTFT and per-output-token latency
  histograms, aggregate tokens/sec — JSONL + Prometheus;
- :mod:`.journal` — the append-only, fsync'd request journal (one record
  per submission / emitted token / completion / shed, carrying live PRNG
  key state and the supervisor's monotonic tick), with a
  corruption-tolerant tail like the checkpoint store's ``latest_valid``;
- :mod:`.tracing` — :class:`ServeTrace`: request-scoped tracing — per-rid
  async span timelines (submit, queue wait, prefill chunks, decode/spec
  ticks, preempt/resume, crash re-admission, completion) exported as
  Chrome-trace async events plus a per-request JSONL timeline; spans join
  across restarts because the journal's rid is the trace id, and the
  recorder never reads a clock (engine-supplied stamps only);
- :mod:`.flight` — :class:`FlightRecorder`: a bounded ring of per-tick
  engine snapshots, dumped by the supervisor as post-mortem bundles
  (flight rows + request states + metrics snapshot + journal tail) on
  every restart, ``DrainTimeout`` and shed burst;
- :mod:`.supervisor` — :class:`ServeSupervisor`: the crash-restartable
  serving loop (RUNNING → RECOVERING → RUNNING | DEGRADED) that rebuilds a
  failed engine and re-admits in-flight requests from the journal
  BIT-EXACT through the preempt/resume machinery, enforces per-request
  TTFT/total deadlines at tick boundaries, and applies
  :class:`OverloadPolicy` admission control (per-class token buckets,
  queue-depth backpressure, degraded modes) — ``cli.py --serve-chaos`` /
  ``--serve-deadline-ms``;
- :mod:`.router` — :class:`FleetRouter`: which replica serves a request —
  prefix-cache affinity over the paged pools' registries first,
  least-loaded by queue-depth/occupancy otherwise, round-robin as the
  affinity-blind baseline;
- :mod:`.fleet` — :class:`ServeFleet` + :class:`AutoscalePolicy`: N
  supervised replicas behind the router with fleet-unique rids,
  health-aware rotation (hysteresis re-entry), JOURNAL-BACKED
  cross-replica migration on replica loss (every in-flight stream
  re-admitted onto survivors bit-exact from the dead replica's journal
  alone), and a queue-depth/KV-residency autoscaler (scale-out on
  sustained backlog, drain-then-retire on idle) —
  ``cli.py --serve-replicas``.

Correctness anchor (tests/test_serve.py): with the same seed, every
request's tokens are bit-exact vs decoding it alone through
``models.make_cached_decoder`` — continuous batching is a scheduling
optimization, not a math change.
"""

from simple_distributed_machine_learning_tpu.serve.engine import (  # noqa: F401
    DrainTimeout,
    InferenceEngine,
)
from simple_distributed_machine_learning_tpu.serve.fleet import (  # noqa: F401
    AutoscalePolicy,
    ServeFleet,
)
from simple_distributed_machine_learning_tpu.serve.flight import (  # noqa: F401
    FlightRecorder,
    write_bundle,
)
from simple_distributed_machine_learning_tpu.serve.journal import (  # noqa: F401
    RequestJournal,
)
from simple_distributed_machine_learning_tpu.serve.metrics import (  # noqa: F401
    ServeMetrics,
)
from simple_distributed_machine_learning_tpu.serve.request import (  # noqa: F401
    Request,
)
from simple_distributed_machine_learning_tpu.serve.router import (  # noqa: F401
    FleetRouter,
)
from simple_distributed_machine_learning_tpu.serve.scheduler import (  # noqa: F401
    FCFSScheduler,
    PriorityScheduler,
)
from simple_distributed_machine_learning_tpu.serve.simulator import (  # noqa: F401
    SimConfig,
    TrafficClass,
    simulate,
)
from simple_distributed_machine_learning_tpu.serve.slots import (  # noqa: F401
    KVCachePool,
    PagedKVPool,
)
from simple_distributed_machine_learning_tpu.serve.supervisor import (  # noqa: F401
    OverloadPolicy,
    ServeSupervisor,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.tracing import (  # noqa: F401
    ServeTrace,
)
