"""The continuous-batching inference engine: submit / step / drain.

:class:`InferenceEngine` is the serving API over the slot-wise model ops
(``models/gpt.py::make_slot_prefill`` / ``make_slot_decode_step``), the
KV-cache pool and the FCFS scheduler:

- ``submit(prompt, ...) -> Request`` enqueues one sequence with its own
  sampling params and seeded key stream, and returns the live handle
  (``handle.tokens`` grows as the engine runs; ``on_token`` streams);
- ``step()`` is one *tick*: admit waiting requests into free slots (one
  prefill each — compiled per prompt length), then ONE batched decode step
  over all slots (one compiled program regardless of occupancy), then
  retire finished requests so their slots free for the next tick;
- ``drain()`` ticks until queue and slots are empty.

Device state is exactly the pool's K/V buffers; everything else (positions,
last tokens, key streams, request lifecycle) is host-side numpy assembled
into each tick's inputs — the scheduler stays plain Python while every FLOP
runs inside the two jitted programs.

Correctness anchor: a request's tokens are bit-exact vs decoding it alone
via ``make_cached_decoder`` with the same seed (tests/test_serve.py) —
admission order, co-residents, and occupancy cannot change anyone's output.
"""

from __future__ import annotations

import time

import numpy as np

from simple_distributed_machine_learning_tpu.serve.metrics import ServeMetrics
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    Request,
    validate_request,
)
from simple_distributed_machine_learning_tpu.serve.scheduler import (
    FCFSScheduler,
)
from simple_distributed_machine_learning_tpu.serve.slots import KVCachePool

# sampling-param sentinels (models/gpt.py::_sample_dyn): 0 disables top-k,
# anything > 1 disables top-p
_NO_TOP_K = 0
_NO_TOP_P = 2.0


class InferenceEngine:
    """Continuous-batching serving over a dense single-device GPT build.

    ``stages``/``cfg``: a ``make_gpt_stages`` build (dense-MLP, unsharded —
    the ``make_cached_decoder`` restrictions). ``params`` overrides the
    stages' init weights (e.g. checkpoint-restored trees from
    ``Pipeline.unpack``). ``max_len`` caps each slot's prompt+generation
    budget (defaults to ``cfg.seq_len``); ``cache_dtype`` is the pool's
    storage dtype (bf16 halves pool memory, the ``_cache_dtype`` rule).
    """

    def __init__(self, stages, cfg, *, params=None, n_slots: int = 4,
                 max_len: int | None = None, cache_dtype=None,
                 metrics: ServeMetrics | None = None,
                 scheduler: FCFSScheduler | None = None,
                 clock=time.monotonic) -> None:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            make_slot_decode_step,
            make_slot_prefill,
        )
        self.cfg = cfg
        self.params = (params if params is not None
                       else [s.params for s in stages])
        self.max_len = int(max_len if max_len is not None else cfg.seq_len)
        n_layers = sum(len(p["blocks"]) for p in self.params)
        self.pool = KVCachePool(n_layers, n_slots, cfg.n_heads, self.max_len,
                                cfg.d_model // cfg.n_heads, cache_dtype)
        self._prefill = make_slot_prefill(stages, cfg, self.max_len,
                                          cache_dtype)
        self._decode = make_slot_decode_step(stages, cfg, self.max_len,
                                             cache_dtype)
        self.scheduler = scheduler or FCFSScheduler(self.pool)
        self.metrics = metrics
        self._clock = clock
        self._next_rid = 0
        self.requests: dict[int, Request] = {}
        # per-request last-emit timestamps for TPOT accounting
        self._last_emit: dict[int, float] = {}

    # -- public API --------------------------------------------------------

    @property
    def busy(self) -> bool:
        return bool(self.scheduler.queue_depth or self.pool.n_active)

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int | None = None, top_p: float | None = None,
               eos_id: int | None = None, seed: int | None = None,
               on_token=None, arrival_time: float | None = None) -> Request:
        """Enqueue one request; returns its live handle immediately.

        ``arrival_time`` backdates ``submit_time`` to when the request
        actually ARRIVED (the open-loop simulator's Poisson timestamp), so
        TTFT absorbs queue wait accrued while the engine was inside a tick
        — without it, arrival-to-submit wait would silently vanish from
        the headline latency exactly in the overload regime."""
        import jax

        prompt = np.asarray(prompt, np.int32)
        validate_request(prompt, max_new_tokens, temperature, top_k, top_p,
                         self.cfg.vocab, self.max_len)
        rid = self._next_rid
        self._next_rid += 1
        seed = rid if seed is None else seed
        r = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_id=eos_id, seed=seed, on_token=on_token)
        # the request's independent key stream — the SAME key a solo
        # make_cached_decoder call would be handed, so streams align
        r.key_data = np.asarray(jax.random.key_data(jax.random.key(seed)))
        r.submit_time = (self._clock() if arrival_time is None
                         else arrival_time)
        self.requests[rid] = r
        self.scheduler.enqueue(r)
        if self.metrics is not None:
            self.metrics.on_submit()
        return r

    def step(self) -> int:
        """One tick (admit -> batched decode -> retire); returns the number
        of tokens emitted. A true no-op returning 0 when idle — idle ticks
        touch no metrics, so a polling loop cannot drag the occupancy
        histogram toward zero."""
        if not self.busy:
            return 0
        emitted = self._admit()
        # occupancy the batched decode actually RUNS at — sampled before
        # same-tick retirement so short requests cannot bias it low
        decode_active = self.pool.n_active
        emitted += self._decode_tick()
        if self.metrics is not None:
            self.metrics.on_tick(self.scheduler.queue_depth,
                                 self.pool.n_active, self.pool.n_slots,
                                 decode_active=decode_active)
        return emitted

    def drain(self, max_ticks: int | None = None) -> list[Request]:
        """Tick until idle (or ``max_ticks``); returns finished requests in
        completion order is not guaranteed — use ``handle.tokens``."""
        ticks = 0
        while self.busy:
            if max_ticks is not None and ticks >= max_ticks:
                raise RuntimeError(
                    f"drain exceeded {max_ticks} ticks with "
                    f"{self.scheduler.queue_depth} queued / "
                    f"{self.pool.n_active} active — a request is stuck")
            self.step()
            ticks += 1
        return [r for r in self.requests.values() if r.state == DONE]

    # -- tick internals ----------------------------------------------------

    def _admit(self) -> int:
        emitted = 0
        for r in self.scheduler.admit():
            t0 = int(r.prompt.shape[0])
            kc, vc, tok, kd = self._prefill(
                self.params, self.pool.kc, self.pool.vc,
                r.prompt[None, :], np.int32(r.slot), r.key_data,
                np.float32(r.temperature),
                np.int32(r.top_k if r.top_k is not None else _NO_TOP_K),
                np.float32(r.top_p if r.top_p is not None else _NO_TOP_P))
            self.pool.kc, self.pool.vc = kc, vc
            tok = int(np.asarray(tok))           # host sync: TTFT endpoint
            r.key_data = np.asarray(kd)
            now = self._clock()
            r.first_token_time = now
            self._last_emit[r.rid] = now
            r.emit(tok)
            emitted += 1
            if self.metrics is not None:
                self.metrics.on_first_token(r.ttft_s)
            reason = r.finished_by(tok)
            if reason is not None:
                self._finish(r, reason, now)
            else:
                self.pool.seat(r.slot, t0, tok)
        return emitted

    def _decode_tick(self) -> int:
        active = self.pool.active_slots()
        if not active:
            return 0
        S = self.pool.n_slots
        kd = np.zeros((S, 2), np.uint32)
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.full(S, _NO_TOP_P, np.float32)
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            kd[s] = r.key_data
            temps[s] = r.temperature
            top_ks[s] = r.top_k if r.top_k is not None else _NO_TOP_K
            top_ps[s] = r.top_p if r.top_p is not None else _NO_TOP_P
        kc, vc, toks, kd2 = self._decode(
            self.params, self.pool.kc, self.pool.vc,
            self.pool.last_token.copy(), self.pool.positions.copy(),
            kd, temps, top_ks, top_ps)
        self.pool.kc, self.pool.vc = kc, vc
        toks = np.asarray(toks)                  # host sync: tick endpoint
        kd2 = np.asarray(kd2)
        now = self._clock()
        emitted = 0
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            tok = int(toks[s])
            r.key_data = kd2[s]
            r.emit(tok)
            emitted += 1
            if self.metrics is not None:
                self.metrics.on_token(now - self._last_emit[r.rid])
            self._last_emit[r.rid] = now
            reason = r.finished_by(tok)
            if reason is not None:
                self._finish(r, reason, now)
            else:
                self.pool.advance(s, tok)
        return emitted

    def _finish(self, r: Request, reason: str, now: float) -> None:
        r.done_time = now
        self._last_emit.pop(r.rid, None)
        if r.state == ACTIVE:
            self.scheduler.retire(r, reason)
        if self.metrics is not None:
            self.metrics.on_complete()
