"""The continuous-batching inference engine: submit / step / drain.

:class:`InferenceEngine` is the serving API over the slot-wise model ops
(``models/gpt.py``), the KV-cache pool and the FCFS scheduler:

- ``submit(prompt, ...) -> Request`` enqueues one sequence with its own
  sampling params and seeded key stream, and returns the live handle
  (``handle.tokens`` grows as the engine runs; ``on_token`` streams);
- ``step()`` is one *tick*; ``drain()`` ticks until queue and slots are
  empty.

Two KV-cache layouts (``kv_layout``):

- ``"paged"`` (default) — block-table paged pool (``serve/slots.py::
  PagedKVPool``) with prefix sharing, copy-on-write and CHUNKED prefill:
  each tick runs at most one prefill chunk (``prefill_chunk`` prompt
  positions of the oldest still-prefilling request) and then ONE batched
  block-gather decode step over every decoding slot — a long prompt no
  longer freezes in-flight requests, and admission is gated on free
  BLOCKS (the request's worst-case footprint after prefix sharing), not
  free rows. Non-decoding slots' tick writes are routed to the pool's
  trash block (see the stale-write note in ``serve/slots.py``).
- ``"dense"`` — the PR-5 slot-row pool: admission prefills the whole
  prompt in one shot (``make_slot_prefill``) and every occupied slot
  decodes each tick. Kept as the measured baseline of
  ``bench.py --serve``'s paged-vs-dense comparison.

Device state is exactly the pool's K/V buffers; everything else (positions,
last tokens, block tables, key streams, request lifecycle) is host-side
numpy assembled into each tick's inputs — the scheduler stays plain Python
while every FLOP runs inside the compiled programs.

Correctness anchor: a request's tokens are bit-exact vs decoding it alone
via ``make_cached_decoder`` with the same seed (tests/test_serve.py) —
admission order, co-residents, occupancy, paged blocks, SHARED prefixes and
chunk boundaries cannot change anyone's output.
"""

from __future__ import annotations

import collections
import time

import numpy as np

from simple_distributed_machine_learning_tpu.resilience.faults import (
    maybe_fire,
)
from simple_distributed_machine_learning_tpu.serve.metrics import ServeMetrics
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    SHED,
    Request,
    validate_request,
)
from simple_distributed_machine_learning_tpu.serve.scheduler import (
    FCFSScheduler,
)
from simple_distributed_machine_learning_tpu.serve.slots import (
    KVCachePool,
    PagedKVPool,
)

# sampling-param sentinels (models/gpt.py::_sample_dyn): 0 disables top-k,
# anything > 1 disables top-p
_NO_TOP_K = 0
_NO_TOP_P = 2.0


class DrainTimeout(RuntimeError):
    """``drain(max_ticks=...)`` hit its cap with requests still in flight.

    Carries the abandoned work: ``unfinished`` is the list of live
    :class:`Request` handles (queued + active) at the moment the cap hit,
    so a caller can requeue, shed or report them instead of silently
    losing whatever the return value didn't include."""

    def __init__(self, max_ticks: int, unfinished: list):
        states = collections.Counter(r.state for r in unfinished)
        super().__init__(
            f"drain exceeded {max_ticks} ticks with {len(unfinished)} "
            f"unfinished request(s) ({dict(states)}) — rids "
            f"{[r.rid for r in unfinished]}")
        self.max_ticks = max_ticks
        self.unfinished = unfinished


class InferenceEngine:
    """Continuous-batching serving over a dense single-device GPT build.

    ``stages``/``cfg``: a ``make_gpt_stages`` build (dense-MLP, unsharded —
    the ``make_cached_decoder`` restrictions). ``params`` overrides the
    stages' init weights (e.g. checkpoint-restored trees from
    ``Pipeline.unpack``). ``max_len`` caps each slot's prompt+generation
    budget (defaults to ``cfg.seq_len``); ``cache_dtype`` is the pool's
    storage dtype (bf16 halves pool memory, the ``_cache_dtype`` rule).

    Paged knobs (``kv_layout="paged"``): ``block_size`` positions per K/V
    block; ``n_blocks`` pool capacity (default: the dense pool's capacity,
    ``n_slots * ceil(max_len/block_size)`` — shrink it to serve more slots
    than the memory could densely back); ``prefill_chunk`` prompt positions
    per prefill chunk (``None`` = the whole remaining prompt in one chunk);
    ``attn_kernel`` the decode/verify attention path — ``"dense"``
    (gather-then-dense, the parity anchor) or ``"fused"`` (the Pallas
    paged-attention kernel: block gather + online-softmax attention in one
    HBM pass, ``ops/paged_attention.py``; greedy token streams stay
    bit-exact vs ``"dense"``). A QUANTIZED ``cache_dtype`` (``"int8"``, or
    fp8 where the jnp build has it) stores paged blocks narrow with
    per-row f32 scales (``models/gpt.py::QuantKV``) — roughly 3.6x more
    resident requests per byte than f32 at pinned-tolerance logits, with
    dequantize fused into both attention paths; paged-only (dense layouts
    reject it). ``host_cache_blocks > 0`` enables the LRU host-RAM
    offload tier (evicted prefix blocks demote to host; a router affinity
    hit on a host-resident prefix starts an async upload landing after
    ``prefetch_ticks`` ticks — ``serve/slots.py`` "Host offload tier");
    paged-only as well.

    Tensor parallelism: build ``cfg`` with ``n_tensor_parallel = tp > 1``
    (the stages stay the UNSHARDED dense build) and pass a ``mesh`` whose
    ``model`` axis is exactly ``tp``. The engine slices the dense weights
    into the Megatron serving layout (``pack_tp_serve_params``) and places
    the K/V pool sharded over its HEAD axis, so every tick's compiled
    program runs head-sharded QKV/O + collective-matmul MLP over ``tp``
    chips and per-chip KV bytes drop by ``tp`` (the pool's
    ``serve_kv_bytes_resident`` gauge reports PER-SHARD bytes).

    Speculative decoding: pass ``draft_stages``/``draft_cfg`` (a smaller
    dense single-device build sharing the target's vocab) and
    ``spec_k >= 2``. Each tick then runs ONE draft propose scan plus ONE
    batched target verify instead of a one-token decode, emitting 1..
    ``spec_k`` tokens per slot; greedy requests stay bit-exact vs their
    solo decode (the models/gpt.py speculative-section contract). The
    draft keeps its own dense slot-pool K/V buffers and per-request key
    stream regardless of the target layout.

    Multi-tenant adapters: pass ``adapters`` (a
    :class:`~.adapters.AdapterStore` built for this engine's ``n_slots``)
    and every decode-path program is built with trailing adapter-bank
    args — each slot gathers its adapter's low-rank rows by a per-slot
    index, so one compiled program serves any adapter mix per tick and a
    hot-swap never retraces. ``submit(..., adapter="tenant")`` pins a
    request to a registered adapter; ``adapter=None`` rides bank row 0
    (the all-zero base row — its stream is identical to an engine with
    no adapter subsystem). The admission gate uploads/refcounts bank
    rows at tick boundaries; the paged prefix cache is namespaced per
    adapter so tenants can never share K/V computed under a different
    model.
    """

    def __init__(self, stages, cfg, *, params=None, n_slots: int = 4,
                 max_len: int | None = None, cache_dtype=None,
                 kv_layout: str = "paged", block_size: int = 16,
                 n_blocks: int | None = None, prefill_chunk: int | None = None,
                 host_cache_blocks: int = 0, prefetch_ticks: int = 1,
                 attn_kernel: str = "dense",
                 metrics: ServeMetrics | None = None,
                 scheduler: FCFSScheduler | None = None,
                 clock=time.monotonic, lint: bool = False,
                 mesh=None, draft_stages=None, draft_cfg=None,
                 spec_k: int = 0, trace=None, flight=None,
                 adapters=None) -> None:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            make_paged_block_copy,
            make_paged_decode_step,
            make_paged_prefill_chunk,
            make_paged_spec_tick,
            make_paged_verify_step,
            make_slot_decode_step,
            make_slot_prefill,
            make_slot_propose,
            make_slot_spec_tick,
            make_slot_verify_step,
        )
        if kv_layout not in ("paged", "dense"):
            raise ValueError(
                f"kv_layout must be 'paged' or 'dense', got {kv_layout!r}")
        if attn_kernel not in ("dense", "fused"):
            raise ValueError(
                f"attn_kernel must be 'dense' (gather-then-dense "
                f"attention) or 'fused' (the Pallas paged-attention "
                f"kernel), got {attn_kernel!r}")
        if attn_kernel == "fused" and kv_layout != "paged":
            raise ValueError(
                "attn_kernel='fused' is the paged pool's kernel (block-"
                "table gather fused with attention); the dense layout has "
                "no block tables — use kv_layout='paged'")
        if prefill_chunk is not None and prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 (or None for whole-prompt "
                f"chunks), got {prefill_chunk}")
        if kv_layout == "dense" and (prefill_chunk is not None
                                     or n_blocks is not None):
            raise ValueError(
                "prefill_chunk/n_blocks are paged-pool knobs; the dense "
                "layout prefills whole prompts into fixed rows")
        if kv_layout == "dense" and host_cache_blocks:
            raise ValueError(
                "host_cache_blocks is a paged-pool knob (the host offload "
                "tier demotes evicted prefix BLOCKS); the dense layout has "
                "no blocks to demote — use kv_layout='paged'")
        if (draft_stages is None) != (draft_cfg is None):
            raise ValueError(
                "speculative decoding needs BOTH draft_stages and "
                "draft_cfg (the draft build's config)")
        if draft_stages is not None and spec_k < 2:
            raise ValueError(
                f"speculative decoding needs spec_k >= 2 (got {spec_k}); "
                f"spec_k=1 is plain one-token decode — drop the draft")
        if draft_stages is None and spec_k:
            raise ValueError(
                f"spec_k={spec_k} without draft_stages/draft_cfg — the "
                f"draft model is what proposes the speculated tokens")
        if adapters is not None and adapters.n_rows != n_slots + 1:
            raise ValueError(
                f"AdapterStore has {adapters.n_rows} bank rows but this "
                f"engine needs n_slots + 1 = {n_slots + 1} (base row + one "
                f"per slot — the never-refuse sizing)")
        self._adapters = adapters
        self.cfg = cfg
        self.stages = stages       # kept for the analyzer's program registry
        self.kv_layout = kv_layout
        self.attn_kernel = attn_kernel
        self.prefill_chunk = prefill_chunk
        self.params = (params if params is not None
                       else [s.params for s in stages])
        self.max_len = int(max_len if max_len is not None else cfg.seq_len)
        self.tp = int(cfg.n_tensor_parallel)
        self.mesh = mesh if self.tp > 1 else None
        self.spec_k = int(spec_k)
        self.speculative = draft_stages is not None
        self.draft_stages = draft_stages   # for the analyzer's registry
        self.draft_cfg = draft_cfg
        n_layers = sum(len(p["blocks"]) for p in self.params)
        head_dim = cfg.d_model // cfg.n_heads
        adp = adapters is not None
        if kv_layout == "paged":
            self.pool = PagedKVPool(n_layers, n_slots, cfg.n_heads,
                                    self.max_len, head_dim, cache_dtype,
                                    block_size=block_size, n_blocks=n_blocks,
                                    tp=self.tp,
                                    host_cache_blocks=host_cache_blocks,
                                    prefetch_ticks=prefetch_ticks)
            self._chunk_prefill = make_paged_prefill_chunk(
                stages, cfg, self.max_len, block_size, cache_dtype,
                mesh=mesh, adapters=adp)
            self._decode = make_paged_decode_step(
                stages, cfg, self.max_len, block_size, cache_dtype,
                mesh=mesh, kernel=attn_kernel, adapters=adp)
            self._copy_block = make_paged_block_copy()
            if self.speculative:
                self._verify = make_paged_verify_step(
                    stages, cfg, self.max_len, block_size, spec_k,
                    cache_dtype, mesh=mesh, kernel=attn_kernel,
                    adapters=adp)
        else:
            self.pool = KVCachePool(n_layers, n_slots, cfg.n_heads,
                                    self.max_len, head_dim, cache_dtype,
                                    tp=self.tp)
            self._prefill = make_slot_prefill(stages, cfg, self.max_len,
                                              cache_dtype, mesh=mesh,
                                              adapters=adp)
            self._decode = make_slot_decode_step(stages, cfg, self.max_len,
                                                 cache_dtype, mesh=mesh,
                                                 adapters=adp)
            if self.speculative:
                self._verify = make_slot_verify_step(
                    stages, cfg, self.max_len, spec_k, cache_dtype,
                    mesh=mesh, adapters=adp)
        if self.speculative:
            if draft_cfg.vocab != cfg.vocab:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab} != target vocab "
                    f"{cfg.vocab} — the draft proposes target token ids")
            # the draft pool is dense slot rows (no per-block scales), so a
            # quantized TARGET dtype falls back to f32 for the draft — the
            # draft cache is small by design, and its rows feed proposals
            # only (acceptance always re-scores on the target)
            from simple_distributed_machine_learning_tpu.models.gpt import (
                _is_quantized_dtype,
            )
            self._draft_cache_dtype = (None if _is_quantized_dtype(
                cache_dtype) else cache_dtype)
            self._draft_prefill = make_slot_prefill(
                draft_stages, draft_cfg, self.max_len,
                self._draft_cache_dtype)
            self._propose = make_slot_propose(
                draft_stages, draft_cfg, self.max_len, spec_k,
                self._draft_cache_dtype)
            if self.tp == 1:
                # single-device targets run the FUSED tick: one dispatch
                # per speculative tick, draft rows never leave the device
                self._spec_fused = (
                    make_paged_spec_tick(stages, cfg, draft_stages,
                                         draft_cfg, self.max_len,
                                         block_size, spec_k, cache_dtype,
                                         kernel=attn_kernel, adapters=adp)
                    if kv_layout == "paged" else
                    make_slot_spec_tick(stages, cfg, draft_stages,
                                        draft_cfg, self.max_len, spec_k,
                                        cache_dtype, adapters=adp))
            else:
                # a TP target verifies in a shard_map program while the
                # draft stays replicated single-device — two dispatches
                self._spec_fused = None
            self._draft_params = [s.params for s in draft_stages]
            self._init_draft_pool(n_slots)
        if self.tp > 1:
            self._place_tp(mesh)
        if scheduler is None:
            scheduler = FCFSScheduler(self.pool)
        elif not isinstance(scheduler, FCFSScheduler) and callable(scheduler):
            # a scheduler CLASS/factory: the pool is engine-built, so the
            # caller cannot construct the instance up front
            scheduler = scheduler(self.pool)
        self.scheduler = scheduler
        self.scheduler.attach(self)
        if lint:
            # preflight the EXACT compiled programs this engine just built
            # (analysis/programs.py registry: scatter-bounds over the
            # block/position contracts, donation flow through the tick,
            # retrace policy) — trace-only, no FLOPs; construction fails
            # loudly on any ERROR finding rather than serving corruptable
            # programs
            from simple_distributed_machine_learning_tpu.analysis.programs import (  # noqa: E501
                lint_engine,
            )
            report = lint_engine(self)
            if not report.ok():
                raise RuntimeError(
                    "InferenceEngine(lint=True): the serve-program "
                    "preflight found ERROR findings:\n" + report.format())
        self.metrics = metrics
        # request-scoped tracing (serve/tracing.py) and the tick flight
        # recorder (serve/flight.py): both None by default — the hot path
        # pays exactly one `is None` test per site when disabled, and the
        # trace recorder is only ever handed timestamps this engine
        # already read (never a fresh clock read), so enabling it cannot
        # perturb virtual-clock scenario numbers
        self.trace = trace
        self.flight = flight
        self._n_layers = n_layers
        self._predict = None     # lazy (ServeSpec, predict_fn) for kv drift
        self._clock = clock
        # the engine's most recent clock reading — what trace events with
        # no clock read of their own (paged admission, preemption, crash)
        # are stamped with; updated at every site that reads the clock
        # anyway, NEVER by an extra read
        self._now = 0.0
        self._next_rid = 0
        self._tick_count = 0
        self.requests: dict[int, Request] = {}
        # rids admitted but not yet fully prefilled, admission order (the
        # chunked-prefill work queue; always empty in dense layout)
        self._prefilling: collections.deque[int] = collections.deque()
        # per-request last-emit timestamps for TPOT accounting
        self._last_emit: dict[int, float] = {}
        # rids whose current prefetch-gate episode already traced a
        # ``gate`` row (trace-only bookkeeping; cleared on boarding)
        self._gated: set[int] = set()

    def _init_draft_pool(self, n_slots: int) -> None:
        """The draft model's K/V buffers: ALWAYS the dense slot layout
        (one ``max_len`` row per slot), whatever the target layout — the
        draft is small by design, so paging it buys nothing, and the dense
        trailing-write argument keeps its rejected-tail rows safe."""
        import jax.numpy as jnp

        from simple_distributed_machine_learning_tpu.models.gpt import (
            _cache_dtype,
        )
        dcfg = self.draft_cfg
        dL = sum(len(p["blocks"]) for p in self._draft_params)
        ddh = dcfg.d_model // dcfg.n_heads
        cd = _cache_dtype(self._draft_cache_dtype)
        shape = (dL, n_slots, dcfg.n_heads, self.max_len, ddh)
        self._dkc = jnp.zeros(shape, cd)
        self._dvc = jnp.zeros(shape, cd)

    def _place_tp(self, mesh) -> None:
        """Shard the serving state for the TP programs: the K/V pool
        buffers split over their head axis (per-chip KV drops by ``tp``),
        the dense stage weights sliced into the Megatron serving layout
        (``pack_tp_serve_params``) with block shards on the model axis and
        embed/head replicated. One placement at construction; donation
        keeps the pool buffers sharded across ticks."""
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from simple_distributed_machine_learning_tpu.models.gpt import (
            pack_tp_serve_params,
        )
        from simple_distributed_machine_learning_tpu.parallel.mesh import (
            MODEL_AXIS,
        )
        # the head axis is dim 2 in every pool leaf — block data AND (for
        # quantized pools) the QuantKV scale planes — so one spec places
        # the whole pytree per-shard
        cache_sh = NamedSharding(mesh, P(None, None, MODEL_AXIS))
        self.pool.kc = jax.tree.map(
            lambda leaf: jax.device_put(leaf, cache_sh), self.pool.kc)
        self.pool.vc = jax.tree.map(
            lambda leaf: jax.device_put(leaf, cache_sh), self.pool.vc)
        stacked, rep = pack_tp_serve_params(self.params, self.tp)
        blk_sh = NamedSharding(mesh, P(MODEL_AXIS))
        rep_sh = NamedSharding(mesh, P())
        self.params = (
            [jax.tree.map(lambda leaf: jax.device_put(leaf, blk_sh), bp)
             for bp in stacked],
            jax.tree.map(lambda leaf: jax.device_put(leaf, rep_sh), rep))

    # -- public API --------------------------------------------------------

    @property
    def busy(self) -> bool:
        return bool(self.scheduler.queue_depth or self.pool.n_active)

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int | None = None, top_p: float | None = None,
               eos_id: int | None = None, seed: int | None = None,
               on_token=None, arrival_time: float | None = None,
               cls: str | None = None, priority: int = 0,
               ttft_deadline_s: float | None = None,
               deadline_s: float | None = None,
               adapter: str | None = None) -> Request:
        """Enqueue one request; returns its live handle immediately.

        ``arrival_time`` backdates ``submit_time`` to when the request
        actually ARRIVED (the open-loop simulator's Poisson timestamp), so
        TTFT absorbs queue wait accrued while the engine was inside a tick
        — without it, arrival-to-submit wait would silently vanish from
        the headline latency exactly in the overload regime.

        ``ttft_deadline_s``/``deadline_s`` are stored on the handle; the
        serve SUPERVISOR enforces them at tick boundaries (an unsupervised
        engine is the no-deadline baseline)."""
        import jax

        # fault-injection site: a crash while the request is being accepted
        # (journaled by the supervisor but never admitted — the recovery
        # corner serve/supervisor.py re-admits from the journal alone)
        maybe_fire("serve.admit", step=self._next_rid)
        prompt = np.asarray(prompt, np.int32)
        validate_request(prompt, max_new_tokens, temperature, top_k, top_p,
                         self.cfg.vocab, self.max_len)
        for name, v in (("ttft_deadline_s", ttft_deadline_s),
                        ("deadline_s", deadline_s)):
            if v is not None and v <= 0:
                raise ValueError(f"{name} must be > 0, got {v}")
        self._check_adapter(adapter)
        rid = self._next_rid
        self._next_rid += 1
        seed = rid if seed is None else seed
        r = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    eos_id=eos_id, seed=seed, on_token=on_token,
                    cls=cls, priority=priority,
                    ttft_deadline_s=ttft_deadline_s, deadline_s=deadline_s,
                    adapter=adapter)
        if self._adapters is not None:
            # the version-qualified prefix-cache namespace (refreshed at
            # the admission gate — the probe and the decode must agree on
            # the adapter VERSION or a hot-swap could reuse stale K/V)
            r._prefix_ns = self._adapters.namespace_of(adapter)
        # the request's independent key stream — the SAME key a solo
        # make_cached_decoder call would be handed, so streams align
        r.key_data = np.asarray(jax.random.key_data(jax.random.key(seed)))
        if self.speculative:
            # the draft's own stream, derived but disjoint (fold_in), so
            # sampled proposals never consume the target's splits — greedy
            # consumes neither, which is what keeps greedy speculative
            # decode bit-exact vs solo
            r.draft_key_data = np.asarray(jax.random.key_data(
                jax.random.fold_in(jax.random.key(seed), 1)))
        r.submit_time = (self._clock() if arrival_time is None
                         else arrival_time)
        self._now = max(self._now, r.submit_time)
        self.requests[rid] = r
        self.scheduler.enqueue(r)
        if self.metrics is not None:
            self.metrics.on_submit()
        if self.trace is not None:
            self.trace.on_submit(r, r.submit_time)
        return r

    # -- adapter plumbing --------------------------------------------------

    def register_adapter(self, name: str, weights: dict) -> None:
        """Add or hot-swap a named LoRA adapter (host-side only; the
        device row uploads at the next admission). Same call shape as
        :meth:`ServeSupervisor.register_adapter` /
        :meth:`ServeFleet.register_adapter`, so callers can target any
        serving tier uniformly."""
        if self._adapters is None:
            raise ValueError("this engine was built without an "
                             "AdapterStore — pass adapters= at "
                             "construction")
        self._adapters.register(name, weights)

    def _check_adapter(self, adapter: str | None) -> None:
        if adapter is None:
            return
        if self._adapters is None:
            raise ValueError(
                f"request names adapter {adapter!r} but this engine was "
                f"built without an AdapterStore — pass adapters= at "
                f"construction")
        if not self._adapters.is_registered(adapter):
            raise KeyError(
                f"adapter {adapter!r} is not registered "
                f"(known: {list(self._adapters.names())})")

    def _adapter_board(self, r: Request) -> bool:
        """The scheduler's admission gate: pin the request's adapter row
        (uploading at this tick boundary if needed) and take its ref.
        Structurally never refuses — the bank has one more row than the
        pool has slots, and admission already holds a free slot."""
        if getattr(r, "adapter", None) is None or self._adapters is None:
            r._adapter_row = 0
            return True
        # a hot-swap between submit and boarding changes the version this
        # admission will pin: refresh the prefix namespace (and drop the
        # stale probe memo) BEFORE bind_seq probes the registry, so the
        # K/V the request reuses was computed under the version it decodes
        ns = self._adapters.namespace_of(r.adapter)
        if getattr(r, "_prefix_ns", None) != ns:
            r._prefix_ns = ns
            r._prefix_probe = None
        r._adapter_row = self._adapters.retain(r.adapter)
        return True

    def _adapter_release(self, r: Request) -> None:
        row = getattr(r, "_adapter_row", 0)
        if row and self._adapters is not None:
            self._adapters.release(row)
        r._adapter_row = 0

    def _adapter_inputs(self, active: list[int]) -> np.ndarray:
        """Per-slot adapter row indices for a batched tick — the same
        discipline as :meth:`_sampling_inputs` (inactive slots gather the
        zero base row, whose delta is exactly 0)."""
        aids = np.zeros(self.pool.n_slots, np.int32)
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            aids[s] = getattr(r, "_adapter_row", 0)
        return aids

    def _bank_args(self, aids) -> tuple:
        """The trailing ``(bank, aids)`` program args — empty without a
        store, so every call site stays a one-splat edit."""
        if self._adapters is None:
            return ()
        return (self._adapters.bank, aids)

    def step(self) -> int:
        """One tick; returns the number of tokens emitted. A true no-op
        returning 0 when idle — idle ticks touch no metrics, so a polling
        loop cannot drag the occupancy histogram toward zero.

        Dense tick: admit (whole-prompt prefill each) -> batched decode ->
        retire. Paged tick: admit (board slots, match prefixes, reserve
        blocks) -> ONE prefill chunk of the oldest prefilling request ->
        batched block-gather decode over the DECODING slots -> retire.
        """
        if not self.busy:
            return 0
        # fault-injection site (resilience/faults.py): slow-tick stalls the
        # tick (a degraded device), wedged-device raises DeviceWedged —
        # no-op without an installed plan
        maybe_fire("serve.tick", step=self._tick_count)
        self._tick_count += 1
        if self.kv_layout == "dense":
            emitted = self._admit_dense()
            # occupancy the batched decode actually RUNS at — sampled before
            # same-tick retirement so short requests cannot bias it low
            decode_active = self.pool.n_active
            emitted += (self._spec_tick(self.pool.active_slots())
                        if self.speculative else self._decode_tick_dense())
        else:
            # host-tier upload progress FIRST: blocks completing this tick
            # register before admission probes the prefix registry, so a
            # request blocked on its own prefetch boards this very tick
            self.pool.advance_transfers()
            if self.trace is not None and getattr(self.pool, "_inflight",
                                                  None):
                # trace the upload gate: a queued request held back by its
                # own in-flight prefetch gets ONE ``gate`` row per episode
                # (attribution's queue-vs-prefetch split). Stamped with
                # the most recent clock read, like paged admission — and
                # only probed while uploads are actually in flight, so
                # the common path pays one attribute test
                for r in self.scheduler.queue:
                    if (r.rid not in self._gated
                            and self.pool.prefetch_blocked(r)):
                        self._gated.add(r.rid)
                        self.trace.on_gate(r, self._now)
            self._admit_paged()
            emitted = self._prefill_tick()
            decoding = self._decoding_slots()
            decode_active = len(decoding)
            emitted += (self._spec_tick(decoding) if self.speculative
                        else self._decode_tick_paged(decoding))
        if self.metrics is not None:
            live, predicted = self.kv_drift()
            self.metrics.on_tick(
                self.scheduler.queue_depth, self.pool.n_active,
                self.pool.n_slots, decode_active=decode_active,
                block_stats=(self.pool.stats()
                             if self.kv_layout == "paged" else None),
                tp=self.tp, spec_k=self.spec_k,
                kv_predicted=predicted, kv_drift=live - predicted,
                attn_kernel=self.attn_kernel,
                adapter_stats=(self._adapters.stats()
                               if self._adapters is not None else None))
        if self.flight is not None:
            self.flight.snap(self, self._tick_count, emitted)
        return emitted

    def kv_drift(self) -> tuple[int, int]:
        """``(live, predicted)`` resident K/V bytes: the pool's
        ``serve_kv_bytes_resident`` gauge next to the PR-8 analyzer's
        ``predict_kv_bytes_resident`` over the live sequences' written-row
        counts — the static model checked as a RUNTIME invariant every
        tick. ``live - predicted`` is the drift gauge: exactly 0 without
        prefix sharing, ≤ 0 with it (sharing only shrinks the truth), and
        > 0 only if the pool leaks blocks the model says no live sequence
        can be pinning."""
        if self._predict is None:
            from simple_distributed_machine_learning_tpu.analysis.programs import (  # noqa: E501
                engine_spec,
                predict_kv_bytes_resident,
            )
            # the SAME engine->spec mapping the lint preflight uses, so
            # the drift check can never describe a different deployment
            self._predict = (engine_spec(self), predict_kv_bytes_resident)
        sspec, predict = self._predict
        rows = []
        if self.kv_layout == "paged":
            for s in self.pool.active_slots():
                r = self.requests[self.pool.occupant(s)]
                n = (r.prefill_pos if r.prefill_pos is not None
                     else int(self.pool.positions[s]))
                if n > 0:
                    rows.append(n)
        return (self.pool.bytes_resident(),
                predict(sspec, rows, n_layers=self._n_layers))

    def preempt(self, rid: int) -> None:
        """Evict an ACTIVE request from its slot (priority scheduling's
        room-making — ``PriorityScheduler._make_room``): the slot and its
        K/V blocks free NOW, the request returns to the queue front with
        its emitted tokens intact. Re-admission recomputes K/V for
        ``resume_seq`` (registered prefix blocks usually make that cheap)
        and reseats on the stored last token with the key stream untouched,
        so the continued decode is bit-exact vs an unpreempted run.

        Compile-cost note: the dense layout (and a paged engine with
        ``prefill_chunk=None``) prefills whole sequences, retracing per
        distinct length — every distinct preemption point is a fresh XLA
        compile. Preemption-heavy serving should run the default paged
        layout WITH a ``prefill_chunk``, which bounds prefill shapes to
        chunk sizes the engine has already compiled."""
        r = self.requests[rid]
        if r.state != ACTIVE or r.slot is None:
            raise ValueError(
                f"request {rid} is not active (state {r.state!r}, slot "
                f"{r.slot!r}) — only active requests preempt")
        try:
            self._prefilling.remove(rid)   # may be mid-prefill
        except ValueError:
            pass
        self.pool.unbind_seq(r.slot)
        self.pool.release(r.slot)
        self._adapter_release(r)   # re-acquired (maybe a new row) on re-admit
        r.slot = None
        r.prefill_pos = None
        r.state = QUEUED
        r.n_preempted += 1
        # front of the queue: the victim arrived before anything still
        # waiting in its own class (pick() is priority-then-FCFS, so this
        # only orders it within its class)
        self.scheduler.queue.appendleft(r)
        if self.metrics is not None:
            self.metrics.on_preempt(r.cls)
        if self.trace is not None:
            self.trace.on_preempt(r, self._now)

    def cancel(self, rid: int, reason: str = "cancelled") -> Request:
        """Remove a live request NOW with a structured rejection: a queued
        request leaves the queue, an active one frees its slot and (paged)
        decrefs its table blocks and returns its unused reservation — the
        full budget refund, same release path as retirement — and the
        handle lands in ``SHED`` with ``finish_reason = reason``. The
        supervisor's deadline/overload shedding calls this; metrics
        accounting is the CALLER's job (it knows the reason taxonomy)."""
        r = self.requests[rid]
        if r.state not in (QUEUED, ACTIVE):
            raise ValueError(
                f"request {rid} is {r.state!r} — only queued/active "
                f"requests cancel")
        if r.state == ACTIVE:
            try:
                self._prefilling.remove(rid)    # may be mid-prefill
            except ValueError:
                pass
            self.pool.unbind_seq(r.slot)
            self.pool.release(r.slot)
            self._adapter_release(r)
            r.slot = None
            r.prefill_pos = None
        else:
            # identity scan, not deque.remove: Request's dataclass __eq__
            # would compare prompt arrays between same-rid duplicates
            for i, q in enumerate(self.scheduler.queue):
                if q is r:
                    del self.scheduler.queue[i]
                    break
            else:               # pragma: no cover - state-machine guard
                raise RuntimeError(
                    f"queued request {rid} missing from the scheduler "
                    f"queue — lifecycle bookkeeping corrupted")
        r.state = SHED
        r.finish_reason = reason
        r.done_time = self._now = self._clock()
        self._last_emit.pop(rid, None)
        if self.trace is not None:
            self.trace.on_shed(r, r.done_time, reason)
        return r

    def restore(self, request: Request) -> Request:
        """Re-admit a journal-recovered request into THIS engine (the serve
        supervisor's rebuild path): the handle keeps its rid, emitted
        tokens and live key stream, re-enters the queue and — exactly like
        a PR-7 preemption victim — re-prefills ``resume_seq`` on boarding
        with the sample and key advance discarded, reseating on its stored
        newest token, so the continued decode is bit-exact vs the
        uninterrupted run. Callers re-admit in rid order to preserve FCFS
        arrival order across the restart."""
        import jax

        if request.rid in self.requests:
            raise ValueError(f"request {request.rid} already lives in this "
                             f"engine — restore() is for rebuilt engines")
        validate_request(request.prompt, request.max_new_tokens,
                         request.temperature, request.top_k, request.top_p,
                         self.cfg.vocab, self.max_len)
        self._check_adapter(getattr(request, "adapter", None))
        request.state = QUEUED
        request.slot = None
        request.prefill_pos = None
        request._adapter_row = 0   # re-acquired at boarding on THIS engine
        request._prefix_ns = (
            None if self._adapters is None
            else self._adapters.namespace_of(
                getattr(request, "adapter", None)))
        request._prefix_probe = None   # probed against THIS pool's registry
        if request.key_data is None:
            # never emitted a token: the stream starts where submit's would
            request.key_data = np.asarray(
                jax.random.key_data(jax.random.key(request.seed)))
        if self.speculative and request.draft_key_data is None:
            request.draft_key_data = np.asarray(jax.random.key_data(
                jax.random.fold_in(jax.random.key(request.seed), 1)))
        self.requests[request.rid] = request
        self._next_rid = max(self._next_rid, request.rid + 1)
        self.scheduler.enqueue(request)
        if self.trace is not None:
            self.trace.on_readmit(request, self._now)
        return request

    def drain(self, max_ticks: int | None = None) -> list[Request]:
        """Tick until idle (or ``max_ticks``); returns finished requests in
        completion order is not guaranteed — use ``handle.tokens``.

        Hitting the cap with work still in flight raises
        :class:`DrainTimeout` carrying the unfinished request handles —
        abandoned requests are a loud, structured signal, never a
        silently shorter return value (tests/test_serve.py pins it)."""
        ticks = 0
        while self.busy:
            if max_ticks is not None and ticks >= max_ticks:
                raise DrainTimeout(max_ticks, [
                    r for r in self.requests.values()
                    if r.state in (QUEUED, ACTIVE)])
            self.step()
            ticks += 1
        return [r for r in self.requests.values() if r.state == DONE]

    # -- dense tick internals ---------------------------------------------

    def _admit_dense(self) -> int:
        emitted = 0
        for r in self.scheduler.admit():
            seq = r.resume_seq       # == r.prompt unless resuming preempted
            t0 = int(seq.shape[0])
            kc, vc, tok, kd = self._prefill(
                self.params, self.pool.kc, self.pool.vc,
                seq[None, :], np.int32(r.slot), r.key_data,
                np.float32(r.temperature),
                np.int32(r.top_k if r.top_k is not None else _NO_TOP_K),
                np.float32(r.top_p if r.top_p is not None else _NO_TOP_P),
                *self._bank_args(np.int32(getattr(r, "_adapter_row", 0))))
            self.pool.kc, self.pool.vc = kc, vc
            if self.speculative:
                self._draft_prefill_slot(r, seq)
            if r.tokens:
                # resuming after preemption: the prefill only rebuilt K/V;
                # its sampled token AND advanced key are discarded (the key
                # stream already consumed this split before the preemption)
                # and decode restarts from the stored newest token. The
                # TPOT base resets to NOW deliberately: the stall is
                # preemption wait, tracked by the preemption counters (and
                # the request-level tpot_s mean), not decode cadence — one
                # giant sample would distort the per-class cadence
                # histogram the SLO gate reads
                self.pool.seat(r.slot, t0, r.tokens[-1])
                now = self._now = self._clock()
                self._last_emit[r.rid] = now
                if self.trace is not None:
                    self.trace.on_admit(r, now, r.slot)
                    self.trace.on_resume(r, now)
                continue
            tok = int(np.asarray(tok))           # host sync: TTFT endpoint
            r.key_data = np.asarray(kd)
            now = self._now = self._clock()
            r.first_token_time = now
            self._last_emit[r.rid] = now
            r.emit(tok)
            emitted += 1
            if self.metrics is not None:
                self.metrics.on_first_token(r.ttft_s, cls=r.cls)
            if self.trace is not None:
                # dense admission prefills in one shot: boarding and the
                # TTFT endpoint share this tick's single clock read
                self.trace.on_admit(r, now, r.slot)
                self.trace.on_first_token(r, now)
            reason = r.finished_by(tok)
            if reason is not None:
                self._finish(r, reason, now)
            else:
                self.pool.seat(r.slot, t0, tok)
        return emitted

    def _decode_tick_dense(self) -> int:
        active = self.pool.active_slots()
        if not active:
            return 0
        kd, temps, top_ks, top_ps = self._sampling_inputs(active)
        kc, vc, toks, kd2 = self._decode(
            self.params, self.pool.kc, self.pool.vc,
            self.pool.last_token.copy(), self.pool.positions.copy(),
            kd, temps, top_ks, top_ps,
            *self._bank_args(self._adapter_inputs(active)))
        self.pool.kc, self.pool.vc = kc, vc
        return self._emit_decoded(active, toks, kd2)

    # -- paged tick internals ---------------------------------------------

    def _admit_paged(self) -> None:
        """Board waiting requests. The scheduler's admit loop already bound
        each sequence to its slot (prefix matched, shared blocks
        referenced, worst-case budget reserved — ``PagedKVPool.bind_seq``)
        and parked the first position to compute in ``r.prefill_pos``. No
        model FLOPs here — prefill happens chunk by chunk in
        :meth:`_prefill_tick`."""
        for r in self.scheduler.admit():
            self._prefilling.append(r.rid)
            self._gated.discard(r.rid)
            if self.trace is not None:
                # boarding performs no clock read; stamped with the most
                # recent one (at most a tick stale, see serve/tracing.py)
                self.trace.on_admit(r, self._now, r.slot)

    def _prefill_tick(self) -> int:
        """At most ONE prefill chunk per tick — the scheduler's budget that
        keeps a long prompt from stalling every decode tick. Processes the
        oldest still-prefilling request (FCFS, matching admission order);
        the final chunk samples the request's first token (TTFT endpoint)
        and registers its prompt blocks for future prefix sharing."""
        if not self._prefilling:
            return 0
        r = self.requests[self._prefilling[0]]
        seq = r.resume_seq           # == r.prompt unless resuming preempted
        plen = int(seq.shape[0])
        p0 = r.prefill_pos
        c = (plen - p0 if self.prefill_chunk is None
             else min(self.prefill_chunk, plen - p0))
        t_start = self._now = self._clock()
        self._ensure_writable_range(r.slot, p0, c)
        kc, vc, tok, kd = self._chunk_prefill(
            self.params, self.pool.kc, self.pool.vc,
            seq[None, p0:p0 + c], np.int32(p0),
            self.pool.device_table(r.slot), r.key_data,
            np.float32(r.temperature),
            np.int32(r.top_k if r.top_k is not None else _NO_TOP_K),
            np.float32(r.top_p if r.top_p is not None else _NO_TOP_P),
            *self._bank_args(np.int32(getattr(r, "_adapter_row", 0))))
        self.pool.kc, self.pool.vc = kc, vc
        tok = int(np.asarray(tok))     # host sync: honest chunk timing
        now = self._now = self._clock()
        if self.metrics is not None:
            self.metrics.on_prefill_chunk((now - t_start) * 1e3)
        if self.trace is not None:
            self.trace.on_prefill_chunk(r, t_start, now, p0, c)
        if p0 + c < plen:
            # mid-prompt chunk: the sampled token AND returned key are
            # discarded — the request's key stream advances exactly once,
            # at the final chunk, where its solo decode would split too
            r.prefill_pos = p0 + c
            return 0
        self._prefilling.popleft()
        r.prefill_pos = None
        # publish the sequence's blocks BEFORE any same-tick retirement so
        # even a 1-token request leaves its prefix reusable (cached blocks
        # survive end_seq as reclaimable)
        self.pool.register_prefix(r.slot, seq)
        if self.speculative:
            # the draft prefills the WHOLE sequence in one shot at the
            # final target chunk: its cache must cover every prompt
            # position before the first propose scan, and the draft is
            # cheap by design (no chunking needed)
            self._draft_prefill_slot(r, seq)
        if r.tokens:
            # resuming after preemption: the final chunk only rebuilt K/V;
            # its sample and advanced key are discarded like a mid-prompt
            # chunk's (the stream already consumed this split before the
            # preemption) and decode restarts from the stored newest token.
            # TPOT base resets to NOW deliberately (see the dense twin):
            # preemption wait is not decode cadence
            self.pool.seat(r.slot, plen, r.tokens[-1])
            self._last_emit[r.rid] = now
            if self.trace is not None:
                self.trace.on_resume(r, now)
            return 0
        r.key_data = np.asarray(kd)
        r.first_token_time = now
        self._last_emit[r.rid] = now
        r.emit(tok)
        if self.metrics is not None:
            self.metrics.on_first_token(r.ttft_s, cls=r.cls)
        if self.trace is not None:
            self.trace.on_first_token(r, now)
        reason = r.finished_by(tok)
        if reason is not None:
            self._finish(r, reason, now)
        else:
            self.pool.seat(r.slot, plen, tok)
        return 1

    def _decoding_slots(self) -> list[int]:
        """Occupied slots whose request finished prefilling — the batched
        decode's participants this tick (still-prefilling slots sit out)."""
        return [s for s in self.pool.active_slots()
                if self.requests[self.pool.occupant(s)].prefill_pos is None]

    def _decode_tick_paged(self, active: list[int]) -> int:
        if not active:
            return 0
        S = self.pool.n_slots
        kd, temps, top_ks, top_ps = self._sampling_inputs(active)
        # non-decoding slots: position 0 + all-trash table, so their
        # garbage write lands in the trash block no table references
        pos = np.zeros(S, np.int32)
        toks = np.zeros(S, np.int32)
        tables = np.full((S, self.pool.blocks_per_seq), PagedKVPool.TRASH,
                         np.int32)
        for s in active:
            # on-demand block allocation as this position advances (and
            # copy-on-write if the write block is still shared)
            self._ensure_writable_range(s, int(self.pool.positions[s]), 1)
            tables[s] = self.pool.device_table(s)
            pos[s] = self.pool.positions[s]
            toks[s] = self.pool.last_token[s]
        kc, vc, toks2, kd2 = self._decode(
            self.params, self.pool.kc, self.pool.vc,
            toks, pos, tables, kd, temps, top_ks, top_ps,
            *self._bank_args(self._adapter_inputs(active)))
        self.pool.kc, self.pool.vc = kc, vc
        return self._emit_decoded(active, toks2, kd2)

    def _ensure_writable_range(self, slot: int, p0: int, n: int) -> None:
        """Allocate/copy-on-write every block covering positions
        ``[p0, p0+n)`` of ``slot``'s sequence; runs the device block copy
        the pool asks for."""
        for p in range(p0, p0 + n):
            cp = self.pool.ensure_writable(slot, p)
            if cp is not None:
                src, dst = cp
                self.pool.kc, self.pool.vc = self._copy_block(
                    self.pool.kc, self.pool.vc, np.int32(dst), np.int32(src))

    # -- speculative tick internals ----------------------------------------

    def _draft_prefill_slot(self, r: Request, seq: np.ndarray) -> None:
        """Record the draft model's K/V for ``seq`` into the draft pool's
        slot row. Greedy sampling args + a dummy key: the prefill's sampled
        token and advanced key are discarded — only the cache write
        matters, so neither the request's target stream nor its draft
        stream moves here."""
        dkc, dvc, _tok, _kd = self._draft_prefill(
            self._draft_params, self._dkc, self._dvc, seq[None, :],
            np.int32(r.slot), np.zeros(2, np.uint32), np.float32(0.0),
            np.int32(_NO_TOP_K), np.float32(_NO_TOP_P))
        self._dkc, self._dvc = dkc, dvc

    def _spec_tick(self, active: list[int]) -> int:
        """One speculative decode tick over the decoding slots: the draft
        propose scan (``spec_k`` fused draft steps) then the batched
        target verify, emitting 1..``spec_k`` tokens per slot. On a
        single-device target both halves run as ONE fused compiled
        program (``make_*_spec_tick``: one dispatch per tick, the draft's
        ``[S, K, V]`` log-prob rows never leave the device); a TP target
        runs them as two dispatches (the verify is a shard_map program,
        the draft stays replicated), proposals flowing between on device
        with no host sync until the verify returns."""
        if not active:
            return 0
        S, K = self.pool.n_slots, self.spec_k
        kd, temps, top_ks, top_ps = self._sampling_inputs(active)
        toks = np.zeros(S, np.int32)
        pos = np.zeros(S, np.int32)
        valid = np.zeros(S, np.int32)
        dkd = np.zeros((S, 2), np.uint32)
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            toks[s] = self.pool.last_token[s]
            pos[s] = self.pool.positions[s]
            # the per-slot clamp: never speculate past the remaining token
            # budget, so every real K/V write stays inside the slot's
            # reservation (non-decoding slots keep valid 0 -> all-trash)
            valid[s] = min(K, r.max_new_tokens - len(r.tokens))
            dkd[s] = r.draft_key_data
        tables = None
        if self.kv_layout == "paged":
            tables = np.full((S, self.pool.blocks_per_seq), PagedKVPool.TRASH,
                             np.int32)
            for s in active:
                self._ensure_writable_range(s, int(pos[s]), int(valid[s]))
                tables[s] = self.pool.device_table(s)
        # adapters ride the VERIFY side only: the draft proposes as the
        # base model (a wrong proposal costs acceptance rate, never
        # correctness — the adapted verify rows decide every emission)
        bank_args = self._bank_args(self._adapter_inputs(active))
        if self._spec_fused is not None:
            args = (toks, pos, valid) + (() if tables is None
                                         else (tables,))
            dkc, dvc, kc, vc, otoks, nacc, kd2, dkd2 = self._spec_fused(
                self._draft_params, self._dkc, self._dvc, self.params,
                self.pool.kc, self.pool.vc, *args, dkd, kd, temps,
                top_ks, top_ps, *bank_args)
        else:
            dkc, dvc, drafts, qrows, dkd2 = self._propose(
                self._draft_params, self._dkc, self._dvc, toks, pos, dkd,
                temps, top_ks, top_ps)
            # the propose outputs flow into verify VERBATIM, still on
            # device; verify itself consumes only the first K-1 proposals
            # (the K-th exists to keep the draft cache ahead; models/gpt.py
            # section comment)
            if tables is not None:
                kc, vc, otoks, nacc, kd2 = self._verify(
                    self.params, self.pool.kc, self.pool.vc, toks, pos,
                    drafts, qrows, valid, tables, kd, temps, top_ks,
                    top_ps, *bank_args)
            else:
                kc, vc, otoks, nacc, kd2 = self._verify(
                    self.params, self.pool.kc, self.pool.vc, toks, pos,
                    drafts, qrows, valid, kd, temps, top_ks, top_ps,
                    *bank_args)
        self._dkc, self._dvc = dkc, dvc
        self.pool.kc, self.pool.vc = kc, vc
        return self._emit_spec(active, otoks, nacc, kd2, dkd2, valid)

    def _emit_spec(self, active: list[int], otoks, nacc, kd2, dkd2,
                   valid) -> int:
        """Host-side tail of a speculative tick: emit each slot's accepted
        tokens in order (truncating at EOS — later positions' K/V is
        already written but gets overwritten before it can be attended),
        advance positions by the count actually emitted, and feed the
        proposed/accepted counters."""
        otoks = np.asarray(otoks)                # host sync: tick endpoint
        nacc = np.asarray(nacc)
        kd2 = np.asarray(kd2)
        dkd2 = np.asarray(dkd2)
        now = self._now = self._clock()
        emitted = proposed = accepted = 0
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            r.key_data = kd2[s]
            r.draft_key_data = dkd2[s]
            m = int(nacc[s])                     # >= 1: valid[s] >= 1
            n_emit = 0
            finish = None
            for tok in otoks[s, :m]:
                n_emit += 1
                r.emit(int(tok))
                finish = r.finished_by(int(tok))
                if finish is not None:
                    break
            dt = now - self._last_emit[r.rid]
            if self.metrics is not None:
                # the tick emitted n_emit tokens in one dt window: spread
                # the interval so the TPOT mean stays the true cadence
                for _ in range(n_emit):
                    self.metrics.on_token(dt / n_emit, cls=r.cls)
            self._last_emit[r.rid] = now
            emitted += n_emit
            slot_proposed = max(int(valid[s]) - 1, 0)
            slot_accepted = max(n_emit - 1, 0)
            proposed += slot_proposed
            accepted += slot_accepted
            if self.trace is not None:
                self.trace.on_tick_tokens(r, now, n_emit,
                                          proposed=slot_proposed,
                                          accepted=slot_accepted)
            if finish is not None:
                self._finish(r, finish, now)
            else:
                self.pool.positions[s] += n_emit
                self.pool.last_token[s] = r.tokens[-1]
        if self.metrics is not None and proposed:
            self.metrics.on_spec(proposed, accepted)
        return emitted

    # -- shared tick tails -------------------------------------------------

    def _sampling_inputs(self, active: list[int]):
        S = self.pool.n_slots
        kd = np.zeros((S, 2), np.uint32)
        temps = np.zeros(S, np.float32)
        top_ks = np.zeros(S, np.int32)
        top_ps = np.full(S, _NO_TOP_P, np.float32)
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            kd[s] = r.key_data
            temps[s] = r.temperature
            top_ks[s] = r.top_k if r.top_k is not None else _NO_TOP_K
            top_ps[s] = r.top_p if r.top_p is not None else _NO_TOP_P
        return kd, temps, top_ks, top_ps

    def _emit_decoded(self, active: list[int], toks, kd2) -> int:
        toks = np.asarray(toks)                  # host sync: tick endpoint
        kd2 = np.asarray(kd2)
        now = self._now = self._clock()
        emitted = 0
        for s in active:
            r = self.requests[self.pool.occupant(s)]
            tok = int(toks[s])
            r.key_data = kd2[s]
            r.emit(tok)
            emitted += 1
            if self.metrics is not None:
                self.metrics.on_token(now - self._last_emit[r.rid],
                                      cls=r.cls)
            if self.trace is not None:
                self.trace.on_tick_tokens(r, now, 1)
            self._last_emit[r.rid] = now
            reason = r.finished_by(tok)
            if reason is not None:
                self._finish(r, reason, now)
            else:
                self.pool.advance(s, tok)
        return emitted

    def _finish(self, r: Request, reason: str, now: float) -> None:
        r.done_time = now
        self._last_emit.pop(r.rid, None)
        if self.trace is not None:
            self.trace.on_finish(r, now, reason)
        if r.state == ACTIVE:
            # scheduler.retire unbinds the sequence (paged: decref table
            # blocks — registered ones stay reclaimable — and return the
            # unused reservation) before the slot frees
            self.scheduler.retire(r, reason)
        self._adapter_release(r)
        if self.metrics is not None:
            self.metrics.on_complete(cls=r.cls,
                                     adapter=getattr(r, "adapter", None))
