"""The serving request object: prompt, sampling params, lifecycle timestamps.

One :class:`Request` is one user sequence moving through the engine:
``QUEUED`` (waiting for a slot) → ``ACTIVE`` (owns a KV-cache slot, decoding)
→ ``DONE`` (EOS emitted or ``max_new_tokens`` reached; slot freed), or →
``SHED`` (the overload/deadline exit: the supervisor cancelled it with a
structured rejection in ``finish_reason`` — ``deadline``, ``backpressure``
or ``class`` — and its slot/block budget was refunded). Sampling
config is per-request — greedy (``temperature=0``) or temperature sampling
with optional top-k / top-p filtering — with an independent key stream seeded
from ``seed``, so two requests never share randomness and each one's tokens
are bit-exact vs decoding it alone (tests/test_serve.py).

Latency accounting follows the serving-standard split: TTFT (time to first
token — queue wait + prefill) and TPOT (time per output token — the decode
tick cadence), both recorded by the engine on host wall-clock.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

QUEUED = "queued"
ACTIVE = "active"
DONE = "done"
SHED = "shed"


@dataclasses.dataclass
class Request:
    """One sequence's serving state; constructed via ``engine.submit``."""

    rid: int
    prompt: np.ndarray                  # [T0] int32 tokens
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int | None = None
    top_p: float | None = None
    eos_id: int | None = None
    seed: int = 0
    # streaming: called with (request, token:int) as each token materializes
    on_token: Callable | None = None
    # traffic class: the scenario suite's per-class SLO label and the
    # priority schedulers' ordering key (higher boards first and may
    # preempt lower — serve/scheduler.py::PriorityScheduler). 0 is the
    # best-effort floor; cls=None requests aggregate into the unlabeled
    # serving metrics only.
    cls: str | None = None
    priority: int = 0
    # deadlines, in seconds RELATIVE to submit_time: ``ttft_deadline_s``
    # bounds time-to-first-token, ``deadline_s`` bounds the whole request.
    # The ENGINE only stores them; enforcement (shed at tick boundaries,
    # budget refunded) is the serve supervisor's job — an unsupervised
    # engine is the "no-deadline baseline" the overload scenarios compare
    # against (serve/supervisor.py).
    ttft_deadline_s: float | None = None
    deadline_s: float | None = None
    # multi-tenant serving: the named LoRA adapter this request decodes
    # under (serve/adapters.py AdapterStore), None = the base model. Part
    # of the request's IDENTITY — journaled (`adp`), carried across
    # recovery/migration, and the prefix-cache namespace key, because K/V
    # computed under one adapter is wrong for every other.
    adapter: str | None = None

    # -- lifecycle (engine-owned) -----------------------------------------
    state: str = QUEUED
    slot: int | None = None
    # paged chunked prefill: next prompt position to compute while the
    # request is admitted but not yet decoding (None once seated — and
    # always None in the dense layout's whole-prompt prefill)
    prefill_pos: int | None = None
    tokens: list[int] = dataclasses.field(default_factory=list)
    key_data: np.ndarray | None = None  # live PRNG key data (uint32 [2])
    # speculative decoding: the draft model's SEPARATE key stream (set by
    # the engine when a draft is configured; fold_in(key(seed), 1), so
    # sampled proposals never consume the target stream's splits)
    draft_key_data: np.ndarray | None = None
    submit_time: float | None = None
    first_token_time: float | None = None
    done_time: float | None = None
    # "eos" | "length", or the SHED reasons "deadline" | "backpressure"
    # | "class"
    finish_reason: str | None = None
    # migration cause of the last journal `snap` written for this request
    # ("failure" | "handoff"; the record's `why` key — serve/journal.py),
    # None for never-migrated requests and pre-field journals
    snap_reason: str | None = None
    # preemption accounting: a preempted request goes back to QUEUED with
    # its emitted tokens intact; re-admission recomputes its K/V from
    # `resume_seq` WITHOUT touching the key stream, so the continued decode
    # is bit-exact vs never having been preempted (tests/test_scenarios.py)
    n_preempted: int = 0
    # scheduler bookkeeping: boarding order (set at admission), used by the
    # priority scheduler's newest-first victim pick
    _board_seq: int = -1
    # the adapter-bank row this request's admission pinned (0 = base row;
    # engine-transient — NOT identity: a re-admission or another replica
    # may seat the same adapter on a different row)
    _adapter_row: int = 0
    # the resolved prefix-cache namespace (AdapterStore.namespace_of —
    # version-qualified, set by the engine at submit/restore and refreshed
    # at the admission gate); None = derive from `adapter` by name alone
    # (pools driven without an adapter store). Engine-transient.
    _prefix_ns: bytes | None = None

    @property
    def resume_seq(self) -> np.ndarray:
        """The token sequence (re-)admission must have K/V for: the prompt,
        plus — after a preemption — every emitted token except the newest
        (whose K/V the next decode step writes; it rides in ``last_token``).
        Fresh requests: exactly the prompt."""
        if not self.tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens[:-1], np.int32)])

    @property
    def resume_max_new(self) -> int:
        """Remaining new-token budget paired with :attr:`resume_seq` so the
        pool's worst-case row bound (``len(seq) + budget - 1``) stays exactly
        ``prompt_len + max_new_tokens - 1`` across preemptions."""
        return self.max_new_tokens - max(0, len(self.tokens) - 1)

    @property
    def ttft_s(self) -> float | None:
        if self.submit_time is None or self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def tpot_s(self) -> float | None:
        """Mean time per output token AFTER the first (None for 1-token
        requests — there is no inter-token interval to average)."""
        if (self.first_token_time is None or self.done_time is None
                or len(self.tokens) < 2):
            return None
        return (self.done_time - self.first_token_time) / (len(self.tokens) - 1)

    def emit(self, token: int) -> None:
        self.tokens.append(int(token))
        if self.on_token is not None:
            self.on_token(self, int(token))

    def finished_by(self, token: int) -> str | None:
        """Finish reason if ``token`` (just emitted) terminates the request."""
        if self.eos_id is not None and int(token) == self.eos_id:
            return "eos"
        if len(self.tokens) >= self.max_new_tokens:
            return "length"
        return None


def validate_request(prompt: np.ndarray, max_new_tokens: int,
                     temperature: float, top_k: int | None,
                     top_p: float | None, vocab: int, max_len: int) -> None:
    """Submit-time validation: length/prompt bounds here, sampling args
    delegated to the one-shot decoders' ``_check_sampling_args`` — one
    source of truth, so a request the engine accepts is exactly one
    ``make_cached_decoder`` accepts."""
    prompt = np.asarray(prompt)
    if prompt.ndim != 1 or prompt.shape[0] < 1:
        raise ValueError(
            f"prompt must be a non-empty 1-D token array, got shape "
            f"{prompt.shape}")
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if prompt.shape[0] + max_new_tokens > max_len:
        raise ValueError(
            f"prompt {prompt.shape[0]} + max_new_tokens {max_new_tokens} "
            f"exceeds the pool's sequence budget {max_len}")
    if prompt.min() < 0 or prompt.max() >= vocab:
        raise ValueError(
            f"prompt tokens outside [0, vocab={vocab})")
    from simple_distributed_machine_learning_tpu.models.gpt import (
        _check_sampling_args,
    )
    _check_sampling_args(temperature, top_k, top_p, vocab)
