"""FCFS continuous-batching scheduler: queue -> slots, EOS/budget -> free.

The policy layer between the request queue and the KV-cache pool. FCFS
(first-come-first-served) admission is the serving baseline — no reordering,
no preemption — which keeps TTFT fairness trivial to reason about and makes
the scheduler invariants sharp enough to pin in tests:

- a request is admitted the first tick the POOL accepts it
  (``pool.can_admit``: a free slot for the dense layout; a free slot AND
  the block budget after prefix sharing for the paged one), never before a
  request that arrived earlier (queue order IS arrival order — the
  head-of-line request is probed, so a big request is never starved by
  smaller ones slipping past it);
- admission BINDS the sequence to its slot inside the loop
  (``pool.bind_seq``: the paged pool matches/references shared prefix
  blocks and reserves the worst-case budget), so a burst cannot admit
  past the pool's actual capacity;
- retirement (EOS sampled, or ``max_new_tokens`` reached) unbinds and
  releases in the SAME tick, so a waiting request boards on the very next
  tick — that mid-flight boarding is the whole point of continuous
  batching;
- the pool's own guards make double-occupancy, double-release and block
  double-alloc/free raise rather than corrupt (``serve/slots.py``).

Smarter policies (shortest-job-first on ``max_new_tokens``, priority
classes) would subclass and override :meth:`FCFSScheduler.pick`.
"""

from __future__ import annotations

import collections

from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    Request,
)
from simple_distributed_machine_learning_tpu.serve.slots import KVCachePool


class FCFSScheduler:
    """First-come-first-served admission over a :class:`KVCachePool`."""

    def __init__(self, pool: KVCachePool) -> None:
        self.pool = pool
        self.queue: collections.deque[Request] = collections.deque()

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def enqueue(self, request: Request) -> None:
        if request.state != QUEUED:
            raise ValueError(
                f"request {request.rid} is {request.state}, not queued")
        self.queue.append(request)

    def pick(self) -> Request:
        """The next request to admit (FCFS: the oldest). Override for other
        policies; callers guarantee the queue is non-empty."""
        return self.queue.popleft()

    def admit(self) -> list[Request]:
        """Board waiting requests into free slots (as many as fit), FCFS.
        Returns the newly admitted requests with ``slot`` assigned; the
        engine prefills each one.

        Admission is gated on the POOL's judgment (``pool.can_admit``), not
        just a free slot: the dense pool's answer is "a slot is free" (the
        row IS the whole budget), the paged pool's is "a slot is free AND
        enough blocks remain for this request's worst-case footprint after
        prefix sharing". The gate runs on the request :meth:`pick` actually
        RETURNS (not a peeked head), so a subclass policy reordering the
        queue is still budget-checked; a picked request that doesn't fit
        goes back to the front and admission stops — head-of-line blocking,
        no starvation of big requests behind a stream of small ones."""
        admitted = []
        while self.queue:
            r = self.pick()
            if not self.pool.can_admit(r):
                self.queue.appendleft(r)
                break
            r.slot = self.pool.acquire(r.rid)
            # bind INSIDE the loop: the paged pool reserves this request's
            # block budget here, so the next iteration's can_admit probe
            # already sees it (a burst cannot over-admit the pool)
            r.prefill_pos = self.pool.bind_seq(r)
            r.state = ACTIVE
            admitted.append(r)
        return admitted

    def retire(self, request: Request, reason: str) -> None:
        """Free the request's slot immediately (same tick) so the next
        :meth:`admit` can reuse it."""
        if request.state != ACTIVE or request.slot is None:
            raise ValueError(
                f"request {request.rid} is not active (state "
                f"{request.state!r}, slot {request.slot!r})")
        self.pool.unbind_seq(request.slot)
        self.pool.release(request.slot)
        request.slot = None
        request.state = DONE
        request.finish_reason = reason
