"""FCFS continuous-batching scheduler: queue -> slots, EOS/budget -> free.

The policy layer between the request queue and the KV-cache pool. FCFS
(first-come-first-served) admission is the serving baseline — no reordering,
no preemption — which keeps TTFT fairness trivial to reason about and makes
the scheduler invariants sharp enough to pin in tests:

- a request is admitted the first tick a slot is free, never before a
  request that arrived earlier (queue order IS arrival order);
- retirement (EOS sampled, or ``max_new_tokens`` reached) releases the slot
  in the SAME tick, so a waiting request boards on the very next tick —
  that mid-flight boarding is the whole point of continuous batching;
- the pool's own guards make double-occupancy and double-release raise
  rather than corrupt (``serve/slots.py``).

Smarter policies (shortest-job-first on ``max_new_tokens``, priority
classes) would subclass and override :meth:`FCFSScheduler.pick`.
"""

from __future__ import annotations

import collections

from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    Request,
)
from simple_distributed_machine_learning_tpu.serve.slots import KVCachePool


class FCFSScheduler:
    """First-come-first-served admission over a :class:`KVCachePool`."""

    def __init__(self, pool: KVCachePool) -> None:
        self.pool = pool
        self.queue: collections.deque[Request] = collections.deque()

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def enqueue(self, request: Request) -> None:
        if request.state != QUEUED:
            raise ValueError(
                f"request {request.rid} is {request.state}, not queued")
        self.queue.append(request)

    def pick(self) -> Request:
        """The next request to admit (FCFS: the oldest). Override for other
        policies; callers guarantee the queue is non-empty."""
        return self.queue.popleft()

    def admit(self) -> list[Request]:
        """Board waiting requests into free slots (as many as fit), FCFS.
        Returns the newly admitted requests with ``slot`` assigned; the
        engine prefills each one."""
        admitted = []
        while self.queue and self.pool.n_free:
            r = self.pick()
            r.slot = self.pool.acquire(r.rid)
            r.state = ACTIVE
            admitted.append(r)
        return admitted

    def retire(self, request: Request, reason: str) -> None:
        """Free the request's slot immediately (same tick) so the next
        :meth:`admit` can reuse it."""
        if request.state != ACTIVE or request.slot is None:
            raise ValueError(
                f"request {request.rid} is not active (state "
                f"{request.state!r}, slot {request.slot!r})")
        self.pool.release(request.slot)
        request.slot = None
        request.state = DONE
        request.finish_reason = reason
