"""FCFS continuous-batching scheduler: queue -> slots, EOS/budget -> free.

The policy layer between the request queue and the KV-cache pool. FCFS
(first-come-first-served) admission is the serving baseline — no reordering,
no preemption — which keeps TTFT fairness trivial to reason about and makes
the scheduler invariants sharp enough to pin in tests:

- a request is admitted the first tick the POOL accepts it
  (``pool.can_admit``: a free slot for the dense layout; a free slot AND
  the block budget after prefix sharing for the paged one), never before a
  request that arrived earlier (queue order IS arrival order — the
  head-of-line request is probed, so a big request is never starved by
  smaller ones slipping past it);
- admission BINDS the sequence to its slot inside the loop
  (``pool.bind_seq``: the paged pool matches/references shared prefix
  blocks and reserves the worst-case budget), so a burst cannot admit
  past the pool's actual capacity;
- retirement (EOS sampled, or ``max_new_tokens`` reached) unbinds and
  releases in the SAME tick, so a waiting request boards on the very next
  tick — that mid-flight boarding is the whole point of continuous
  batching;
- the pool's own guards make double-occupancy, double-release and block
  double-alloc/free raise rather than corrupt (``serve/slots.py``).

Smarter policies subclass and override :meth:`FCFSScheduler.pick`:
:class:`PriorityScheduler` (the scenario suite's policy) admits by request
``priority`` — FCFS within a class — and, when the pool cannot fit a
higher-priority request, *preempts* best-effort traffic: a lower-priority
active request is evicted (slot and blocks freed, request re-queued with
its emitted tokens intact) so the interactive request's prefill boards this
tick instead of waiting out a batch request's whole decode. The preempted
request later re-admits and recomputes its K/V from ``resume_seq`` without
touching its key stream, so its final tokens are bit-exact vs never having
been preempted (tests/test_scenarios.py).
"""

from __future__ import annotations

import collections

from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    Request,
)
from simple_distributed_machine_learning_tpu.serve.slots import KVCachePool


class FCFSScheduler:
    """First-come-first-served admission over a :class:`KVCachePool`."""

    def __init__(self, pool: KVCachePool) -> None:
        self.pool = pool
        self.queue: collections.deque[Request] = collections.deque()
        # the engine this scheduler serves (attach()): policies that evict
        # active requests (PriorityScheduler) need it; FCFS never does
        self._engine = None
        self._board_count = 0

    def attach(self, engine) -> None:
        """Called by the engine at construction; see ``_engine``."""
        self._engine = engine

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    def enqueue(self, request: Request) -> None:
        if request.state != QUEUED:
            raise ValueError(
                f"request {request.rid} is {request.state}, not queued")
        self.queue.append(request)

    def pick(self) -> Request:
        """The next request to admit (FCFS: the oldest). Override for other
        policies; callers guarantee the queue is non-empty."""
        return self.queue.popleft()

    def admit(self) -> list[Request]:
        """Board waiting requests into free slots (as many as fit), FCFS.
        Returns the newly admitted requests with ``slot`` assigned; the
        engine prefills each one.

        Admission is gated on the POOL's judgment (``pool.can_admit``), not
        just a free slot: the dense pool's answer is "a slot is free" (the
        row IS the whole budget), the paged pool's is "a slot is free AND
        enough blocks remain for this request's worst-case footprint after
        prefix sharing". The gate runs on the request :meth:`pick` actually
        RETURNS (not a peeked head), so a subclass policy reordering the
        queue is still budget-checked; a picked request that doesn't fit
        goes back to the front and admission stops — head-of-line blocking,
        no starvation of big requests behind a stream of small ones."""
        admitted = []
        while self.queue:
            r = self.pick()
            if not self.pool.can_admit(r) and not self._make_room(r):
                self.queue.appendleft(r)
                break
            # the adapter gate runs AFTER the pool accepts (a free slot is
            # what makes a free bank row structurally certain) and BEFORE
            # the slot binds: it pins/uploads the request's adapter-bank
            # row at this tick boundary (serve/adapters.py)
            gate = getattr(self._engine, "_adapter_board", None)
            if gate is not None and not gate(r):
                self.queue.appendleft(r)
                break
            r.slot = self.pool.acquire(r.rid)
            # bind INSIDE the loop: the paged pool reserves this request's
            # block budget here, so the next iteration's can_admit probe
            # already sees it (a burst cannot over-admit the pool)
            r.prefill_pos = self.pool.bind_seq(r)
            r.state = ACTIVE
            r._board_seq = self._board_count
            self._board_count += 1
            admitted.append(r)
        return admitted

    def _make_room(self, request: Request) -> bool:
        """Policy hook: may the scheduler free capacity for ``request``
        (e.g. by preempting lower-priority actives)? FCFS never reorders or
        evicts — a blocked head blocks."""
        return False

    def retire(self, request: Request, reason: str) -> None:
        """Free the request's slot immediately (same tick) so the next
        :meth:`admit` can reuse it."""
        if request.state != ACTIVE or request.slot is None:
            raise ValueError(
                f"request {request.rid} is not active (state "
                f"{request.state!r}, slot {request.slot!r})")
        self.pool.unbind_seq(request.slot)
        self.pool.release(request.slot)
        request.slot = None
        request.state = DONE
        request.finish_reason = reason


class PriorityScheduler(FCFSScheduler):
    """Priority-class admission with prefill preemption of best-effort
    traffic (the scenario suite's policy; ``resilience/scenarios.py``).

    - :meth:`pick` returns the highest-``priority`` queued request, FCFS
      within a priority (queue position is arrival order, so the scan's
      first maximum is the oldest of its class);
    - when the pool cannot admit the pick, :meth:`_make_room` preempts
      ACTIVE requests of strictly lower priority — lowest priority first,
      newest-boarded first within a priority (the least sunk work) — until
      the pick fits or no eligible victim remains. Victims are re-queued at
      the FRONT (they arrived before anything still waiting of their class)
      and later resume by recomputing K/V for their emitted tokens, key
      stream untouched — output-preserving preempt-and-recompute, so SLO
      protection is a scheduling change, not a correctness change;
    - the base class's budget gate still runs on whatever pick returns, so
      admission can never outspend the pool.
    """

    def pick(self) -> Request:
        best_i = 0
        for i, r in enumerate(self.queue):
            if r.priority > self.queue[best_i].priority:
                best_i = i
        r = self.queue[best_i]
        del self.queue[best_i]
        return r

    def _victims_below(self, priority: int) -> list[Request]:
        victims = [self._engine.requests[self.pool.occupant(s)]
                   for s in self.pool.active_slots()]
        return [v for v in victims if v.priority < priority]

    def _make_room(self, request: Request) -> bool:
        if self._engine is None:
            return False
        if self.pool.prefetch_blocked(request):
            # an in-flight host->HBM upload covers this request's prefix:
            # the ONE can_admit failure eviction can never fix — it boards
            # when the upload lands, so preempting would destroy work for
            # nothing (serve/slots.py host offload tier)
            return False
        victims = self._victims_below(request.priority)
        if not victims:
            return False
        # feasibility precheck: eviction discards the victims' computed K/V
        # irreversibly, so never start unless freeing EVERY eligible victim
        # would cover the requester's block shortfall — otherwise the loop
        # would strand the requester unadmitted after throwing away work
        # (the slot side needs no precheck: any one eviction frees a slot)
        if self.pool.admit_shortfall(request) > sum(
                self.pool.freeable_blocks(v.slot) for v in victims):
            return False
        while not self.pool.can_admit(request):
            victims = self._victims_below(request.priority)
            if not victims:         # pragma: no cover - precheck bound
                return False
            # lowest priority first; newest boarding within it
            victim = max(victims,
                         key=lambda v: (-v.priority, v._board_seq))
            self._engine.preempt(victim.rid)
        return True
