"""The fleet router: which replica should serve this request?

One :class:`FleetRouter` sits in front of the fleet's replicas
(``serve/fleet.py``) and answers exactly one question per submission —
*which in-rotation replica gets it* — from two signals the serving stack
already maintains:

- **Prefix-cache affinity** (policy ``"affinity"``, the default): the
  paged pool's prefix registry (``serve/slots.py::PagedKVPool``) is probed
  per replica via ``pool.shared_prefix_len(prompt)`` — a pure read, no
  referencing, no memo — and the request routes to the replica already
  holding the LONGEST registered prefix of its prompt. That is the
  system-prompt case at fleet scale: the first request pays the prefix's
  prefill once on one replica, and every later request with the same
  prefix lands where the blocks already live instead of recomputing them
  on a cold replica (the hot-prefix-skew scenario pins affinity strictly
  above round-robin on the prefix-hit counters). A prefix resident in a
  replica's HOST offload tier (``pool.host_prefix_len``) counts too —
  those blocks are one async prefetch upload away, which the fleet
  starts at routing time (``serve/fleet.py``). Ties — including the
  no-registered-prefix cold start — fall back to least-loaded.
- **Least-loaded fallback** (policy ``"least-loaded"``): order replicas by
  ``(queue_depth, occupancy, idx)`` — the same quantities the PR-4
  registry gauges (``serve_queue_depth`` / ``serve_slots_active``) report
  — and take the minimum. Deterministic: the index breaks exact ties, so
  a virtual-clock scenario routes identically on every run.
- **Round-robin** (policy ``"round-robin"``): cycle over the in-rotation
  replicas in index order — the affinity-blind baseline the scenario
  suite compares against.

The router never inspects health itself: the FLEET decides which replicas
are in rotation (supervisor state machine + re-entry hysteresis,
``serve/fleet.py``) and hands the candidate list in. An empty candidate
list is the caller's bug — the fleet always routes over at least one
alive replica (spawning one if the last died).

Alert demotion (ISSUE 19) follows the same division of labour: the fleet
passes the set of replica indices whose per-replica SLO burn alert is
firing (``demoted``), and the router treats them as *last-resort*
capacity — ineligible for the affinity preference (sending more hot
traffic at a replica already burning its latency budget digs the hole
deeper) and ordered after every non-demoted replica in the least-loaded
fallback. When the demotion actually changed the answer — the best
affinity candidate over ALL candidates was demoted and skipped — the
router records it on :attr:`last_suppressed` for the fleet's
``serve_route_alert_demotions_total`` counter.

Multi-tenant adapters (ISSUE 20) add a second affinity signal to the
``"affinity"`` policy: prefix probes are scoped to the request's
ADAPTER NAMESPACE (a tenant can only reuse K/V its own adapter
computed — ``serve/slots.py``), and when no replica holds a prefix, a
replica where the adapter's current version is already DEVICE-RESIDENT
(``AdapterStore.is_resident``) is preferred over the plain least-loaded
answer — routing there skips a bank-row upload. Preference, never a
refusal: with no resident replica the request routes least-loaded and
the destination uploads the adapter at its admission tick. A decision
made by adapter residency (or a prefix hit on a replica that also holds
the adapter) is recorded on :attr:`last_adapter_hit` for the fleet's
``serve_route_adapter_affinity_hits_total`` counter. The baseline
policies stay adapter-blind — the hot-adapter-churn scenario's contrast.
"""

from __future__ import annotations

import numpy as np

POLICIES = ("affinity", "least-loaded", "round-robin")


class FleetRouter:
    """Routing policy over fleet replicas; see module docstring.

    ``route(prompt, candidates)`` returns ``(replica, affinity_hit)``
    where ``affinity_hit`` is True iff the decision was made by a strictly
    positive prefix-registry match (the ``serve_route_affinity_hits_total``
    increment). Candidates are fleet replica records duck-typing
    ``.idx`` and ``.supervisor`` (engine surface: ``scheduler``/``pool``).
    """

    def __init__(self, policy: str = "affinity") -> None:
        if policy not in POLICIES:
            raise ValueError(
                f"unknown route policy {policy!r}; known: {POLICIES}")
        self.policy = policy
        self._rr = 0          # round-robin cursor (monotonic, mod applied)
        #: last route() skipped the best affinity candidate because it was
        #: demoted — the fleet reads this to count alert demotions
        self.last_suppressed = False
        #: last route() was decided by (or landed on) a replica holding
        #: the request's adapter — the fleet's adapter-affinity counter
        self.last_adapter_hit = False

    @staticmethod
    def _load_key(rep) -> tuple:
        """Least-loaded ordering: queue depth first (the backlog a new
        request would sit behind), then slot occupancy (how full the
        continuous batch runs), then the index as the deterministic
        tiebreak."""
        sup = rep.supervisor
        pool = sup.pool
        return (sup.scheduler.queue_depth,
                pool.n_active / pool.n_slots,
                rep.idx)

    @staticmethod
    def _adapter_state(rep, adapter) -> tuple:
        """``(ns, resident)`` for probing ``rep`` on behalf of a request
        under ``adapter``: the replica's OWN namespace for the adapter's
        current version (``ns is None`` = this replica cannot serve the
        tenant's cache at all — no adapter store), and whether that
        version is device-resident there."""
        if adapter is None:
            return b"", False
        store = getattr(rep.supervisor.engine, "_adapters", None)
        if store is None:
            return None, False
        return store.namespace_of(adapter), store.is_resident(adapter)

    def route(self, prompt, candidates: list,
              demoted: frozenset = frozenset(), adapter=None) -> tuple:
        """Pick the replica for ``prompt`` from ``candidates`` (the
        fleet's in-rotation list, index order, non-empty). ``demoted``
        holds replica indices whose burn alert is firing — still legal
        targets (capacity is capacity), but never *preferred*.
        ``adapter`` is the request's tenant (None = base model): it
        scopes the prefix probes and adds the residency preference
        (module docstring)."""
        if not candidates:
            raise ValueError("route over an empty candidate list — the "
                             "fleet must always offer at least one "
                             "alive replica")
        self.last_suppressed = False
        self.last_adapter_hit = False
        if self.policy == "round-robin":
            rep = candidates[self._rr % len(candidates)]
            self._rr += 1
            return rep, False
        if self.policy == "affinity":
            prompt = np.asarray(prompt, np.int32)
            best, best_len = None, 0
            skipped_len = 0   # longest prefix held by a DEMOTED replica
            resident = []     # non-demoted reps holding the adapter
            for rep in candidates:
                pool = rep.supervisor.pool
                ns, res = self._adapter_state(rep, adapter)
                # HBM-registered prefix OR host-tier-resident prefix: a
                # host hit is still an affinity hit — the blocks are one
                # async upload away (pool.prefetch), which beats
                # recomputing the prefix on a cold replica. Pools without
                # a host tier answer 0, so the signal is unchanged there.
                # Probes are NAMESPACE-scoped: only K/V this request's
                # adapter computed counts as reusable.
                n = 0 if ns is None else max(
                    pool.shared_prefix_len(prompt, ns),
                    pool.host_prefix_len(prompt, ns))
                if rep.idx in demoted:
                    skipped_len = max(skipped_len, n)
                else:
                    if res:
                        resident.append(rep)
                    if n > best_len:
                        best, best_len = rep, n
            if skipped_len > best_len:
                # the demotion changed the routing answer: the longest
                # prefix lives on a firing replica and we went elsewhere
                self.last_suppressed = True
            if best is not None:
                self.last_adapter_hit = any(rep is best for rep in resident)
                return best, True
            if resident:
                # no prefix anywhere, but the adapter is uploaded
                # somewhere healthy: route where admission skips the
                # bank-row swap, least-loaded among those replicas
                self.last_adapter_hit = True
                return min(resident, key=self._load_key), False
        # least-loaded: the standalone policy AND the affinity cold-start
        # fallback; demoted replicas sort after every healthy one
        return min(candidates,
                   key=lambda rep: (rep.idx in demoted,
                                    *self._load_key(rep))), False
