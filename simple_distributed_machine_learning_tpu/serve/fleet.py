"""The multi-replica serving fleet: N supervised engines behind one router.

PR 10 made ONE engine crash-restartable; this module is the layer the
ROADMAP's "heavy traffic from millions of users" north star actually
deploys — N :class:`~.supervisor.ServeSupervisor`-wrapped replicas behind
a :class:`~.router.FleetRouter`, surviving the loss of any replica without
losing a single in-flight token stream:

**Routing.** Submissions go to an IN-ROTATION replica picked by the
router: prefix-cache affinity first (the replica whose paged pool already
holds the prompt's registered prefix blocks), least-loaded by
queue-depth/occupancy otherwise (``serve/router.py``). Rids are
fleet-unique (the fleet owns the id space and seeds each replica's engine
counter before every submit), so journals, traces and metrics from
different replicas never collide on a request id.

**Health-aware rotation.** A replica leaves rotation the tick its
supervisor is anything but cleanly RUNNING — a restart consumed
(RECOVERING happened inside the tick), a degraded mode latched, overload
lockout — and re-enters only after ``health_recover_ticks`` consecutive
healthy ticks (hysteresis: one good tick after a crash loop must not pull
traffic back). Out-of-rotation replicas keep ticking and draining; they
just stop receiving new work. If rotation empties entirely, routing falls
back to any alive replica — the fleet never refuses work it could serve.

**Journal-backed cross-replica migration** (the headline robustness
property). A ``replica-kill@fleet.tick`` fault (``resilience/faults.py``)
— or a replica whose supervisor exhausts its restart budget — kills a
whole replica: supervisor, engine, every in-memory structure. The fleet
trusts ONLY the dead replica's on-disk journal: ``read_journal`` +
``recover_state`` rebuild the in-flight picture, each live handle is
rewound to its journaled prefix (``ServeSupervisor._apply_snapshot``),
and the survivors ADOPT the in-flight requests in rid order —
``ServeSupervisor.adopt`` journals the full snapshot into the adopting
replica's journal first (so a second loss, or a crash of the adopter,
replays it like a native submission) and re-admits through
``engine.restore``, the same preempt/resume path crash recovery uses.
Every migrated request's full token stream is bit-exact vs the
uninterrupted single-replica run — across double replica loss and a loss
landing during another replica's crash recovery (tests/test_fleet.py).

**Disaggregated prefill/decode pools** (``prefill_replicas > 0``). The
fleet splits into two independently-sized pools: the router admits new
work to PREFILL replicas only, and the tick a request finishes prefill
(seated, first token emitted) the fleet fires the SAME journal
``snap``/``adopt`` move used for failure migration as a planned
**handoff** onto a decode replica, in copy-then-tombstone order:
``ServeSupervisor.release(seal=False)`` detaches it from the source
WITHOUT journaling, ``adopt(reason="handoff")`` lands the full snapshot
in the destination's journal, and only then does
``ServeSupervisor.seal_handoff`` journal the terminal ``handoff`` event
on the source (so a later loss of the source can never
re-adopt/double-serve it). The ordering is load-bearing: at every crash
point the rid is recoverable from at least one journal — the reverse
order has a window where it lives in none, the ``protocol.lost-request``
counterexample the bounded model checker (analysis/protocol.py) exports.
Between adopt and seal the fleet probes the ``fleet.handoff`` fault
site: a replica-kill there is the kill-racing-adopt schedule, and
``_lose_replica``'s live-elsewhere guard is what keeps it exactly-once.
Every handed-off token stream stays bit-exact vs the symmetric
single-pool run (tests/test_disagg.py pins f32 and int8, greedy and
sampled).
Decode replicas are where the host offload tier pays off
(``host_cache_blocks``): the router knows the prompt BEFORE admission,
so a host-tier-resident prefix on a decode replica starts its async
host→HBM upload AT ROUTING TIME (``pool.prefetch``) — the upload
overlaps the prefill pool's work, and the handoff affinity-routes to
the replica where the blocks land.

**Autoscaling** (:class:`AutoscalePolicy`). Scale-out: when the fleet's
total queue depth (or the paged pools' resident-block fraction — the
``serve_kv_bytes_resident`` signal) sits at/above the high watermark for
``scale_out_ticks`` consecutive fleet ticks, a fresh replica spawns (up
to ``max_replicas``). Drain-then-retire: a replica idle for
``retire_idle_s`` of virtual/wall time leaves rotation and retires (its
journal stays on disk; every request it served is complete), down to
``min_replicas``. Both transitions land in :attr:`ServeFleet.replica_log`
with their fleet tick and timestamp — what the diurnal autoscale scenario
pins exactly.

The fleet duck-types the engine surface the simulator and scenario runner
drive (``submit``/``step``/``drain``/``busy``/``requests``/``metrics``/
``cfg``/``_clock``) and reads the clock NEVER — all timestamps come from
arrival times and the replicas' own engine reads, so virtual-clock
scenario numbers are exact and machine-independent.
"""

from __future__ import annotations

import dataclasses
import os
import time

from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.serve.journal import (
    RequestJournal,
    read_journal,
    recover_state,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    Request,
)
from simple_distributed_machine_learning_tpu.serve.router import FleetRouter
from simple_distributed_machine_learning_tpu.serve.supervisor import (
    RUNNING,
    ServeSupervisor,
)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Autoscaler knobs; see the module docstring.

    ``scale_out_queue_depth`` is the FLEET-TOTAL queued-request high
    watermark; ``kv_frac_high`` optionally adds the paged-pool signal
    (blocks in use / blocks total across alive replicas — the block-count
    form of ``serve_kv_bytes_resident`` over capacity; None disables).
    Either signal held for ``scale_out_ticks`` consecutive fleet ticks
    spawns one replica. ``retire_idle_s`` is how long a replica must sit
    idle (no queued or active work) before it drains out and retires."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_out_queue_depth: int = 4
    scale_out_ticks: int = 3
    retire_idle_s: float = 0.5
    kv_frac_high: float | None = None
    #: optional SLO-burn scale-out trigger (ISSUE 19): when the fleet
    #: runs with an SLO engine and ANY class burn rate (fast window)
    #: reaches this threshold, the tick counts toward the same
    #: ``scale_out_ticks`` backlog streak as queue depth — latency
    #: pressure can add capacity before the queue-depth watermark trips.
    #: None disables (the default: burn alerts only demote routing).
    scale_out_burn_rate: float | None = None

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got "
                             f"{self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas {self.max_replicas} must be >= min_replicas "
                f"{self.min_replicas}")
        if self.scale_out_queue_depth < 1 or self.scale_out_ticks < 1:
            raise ValueError(
                f"scale_out_queue_depth/scale_out_ticks must be >= 1, got "
                f"{self.scale_out_queue_depth}/{self.scale_out_ticks}")
        if self.retire_idle_s <= 0:
            raise ValueError(f"retire_idle_s must be > 0, got "
                             f"{self.retire_idle_s}")
        if self.kv_frac_high is not None and not 0 < self.kv_frac_high <= 1:
            raise ValueError(f"kv_frac_high must be in (0, 1], got "
                             f"{self.kv_frac_high}")
        if (self.scale_out_burn_rate is not None
                and self.scale_out_burn_rate <= 0):
            raise ValueError(f"scale_out_burn_rate must be > 0, got "
                             f"{self.scale_out_burn_rate}")


@dataclasses.dataclass(eq=False)
class _Replica:
    """One fleet member's bookkeeping (identity-hashed: each record IS its
    replica)."""

    idx: int
    supervisor: ServeSupervisor
    journal_path: str
    #: pool membership: "mixed" (symmetric fleet), or "prefill"/"decode"
    #: when the fleet runs disaggregated (``prefill_replicas > 0``)
    role: str = "mixed"
    alive: bool = True
    in_rotation: bool = True
    healthy_streak: int = 0
    last_restarts: int = 0
    # the timestamp the fleet FIRST OBSERVED this replica idle (None while
    # busy or never yet checked). An observation anchor, not a clock read:
    # seeding it from spawn time would break wall-clock runs, where the
    # fleet's _now jumps from 0 to an absolute monotonic value and any
    # 0-anchored idle gap would read as hours
    idle_since: float | None = None


class ServeFleet:
    """N supervised replicas behind a health-aware router; see the module
    docstring.

    ``factory(degraded) -> InferenceEngine`` is the SHARED engine factory
    (``supervisor.engine_factory``) every replica's supervisor rebuilds
    through; replicas journal into ``journal_dir`` as
    ``journal-r<idx>.jsonl`` (pre-existing fleet journals there are
    removed — each fleet run starts fresh). ``metrics``/``clock``/
    ``trace`` are shared across replicas: counters and histograms
    aggregate fleet-wide, rids are fleet-unique so traces join, and the
    per-replica gauges are last-writer-wins by design. Supervisor knobs
    (``max_restarts``/``degrade_after``/``overload``/deadline defaults)
    apply to every replica alike.

    ``prefill_replicas > 0`` disaggregates the fleet (module docstring):
    the first ``prefill_replicas`` replicas form the prefill pool, the
    rest the decode pool, and every request hands off at end-of-prefill.
    Mutually exclusive with ``autoscale``.
    """

    def __init__(self, factory, journal_dir: str, *, n_replicas: int = 2,
                 prefill_replicas: int = 0,
                 route: str = "affinity", metrics=None,
                 clock=time.monotonic, autoscale: AutoscalePolicy | None
                 = None, max_restarts: int = 3,
                 degrade_after: int | None = None, overload=None,
                 default_ttft_deadline_s: float | None = None,
                 default_deadline_s: float | None = None, trace=None,
                 health_recover_ticks: int = 2,
                 journal_sync: bool = True,
                 journal_prefix: str = "journal-r",
                 postmortem_dir: str | None = None,
                 slo=None, alert_recover_ticks: int = 2) -> None:
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if prefill_replicas and not 0 < prefill_replicas < n_replicas:
            raise ValueError(
                f"prefill_replicas {prefill_replicas} must leave at least "
                f"one decode replica: 0 < prefill_replicas < "
                f"n_replicas={n_replicas} (0 disables disaggregation)")
        if prefill_replicas and autoscale is not None:
            raise ValueError(
                "autoscale and prefill_replicas are mutually exclusive: "
                "the autoscaler sizes ONE symmetric pool, a disaggregated "
                "fleet is fixed-size per role")
        if health_recover_ticks < 1:
            raise ValueError(f"health_recover_ticks must be >= 1, got "
                             f"{health_recover_ticks}")
        if alert_recover_ticks < 1:
            raise ValueError(f"alert_recover_ticks must be >= 1, got "
                             f"{alert_recover_ticks}")
        if autoscale is not None and not (autoscale.min_replicas
                                          <= n_replicas
                                          <= autoscale.max_replicas):
            raise ValueError(
                f"n_replicas {n_replicas} outside the autoscale bounds "
                f"[{autoscale.min_replicas}, {autoscale.max_replicas}]")
        self.factory = factory
        self.journal_dir = journal_dir
        self.metrics = metrics
        self._clock = clock
        self.router = FleetRouter(route)
        self.autoscale = autoscale
        self.health_recover_ticks = int(health_recover_ticks)
        # streaming SLO engine (telemetry/slo.py): the FLEET owns the one
        # engine — replicas observe into it (replica-tagged via
        # metrics._slo_replica), the fleet evaluates it once per fleet
        # tick and converts firing per-replica burn alerts into routing
        # demotions with their own re-entry hysteresis
        self.slo = slo
        self.alert_recover_ticks = int(alert_recover_ticks)
        self._alert_demoted: set[int] = set()
        self._alert_clear_streak: dict[int, int] = {}
        if slo is not None and metrics is not None:
            metrics.bind_slo(slo)
        self.journal_sync = journal_sync
        self._sup_kw = dict(
            max_restarts=max_restarts, degrade_after=degrade_after,
            overload=overload,
            default_ttft_deadline_s=default_ttft_deadline_s,
            default_deadline_s=default_deadline_s,
            # every replica dumps crash forensics into the SHARED dir;
            # the per-replica postmortem_tag keeps the bundle names apart
            postmortem_dir=postmortem_dir)
        self.trace = trace
        self.journal_prefix = journal_prefix
        os.makedirs(journal_dir, exist_ok=True)
        import glob
        for stale in glob.glob(os.path.join(journal_dir,
                                            f"{journal_prefix}*.jsonl")):
            os.unlink(stale)               # each fleet run journals fresh
        self.replicas: list[_Replica] = []
        self._next_idx = 0
        #: the fleet-owned rid space: every replica's engine counter is
        #: seeded from this before each submit, so rids are fleet-unique
        self._next_rid = 0
        self.requests: dict[int, Request] = {}
        self._home: dict[int, int] = {}        # rid -> serving replica idx
        self._user_cb: dict[int, object] = {}  # rid -> caller's on_token
        #: monotonic fleet tick (every replica steps once per fleet tick)
        self.tick = 0
        self._now = 0.0       # newest timestamp the fleet has SEEN (never
        #                       a clock read of its own)
        self._backlog_ticks = 0
        self.replica_losses = 0
        self.migrations = 0
        #: disaggregation: first ``prefill_replicas`` spawns take the
        #: "prefill" role, the rest "decode"; 0 keeps the fleet symmetric
        self.prefill_replicas = int(prefill_replicas)
        self.disaggregated = prefill_replicas > 0
        #: planned prefill→decode migrations fired (``_handoff_step``)
        self.handoffs = 0
        #: dynamic fleet events — (tick, t, event, replica, alive count) —
        #: the trajectory the autoscale/loss scenarios pin exactly
        self.replica_log: list[dict] = []
        for i in range(n_replicas):
            role = "mixed"
            if self.disaggregated:
                role = "prefill" if i < prefill_replicas else "decode"
            self._spawn_replica(log=None, role=role)

    # -- replica lifecycle ---------------------------------------------------

    def _spawn_replica(self, log: str | None,
                       role: str = "mixed") -> _Replica:
        idx = self._next_idx
        self._next_idx += 1
        path = os.path.join(self.journal_dir,
                            f"{self.journal_prefix}{idx}.jsonl")
        sup = ServeSupervisor(
            self.factory, RequestJournal(path, sync=self.journal_sync),
            metrics=self.metrics, clock=self._clock, trace=self.trace,
            postmortem_tag=f"-r{idx}", **self._sup_kw)
        if role != "mixed":
            # stamp the pool role onto every flight-recorder row the
            # supervisor writes (serve/flight.py forensics join on it)
            sup.pool_role = role
        if self.slo is not None:
            # the replica's flight rows carry the active-alert set, but
            # EVALUATION is fleet-owned: one engine, one tick domain
            sup.slo = self.slo
            sup._drive_slo = False
        rep = _Replica(idx=idx, supervisor=sup, journal_path=path,
                       role=role)
        self.replicas.append(rep)
        if log is not None:
            self._log_event(log, rep)
            if self.metrics is not None and log == "scale-out":
                self.metrics.on_scale_out()
        return rep

    def _log_event(self, event: str, rep: _Replica) -> None:
        self.replica_log.append({
            "tick": self.tick, "t": round(self._now, 6), "event": event,
            "replica": rep.idx, "alive": self.n_alive})

    def _alive(self) -> list[_Replica]:
        return [r for r in self.replicas if r.alive]

    def _rotation(self) -> list[_Replica]:
        return [r for r in self.replicas if r.alive and r.in_rotation]

    def _role_alive(self, role: str) -> list[_Replica]:
        return [r for r in self.replicas if r.alive and r.role == role]

    def _role_rotation(self, role: str) -> list[_Replica]:
        return [r for r in self.replicas
                if r.alive and r.in_rotation and r.role == role]

    def _role_candidates(self, role: str) -> list[_Replica]:
        """Routing candidates for one pool, degrading but never refusing:
        in-rotation same-role, alive same-role, then ANY in-rotation /
        alive replica — the fleet-wide never-refuse rule applied per
        pool."""
        return (self._role_rotation(role) or self._role_alive(role)
                or self._rotation() or self._alive())

    @property
    def n_alive(self) -> int:
        return sum(1 for r in self.replicas if r.alive)

    @property
    def n_in_rotation(self) -> int:
        return len(self._rotation())

    # -- the engine surface --------------------------------------------------

    @property
    def busy(self) -> bool:
        return any(r.supervisor.busy for r in self._alive())

    @property
    def cfg(self):
        return self._alive()[0].supervisor.cfg

    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               top_k: int | None = None, top_p: float | None = None,
               eos_id: int | None = None, seed: int | None = None,
               on_token=None, arrival_time: float | None = None,
               cls: str | None = None, priority: int = 0,
               ttft_deadline_s: float | None = None,
               deadline_s: float | None = None,
               adapter: str | None = None) -> Request:
        """Route one submission to an in-rotation replica (affinity first,
        least-loaded fallback — ``serve/router.py``) and submit through
        its supervisor: journaled, admission-controlled, deadline-bound
        exactly as a single supervised engine would. ``adapter`` names
        the request's tenant (:meth:`register_adapter`): prefix probes
        scope to its namespace and the router prefers a replica where the
        adapter is already device-resident — falling back to least-loaded
        plus an upload at the destination's admission tick, never
        refusing."""
        if arrival_time is not None:
            self._now = max(self._now, arrival_time)
            self._retire_idle()   # idle troughs advance via arrivals, not
            #                       ticks — check drain-then-retire here too
        from simple_distributed_machine_learning_tpu.resilience.supervisor import (  # noqa: E501
            RestartBudgetExceeded,
        )
        if self.disaggregated:
            # new work boards the PREFILL pool; the decode pool only ever
            # receives requests via handoff (or loss migration)
            candidates = self._role_candidates("prefill")
        else:
            candidates = self._rotation() or self._alive()
        rep, hit = self.router.route(prompt, candidates,
                                     demoted=frozenset(self._alert_demoted),
                                     adapter=adapter)
        if self.metrics is not None:
            if hit:
                self.metrics.on_affinity_hit()
            if self.router.last_adapter_hit:
                self.metrics.on_adapter_affinity_hit()
            if self.router.last_suppressed:
                self.metrics.on_alert_demotion()
        # the router knows the prefix BEFORE admission: if a host-tier
        # copy of it beats what any target pool holds in HBM, start the
        # async upload NOW so it overlaps queueing + prefill instead of
        # serializing in front of the decode
        self._prefetch_host(
            prompt,
            self._role_alive("decode") if self.disaggregated else [rep],
            adapter=adapter)
        rid = self._next_rid
        rep.supervisor.engine._next_rid = rid
        self._user_cb[rid] = on_token
        if self.metrics is not None:
            # admission sheds inside submit() observe into the SLO engine
            # under this replica's index (reset in the finally below)
            self.metrics._slo_replica = rep.idx
        try:
            h = rep.supervisor.submit(
                prompt, max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, eos_id=eos_id, seed=seed,
                on_token=on_token, arrival_time=arrival_time, cls=cls,
                priority=priority, ttft_deadline_s=ttft_deadline_s,
                deadline_s=deadline_s, adapter=adapter)
        except RestartBudgetExceeded as e:
            # an admission crash (serve.admit site) with the replica's
            # restart budget already spent: a replica LOSS, not a fleet
            # crash. The submission was journaled before the engine saw it,
            # so the loss migration re-admits it on a survivor — the
            # caller's handle is the journal-recovered one
            self._lose_replica(rep, f"RestartBudgetExceeded@submit: {e}")
            self._next_rid += 1
            return self.requests[rid]
        finally:
            if self.metrics is not None:
                self.metrics._slo_replica = None
        self._next_rid += 1
        self.requests[h.rid] = h
        self._home[h.rid] = rep.idx
        return h

    def register_adapter(self, name: str, weights: dict) -> None:
        """Register (or hot-swap) a named LoRA adapter on EVERY alive
        replica — a tenant must be servable wherever routing lands it
        (and wherever a loss migration re-admits it). The factory's one
        shared host dict already propagates the weights to future spawns
        and crash rebuilds; this loop is what bumps each replica's store
        VERSION so a hot-swap invalidates resident rows and cached
        prefixes fleet-wide."""
        for rep in self._alive():
            rep.supervisor.register_adapter(name, weights)

    def step(self) -> int:
        """One fleet tick: interpret scheduled replica-kill faults, step
        every alive replica once (a supervisor that exhausts its restart
        budget is treated as a replica loss, not a fleet crash), update
        health/rotation with hysteresis, run the autoscaler, refresh the
        fleet gauges. Returns tokens emitted fleet-wide."""
        from simple_distributed_machine_learning_tpu.resilience.supervisor import (  # noqa: E501
            RestartBudgetExceeded,
        )
        self.tick += 1
        # the fleet.tick fault site: probed once per alive replica (rank =
        # replica idx), interpreted HERE — check(), not fire(), exactly
        # like the watchdog's frozen-peer
        for rep in self._alive():
            if not rep.alive:      # died earlier in THIS probe sweep
                continue
            for spec in faults.check("fleet.tick", step=self.tick,
                                     rank=rep.idx):
                if spec.kind == "replica-kill":
                    self._lose_replica(rep, f"replica-kill@tick{self.tick}")
                    break
        emitted = 0
        for rep in self._alive():
            if self.metrics is not None:
                # latency/shed observations inside this replica's tick
                # land in the SLO engine under ITS index
                self.metrics._slo_replica = rep.idx
            try:
                emitted += rep.supervisor.step()
            except RestartBudgetExceeded as e:
                # a replica that cannot hold an engine anymore is a LOST
                # replica: its in-flight work migrates, the fleet lives on
                self._lose_replica(rep, f"RestartBudgetExceeded: {e}")
                continue
            finally:
                if self.metrics is not None:
                    self.metrics._slo_replica = None
            self._update_health(rep)
        if self.slo is not None:
            # fleet-owned evaluation: one engine over every replica's
            # observations, stamped with the FLEET tick (replicas run with
            # _drive_slo cleared), then alert -> routing-demotion feedback
            self.slo.evaluate(self.tick)
            self._update_alert_demotions()
        if self.disaggregated:
            self._handoff_step()
        if self.autoscale is not None:
            self._autoscale_step()
        if self.metrics is not None:
            self.metrics.set_fleet_replicas(self.n_in_rotation)
            self.metrics.set_journal_bytes(
                sum(r.supervisor.journal.bytes for r in self._alive()))
            if self.disaggregated:
                for role in ("prefill", "decode"):
                    reps = self._role_alive(role)
                    self.metrics.set_pool_stats(
                        role, replicas=len(reps),
                        queue_depth=sum(
                            r.supervisor.scheduler.queue_depth
                            for r in reps),
                        slots_active=sum(
                            r.supervisor.pool.n_active for r in reps))
        return emitted

    # -- disaggregation: routing-time prefetch + end-of-prefill handoff ------

    def _prefetch_host(self, prompt, candidates: list,
                       adapter: str | None = None) -> None:
        """Start the async host→HBM upload of the longest host-resident
        prefix among ``candidates`` — only where the host copy strictly
        beats what that replica's pool already holds in HBM (uploading a
        prefix the registry already serves would waste the free blocks).
        Probes and uploads scope to the request's adapter namespace.
        Pools without a host tier answer 0 everywhere, so symmetric
        HBM-only fleets take this path as a no-op."""
        best, best_len, best_ns = None, 0, b""
        for r in candidates:
            pool = r.supervisor.pool
            ns, _ = FleetRouter._adapter_state(r, adapter)
            if ns is None:
                continue
            n = pool.host_prefix_len(prompt, ns)
            if n > pool.shared_prefix_len(prompt, ns) and n > best_len:
                best, best_len, best_ns = r, n, ns
        if best is not None:
            best.supervisor.pool.prefetch(prompt, best_ns)

    def _handoff_step(self) -> None:
        """The planned prefill→decode migration: every request on a
        prefill replica that FINISHED its prefill this tick (seated,
        first token emitted, still decoding) moves to the decode pool by
        the same journal ``snap``/``adopt`` discipline a replica loss
        uses, in copy-then-tombstone order — ``release(seal=False)``
        detaches without journaling, ``adopt(reason="handoff")`` lands
        the snapshot in the destination's journal, ``seal_handoff``
        journals the terminal ``handoff`` event on the source last. At
        every crash point the rid is recoverable from at least one
        journal: an adoption crash (serve.admit faults exhausting the
        destination's restart budget) happens AFTER the snap landed, so
        losing the destination recovers it; the ``fleet.handoff`` fault
        site between adopt and seal is the replica-kill-racing-adopt
        schedule the model checker explores, where ``_lose_replica``'s
        live-elsewhere guard keeps the unsealed source journal from
        re-adopting the copy the destination already serves. Routed per
        request through the SAME router (affinity first): a prefix the
        routing-time prefetch landed in the destination's HBM makes the
        handoff an affinity hit."""
        from simple_distributed_machine_learning_tpu.resilience.supervisor import (  # noqa: E501
            RestartBudgetExceeded,
        )
        for src in self._role_alive("prefill"):
            sup = src.supervisor
            ready = sorted(
                rid for rid, h in sup.requests.items()
                if h.state == ACTIVE and h.prefill_pos is None
                and h.tokens)
            src_lost = False
            for rid in ready:
                # candidates recomputed per rid: an adoption crash or a
                # fleet.handoff kill earlier in THIS sweep may have
                # shrunk the decode pool
                decode = self._role_candidates("decode")
                cand = [r for r in decode if r is not src] or decode
                h = sup.requests[rid]
                dst, hit = self.router.route(
                    h.prompt, cand,
                    demoted=frozenset(self._alert_demoted),
                    adapter=getattr(h, "adapter", None))
                if dst is src:
                    # degenerate fallback (every decode replica dead and
                    # the source is the only survivor): nothing to move to
                    continue
                if self.metrics is not None:
                    if hit:
                        self.metrics.on_affinity_hit()
                    if self.router.last_adapter_hit:
                        self.metrics.on_adapter_affinity_hit()
                if self.trace is not None:
                    self.trace.on_migrate(h, self._now, src.idx, dst.idx)
                h = sup.release(rid, dst=dst.idx, seal=False)
                try:
                    dst.supervisor.adopt(h, on_token=self._user_cb.get(rid),
                                         reason="handoff")
                except RestartBudgetExceeded as e:
                    # the destination crashed admitting the adoptee — but
                    # adopt() journals the snap before restore runs, so
                    # the rid recovers from the dead journal like any
                    # replica loss (and may re-adopt back onto src, which
                    # is why the source's tombstone was deferred)
                    self._lose_replica(
                        dst, f"RestartBudgetExceeded@handoff: {e}")
                    continue
                self._home[rid] = dst.idx
                self.handoffs += 1
                if self.metrics is not None:
                    self.metrics.on_handoff()
                # the fleet.handoff fault site: the probe sits exactly in
                # the adopt->seal window (the kill-racing-adopt schedule
                # exported counterexamples replay)
                for spec in faults.check("fleet.handoff", step=self.tick,
                                         rank=src.idx):
                    if spec.kind == "replica-kill":
                        self._lose_replica(
                            src, f"replica-kill@handoff(rid={rid})")
                        src_lost = True
                        break
                if src_lost:
                    break
                if rid not in sup.requests:
                    # adoption-crash recovery can route the rid back home;
                    # a tombstone AFTER that snap would drop it on replay
                    sup.seal_handoff(rid, dst=dst.idx)
            if src_lost:
                continue

    def drain(self, max_ticks: int | None = None) -> list[Request]:
        from simple_distributed_machine_learning_tpu.serve.engine import (
            DrainTimeout,
        )
        ticks = 0
        while self.busy:
            if max_ticks is not None and ticks >= max_ticks:
                exc = DrainTimeout(max_ticks, [
                    r for r in self.requests.values()
                    if r.state in (QUEUED, ACTIVE)])
                # the wedged-drain forensics the supervised path dumps:
                # one tagged bundle per alive replica (each sees its own
                # flight rows / requests / journal tail), BEFORE the
                # raise — no-ops without a configured postmortem_dir
                for rep in self._alive():
                    rep.supervisor._dump_postmortem("drain_timeout",
                                                    str(exc))
                raise exc
            self.step()
            ticks += 1
        return [r for r in self.requests.values() if r.state == DONE]

    def close(self) -> None:
        for rep in self._alive():
            rep.supervisor.close()

    # -- health + rotation ---------------------------------------------------

    def _update_health(self, rep: _Replica) -> None:
        """Post-step health: a replica is healthy this tick iff its
        supervisor ended cleanly RUNNING *and* consumed no restart inside
        the tick (recovery is atomic within step(), so the restart counter
        delta is how RECOVERING is observed). Unhealthy -> out of rotation
        now; re-entry needs ``health_recover_ticks`` consecutive healthy
        ticks — the hysteresis that keeps a crash-looping replica from
        flapping back into rotation on every good tick."""
        sup = rep.supervisor
        healthy = (sup.state == RUNNING
                   and sup.restarts == rep.last_restarts)
        rep.last_restarts = sup.restarts
        if not healthy:
            if rep.in_rotation:
                self._log_event("drain", rep)
            rep.in_rotation = False
            rep.healthy_streak = 0
        else:
            rep.healthy_streak += 1
            if (not rep.in_rotation
                    and rep.healthy_streak >= self.health_recover_ticks):
                rep.in_rotation = True
                self._log_event("re-enter", rep)
        self._now = max(self._now, sup.engine._now)

    def _update_alert_demotions(self) -> None:
        """Alert → router feedback (ISSUE 19): a replica whose
        per-replica burn alert (``slo_burn{replica=i}``) is firing loses
        the router's affinity preference and sorts last in the
        least-loaded fallback — still serving (demotion never empties the
        candidate list), just not *attracting* the hot traffic that dug
        the latency hole. Re-entry mirrors ``_update_health``'s
        hysteresis: ``alert_recover_ticks`` consecutive non-firing fleet
        ticks AFTER the alert resolves (which itself took the state
        machine's ``resolve_ticks``), so a flapping alert cannot bounce a
        replica in and out of preference every tick."""
        firing = self.slo.firing_replicas()
        for rep in self._alive():
            if rep.idx in firing:
                if rep.idx not in self._alert_demoted:
                    self._alert_demoted.add(rep.idx)
                    self._log_event("alert-demote", rep)
                self._alert_clear_streak[rep.idx] = 0
            elif rep.idx in self._alert_demoted:
                streak = self._alert_clear_streak.get(rep.idx, 0) + 1
                self._alert_clear_streak[rep.idx] = streak
                if streak >= self.alert_recover_ticks:
                    self._alert_demoted.discard(rep.idx)
                    self._log_event("alert-re-enter", rep)

    # -- replica loss + migration -------------------------------------------

    def _lose_replica(self, rep: _Replica, cause: str) -> None:
        """A whole replica died. Host-death discipline: nothing of its
        memory is trusted — the in-flight picture rebuilds from its
        ON-DISK journal alone (every append was flushed before the
        supervisor acted on it), live handles rewind to their journaled
        prefixes, and survivors adopt the in-flight requests in rid order
        so FCFS arrival order survives the loss."""
        rep.alive = False
        rep.in_rotation = False
        self.replica_losses += 1
        if self.metrics is not None:
            self.metrics.on_replica_loss()
        prev_now = rep.supervisor.engine._now
        self._now = max(self._now, prev_now)
        self._log_event("loss", rep)
        try:
            # release the dead handle; its buffered state was already
            # flushed per append, so this adds nothing the disk lacked
            rep.supervisor.journal.close()
        except OSError:                      # pragma: no cover - env guard
            pass
        snapshots = recover_state(read_journal(rep.journal_path)[0])
        inflight = []
        for rid in sorted(snapshots):
            if any(r.alive and rid in r.supervisor.requests
                   for r in self.replicas if r is not rep):
                # the live-elsewhere guard: the rid already lives on a
                # survivor — a handoff adopt landed but the source died
                # before sealing its tombstone (the fleet.handoff kill
                # racing adopt), so the dead journal's copy is stale.
                # Re-adopting it would double-serve; rewinding the fleet
                # handle to the stale prefix would corrupt the live stream
                continue
            h = self.requests.get(rid)
            if h is None:
                # the submission whose admission crash killed this replica:
                # journaled, but the handle never made it back to the
                # caller — the snapshot BECOMES the caller's handle
                h = snapshots[rid]
                self.requests[rid] = h
            else:
                ServeSupervisor._apply_snapshot(h, snapshots[rid])
            if h.state == QUEUED:
                inflight.append(h)
        if self.trace is not None:
            self.trace.on_crash(prev_now, [h.rid for h in inflight],
                                "ReplicaLost")
        targets = self._alive()
        if not targets:
            # the last replica died: the fleet immediately replaces it —
            # in-flight work must never strand waiting for an autoscaler
            targets = [self._spawn_replica(log="replace", role=rep.role)]
        adopted: dict[_Replica, int] = {}
        for h in inflight:
            if self.disaggregated:
                # keep the pools honest across a loss: a request that has
                # emitted tokens already finished prefill (re-adopt into
                # the DECODE pool, even off a dying prefill replica mid-
                # handoff); one without tokens still owes its prefill
                role = "decode" if h.tokens else "prefill"
                cand = ([r for r in targets
                         if r.in_rotation and r.role == role]
                        or [r for r in targets if r.role == role]
                        or [r for r in targets if r.in_rotation]
                        or targets)
            else:
                cand = [r for r in targets if r.in_rotation] or targets
            dst, hit = self.router.route(
                h.prompt, cand, demoted=frozenset(self._alert_demoted),
                adapter=getattr(h, "adapter", None))
            if self.metrics is not None:
                if hit:
                    self.metrics.on_affinity_hit()
                if self.router.last_adapter_hit:
                    self.metrics.on_adapter_affinity_hit()
            if self.trace is not None:
                self.trace.on_migrate(h, prev_now, rep.idx, dst.idx)
            dst.supervisor.adopt(h, on_token=self._user_cb.get(h.rid))
            self._home[h.rid] = dst.idx
            adopted[dst] = adopted.get(dst, 0) + 1
        self.migrations += len(inflight)
        if self.metrics is not None:
            self.metrics.on_fleet_migrated(len(inflight))
        # the per-replica restart timeline: every ADOPTING journal records
        # the loss it absorbed (observability-only, like supervisor
        # restart records — the report CLI renders these per journal)
        for dst in sorted(adopted, key=lambda r: r.idx):
            dst.supervisor.journal.log_restart(
                self.replica_losses, False,
                f"ReplicaLost(r{rep.idx})->adopted={adopted[dst]} "
                f"[{cause}]", tick=self.tick)

    # -- autoscaling ---------------------------------------------------------

    def _autoscale_step(self) -> None:
        pol = self.autoscale
        # the floor binds on the loss side too: a replica-kill (or budget
        # exhaustion) must not leave the fleet below min_replicas waiting
        # for a backlog that light traffic may never build
        while self.n_alive < pol.min_replicas:
            self._spawn_replica(log="replace")
        alive = self._alive()
        qd = sum(r.supervisor.scheduler.queue_depth for r in alive)
        kv_high = False
        if pol.kv_frac_high is not None:
            use = tot = 0
            for r in alive:
                stats = getattr(r.supervisor.pool, "stats", None)
                if stats is not None:
                    s = stats()
                    use += s["blocks_in_use"]
                    tot += s["blocks_total"]
            kv_high = tot > 0 and use / tot >= pol.kv_frac_high
        burn_high = False
        if pol.scale_out_burn_rate is not None and self.slo is not None:
            # latency pressure as a scale-out signal: any class burning
            # its error budget at >= the threshold counts like backlog
            burn_high = (max(self.slo.burn_rates().values(), default=0.0)
                         >= pol.scale_out_burn_rate)
        if qd >= pol.scale_out_queue_depth or kv_high or burn_high:
            self._backlog_ticks += 1
        else:
            self._backlog_ticks = 0
        if (self._backlog_ticks >= pol.scale_out_ticks
                and self.n_alive < pol.max_replicas):
            self._spawn_replica(log="scale-out")
            self._backlog_ticks = 0
        self._retire_idle()

    def _retire_idle(self) -> None:
        """Drain-then-retire: a replica OBSERVED idle (nothing queued or
        active — i.e. already drained) for ``retire_idle_s`` retires,
        newest first, never below ``min_replicas``. Runs every fleet tick
        AND at every timestamped submit, because an idle trough advances
        time through arrivals, not busy ticks. Idleness is anchored at the
        first idle OBSERVATION (``idle_since``), so the clock base —
        virtual from 0, or absolute wall monotonic — cancels out."""
        if self.autoscale is None:
            return
        pol = self.autoscale
        for rep in sorted(self._alive(), key=lambda r: -r.idx):
            if rep.supervisor.busy:
                rep.idle_since = None
                continue
            if rep.idle_since is None:
                rep.idle_since = self._now
                continue
            if self.n_alive <= pol.min_replicas:
                continue
            if self._now - rep.idle_since >= pol.retire_idle_s:
                rep.alive = False
                rep.in_rotation = False
                rep.supervisor.close()
                self._log_event("retire", rep)
                if self.metrics is not None:
                    self.metrics.on_retire()
