"""KV-cache pools: the serving engine's one device-resident state.

Two layouts, one slot discipline:

- :class:`KVCachePool` — the dense PR-5 layout: a *slot* is one row of every
  layer's K/V cache (``[L, n_slots, H, max_len, dh]``), the static-shape home
  of one in-flight sequence. Memory is reserved at ``max_len`` per slot
  whether the sequence uses it or not, so HBM — not compute — caps
  concurrency. Kept as the paged pool's comparison baseline
  (``bench.py --serve``) and for engines built with ``kv_layout="dense"``.

- :class:`PagedKVPool` — the block-table paged layout (vLLM-style): a global
  pool of fixed-size K/V *blocks* (``[L, n_blocks+1, H, block_size, dh]``;
  physical block 0 is the trash block inactive slots write into), a
  per-slot block table mapping logical block ``j`` (positions
  ``[j*bs, (j+1)*bs)``) to a physical block, on-demand allocation as
  positions advance, and copy-on-write prefix sharing: requests with a
  common prompt prefix reference the same physical blocks until they
  diverge, and the first write into a shared block copies it first.
  A sequence's memory footprint is ``ceil(rows/block_size)`` blocks instead
  of a ``max_len`` row, so the same bytes sustain strictly more concurrent
  requests (the ``bench.py --serve`` fixed-memory comparison).

Both pools share the invariant-guarded slot free list: acquiring an occupied
slot or releasing a free one raises instead of silently corrupting a
neighbor's cache, and the paged pool extends the discipline to blocks — no
double allocation, no double free, no write into a block another sequence
still references (the scheduler invariants pinned in tests/test_serve.py).

Stale-write safety (dense): an idle slot keeps its stale position, and the
batched decode step keeps writing garbage K/V there while the slot is
unoccupied. That is safe by construction — a row at cache index ``p`` only
ever becomes visible to attention at the tick that FIRST reaches position
``p``, and that same tick overwrites index ``p`` with the real K/V before
attending; prefill likewise overwrites ``[0, prompt_len)`` on admission.

Stale-write safety (paged): the dense argument breaks under paging — a
retired slot's stale block-table entries may point at physical blocks
REUSED by a live request, so a garbage write there would corrupt a
neighbor. The engine therefore routes every non-decoding slot's tick write
to the trash block (``PagedKVPool.TRASH``, position 0), which no block
table ever references.

Host offload tier (paged, ``host_cache_blocks > 0``): LRU eviction of a
cached prefix block demotes its rows to host RAM instead of discarding
them, growing the effective prefix cache past HBM. The router probes the
host registry too (:meth:`PagedKVPool.host_prefix_len`), and an affinity
hit on a host-resident prefix starts an **async upload**
(:meth:`PagedKVPool.prefetch`) that lands after ``prefetch_ticks`` engine
ticks (:meth:`PagedKVPool.advance_transfers`). Safety model: the uploaded
keys are registered — and therefore visible to admission's prefix probe —
only at COMPLETION, and ``can_admit`` additionally blocks a request whose
prefix an in-flight upload covers, so a request can never board against
half-uploaded blocks (it waits one or two ticks and then shares the real
ones). Uploads draw from the FREE list only, never by evicting live or
cached blocks, and never below the pool's outstanding reservations — a
prefetch can be refused (a miss), but it can never thrash the working set
or strand an admitted sequence's allocation.
"""

from __future__ import annotations

import collections
import math

import numpy as np


def kv_block_bytes(n_layers: int, n_heads: int, block_size: int,
                   head_dim: int, cache_dtype=None) -> int:
    """Bytes one physical K/V block pins across every layer (K and V).

    The ONE copy of the formula: :class:`PagedKVPool` sizes its
    ``bytes_per_block`` (and therefore the ``serve_kv_bytes_resident``
    gauge) from it, and the analyzer's HBM-bytes-per-tick model
    (``analysis/programs.py``) predicts against it — the cross-check in
    tests/test_analysis_serve.py holds because both sides share this.

    QUANTIZED dtypes (int8/fp8, ``models/gpt.py::_is_quantized_dtype``)
    add the per-block scale planes to the bill: one f32 scale per
    (position, head) row, for K and for V — the honest block footprint,
    so a fixed-byte pool sizing (``n_blocks_for_bytes``) and the
    resident-bytes gauge can never claim the scale planes are free."""
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.models.gpt import (
        _cache_dtype,
        _is_quantized_dtype,
    )
    cd = _cache_dtype(cache_dtype)
    bytes_ = (2 * n_layers * n_heads * block_size * head_dim
              * jnp.dtype(cd).itemsize)
    if _is_quantized_dtype(cache_dtype):
        bytes_ += 2 * n_layers * n_heads * block_size * 4   # f32 scales
    return int(bytes_)


def n_blocks_for_bytes(budget_bytes: int, n_layers: int, n_heads: int,
                       block_size: int, head_dim: int,
                       cache_dtype=None) -> int:
    """Physical blocks a ``budget_bytes`` K/V budget funds — the
    fixed-KV-bytes sizing rule the ``bench.py --serve`` quantized
    concurrency sweep uses (an int8 pool fits ~4x the f32 blocks of the
    same budget, scale planes already billed)."""
    per = kv_block_bytes(n_layers, n_heads, block_size, head_dim,
                         cache_dtype)
    return max(1, budget_bytes // per)


def _bind_seq_of(request) -> np.ndarray:
    """The sequence admission must budget/prefill for: ``resume_seq`` when
    the request tracks preemption state, its plain prompt otherwise (raw
    duck-typed requests in tests)."""
    seq = getattr(request, "resume_seq", None)
    return request.prompt if seq is None else seq


def _bind_budget_of(request) -> int:
    budget = getattr(request, "resume_max_new", None)
    return request.max_new_tokens if budget is None else budget


def _ns_of(request) -> bytes:
    """The request's prefix-cache NAMESPACE: K/V computed under one LoRA
    adapter is wrong for every other, so registry keys are scoped by the
    request's adapter. The engine resolves the VERSION-QUALIFIED
    namespace onto ``_prefix_ns`` (AdapterStore.namespace_of — a hot-swap
    changes it, orphaning the old version's keys); a pool driven without
    the engine's adapter plumbing falls back to the bare name
    (serve/adapters.py::adapter_namespace, imported lazily so a pool
    without adapters never touches the adapter module). Base-model
    requests get the EMPTY namespace: their keys stay byte-identical to
    the pre-adapter registry."""
    ns = getattr(request, "_prefix_ns", None)
    if ns is not None:
        return ns
    adapter = getattr(request, "adapter", None)
    if adapter is None:
        return b""
    from simple_distributed_machine_learning_tpu.serve.adapters import (
        adapter_namespace,
    )
    return adapter_namespace(adapter)


class _SlotPoolBase:
    """Slot occupancy accounting shared by both layouts: the free-slot list
    with invariant guards, and the per-slot decode state (position counters
    and last-token values — tiny host arrays fed into every compiled tick;
    the authoritative copy lives here, not on device)."""

    def __init__(self, n_slots: int, max_len: int) -> None:
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2 (a prompt token plus a "
                             f"generated one), got {max_len}")
        self.n_slots = n_slots
        self.max_len = max_len
        self.positions = np.zeros(n_slots, np.int32)
        self.last_token = np.zeros(n_slots, np.int32)
        self._occupant: list[int | None] = [None] * n_slots
        self._free: list[int] = list(range(n_slots))[::-1]   # pop() -> slot 0

    # -- occupancy accounting ---------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    def active_slots(self) -> list[int]:
        return [s for s, r in enumerate(self._occupant) if r is not None]

    def occupant(self, slot: int) -> int | None:
        return self._occupant[slot]

    def acquire(self, rid: int) -> int:
        """Claim a free slot for request ``rid``; raises when full or on a
        double-occupancy attempt (the invariant, not a best-effort)."""
        if not self._free:
            raise RuntimeError("slot acquire on a full pool — the scheduler "
                               "must check can_admit first")
        slot = self._free.pop()
        if self._occupant[slot] is not None:     # pragma: no cover - guard
            raise RuntimeError(
                f"slot {slot} already occupied by request "
                f"{self._occupant[slot]} — free-list corruption")
        self._occupant[slot] = rid
        return slot

    def release(self, slot: int) -> None:
        if self._occupant[slot] is None:
            raise RuntimeError(f"release of already-free slot {slot}")
        self._occupant[slot] = None
        self._free.append(slot)

    # -- per-slot decode state --------------------------------------------

    def seat(self, slot: int, prompt_len: int, first_token: int) -> None:
        """Post-prefill seating: the slot's next write position is
        ``prompt_len`` (the first generated token's position) and its
        pending input token is the freshly sampled one."""
        if not 0 < prompt_len < self.max_len:
            raise ValueError(f"prompt_len {prompt_len} outside (0, "
                             f"{self.max_len})")
        self.positions[slot] = prompt_len
        self.last_token[slot] = int(first_token)

    def advance(self, slot: int, next_token: int) -> None:
        self.positions[slot] += 1
        self.last_token[slot] = int(next_token)

    # -- layout hooks (scheduler-driven) -----------------------------------

    def bind_seq(self, request) -> int | None:
        """Attach an admitted request's sequence state to its slot. The
        dense layout has none (the row IS the state): returns ``None``.
        The paged override matches/reserves blocks and returns the first
        prompt position prefill must compute. MUST run inside the
        admission loop, immediately after the slot acquire — the next
        head-of-line ``can_admit`` probe has to see this request's
        reservation, or a burst admits past the pool's capacity."""
        return None

    def unbind_seq(self, slot: int) -> None:
        """Release the slot's sequence state at retirement (before the slot
        itself frees). Dense layout: nothing to do."""

    # -- routing affinity (FleetRouter's signal) -----------------------------

    def shared_prefix_len(self, prompt, ns: bytes = b"") -> int:
        """Prompt positions this pool could serve from already-registered
        prefix blocks — the fleet router's affinity signal
        (``serve/router.py``); ``ns`` scopes the probe to one adapter's
        key space. The dense layout shares nothing: 0."""
        return 0

    def host_prefix_len(self, prompt, ns: bytes = b"") -> int:
        """Prompt positions resident in this pool's HOST offload tier — the
        router's second affinity signal (an affinity hit here starts the
        async prefetch upload). Pools without a host tier: 0."""
        return 0

    def prefetch_blocked(self, request) -> bool:
        """True while an in-flight host->HBM upload covers a prefix of
        ``request``'s bind sequence — the one ``can_admit`` failure that
        preemption can NEVER fix (the PriorityScheduler must not evict
        work for it; the request boards when the upload lands). Pools
        without a host tier: never."""
        return False

    # -- preemption feasibility (PriorityScheduler's precheck) --------------

    def admit_shortfall(self, request) -> int:
        """Sequence-budget units ``request`` is short of admission (beyond
        a free slot). Dense layout: the row is the whole budget — 0."""
        return 0

    def freeable_blocks(self, slot: int) -> int:
        """Budget guaranteed back if ``slot``'s sequence ends now. Dense
        layout: nothing beyond the slot itself — 0."""
        return 0


def _check_tp(n_heads: int, tp: int) -> int:
    """Pool-side TP validation: the K/V head axis is what the serving
    shard_map splits, so ``tp`` must divide ``n_heads``. Byte accounting
    (``bytes_per_block``, ``serve_kv_bytes_resident``) is PER SHARD —
    the per-chip resident bytes, the number TP exists to shrink."""
    if tp < 1 or n_heads % tp:
        raise ValueError(
            f"tp={tp} must be >= 1 and divide the K/V head axis "
            f"(n_heads={n_heads})")
    return tp


class KVCachePool(_SlotPoolBase):
    """Dense fixed-capacity slot pool; see module docstring."""

    def __init__(self, n_layers: int, n_slots: int, n_heads: int,
                 max_len: int, head_dim: int, cache_dtype=None,
                 tp: int = 1) -> None:
        super().__init__(n_slots, max_len)
        import jax.numpy as jnp

        from simple_distributed_machine_learning_tpu.models.gpt import (
            _cache_dtype,
            _check_cache_quantization,
        )
        _check_cache_quantization(cache_dtype, "KVCachePool", paged=False)
        self.tp = _check_tp(n_heads, tp)
        shape = (n_layers, n_slots, n_heads, max_len, head_dim)
        cd = _cache_dtype(cache_dtype)
        self.cache_dtype = cd
        self.kc = jnp.zeros(shape, cd)
        self.vc = jnp.zeros(shape, cd)
        # PER-SHARD bytes, like the paged pool's bytes_per_block: one row
        # is a max_len-sized "block", and every row is pinned up front —
        # occupancy never changes what a dense pool holds resident
        self._bytes_total = kv_block_bytes(n_layers, n_heads // self.tp,
                                           max_len, head_dim, cd) * n_slots

    def bytes_resident(self) -> int:
        """The dense pool's resident K/V bytes: the full allocation,
        regardless of occupancy (the paged layout exists to shrink exactly
        this). The KV-drift gauge checks it against the analyzer's dense
        prediction — equality is a geometry/bookkeeping invariant."""
        return self._bytes_total

    def can_admit(self, request) -> bool:
        """Dense admission gate: one free slot IS the whole budget (the row
        reserves ``max_len`` positions up front)."""
        return self.n_free > 0


class PagedKVPool(_SlotPoolBase):
    """Block-table paged K/V pool with prefix sharing; see module docstring.

    Block lifecycle: a physical block is *free* (on the free list), *live*
    (``ref > 0`` request references), or *cached* (``ref == 0`` but holding
    registered prefix content — reclaimable, evicted LRU when the free list
    runs dry). ``ref`` counts live REQUEST references only; the registry's
    interest is the cached flag, so a block can outlive its last request
    exactly as long as the pool isn't under pressure.

    Copy-on-write: writers must call :meth:`ensure_writable` before landing
    K/V at a position. A block referenced by more than one request is copied
    first (the caller performs the device copy of the ``(src, dst)`` pair
    this returns) — UNLESS the writing slot is the block's original
    allocator: sharers trust only the rows below their registered fill and
    copy before their own first write, so the allocator's tail rows land in
    place even while shared (no copy, and no unbudgeted reservation draw).
    A block referenced once is written in place, dropping any registered
    prefix whose covered rows the write would clobber.

    Reservation accounting makes on-demand allocation safe: admission
    reserves this sequence's worst-case block budget (its total rows minus
    fully-shared blocks, which are never written), and every later
    allocation draws from that reservation — so a decode tick can never find
    the pool empty, and admission (``can_admit``) blocks exactly while
    ``free + reclaimable - reserved`` is short.
    """

    TRASH = 0   # physical block 0: the garbage sink for non-decoding slots

    def __init__(self, n_layers: int, n_slots: int, n_heads: int,
                 max_len: int, head_dim: int, cache_dtype=None,
                 block_size: int = 16, n_blocks: int | None = None,
                 tp: int = 1, host_cache_blocks: int = 0,
                 prefetch_ticks: int = 1) -> None:
        super().__init__(n_slots, max_len)
        self.tp = _check_tp(n_heads, tp)
        if host_cache_blocks < 0:
            raise ValueError(
                f"host_cache_blocks must be >= 0, got {host_cache_blocks}")
        if host_cache_blocks and self.tp > 1:
            raise ValueError(
                "host_cache_blocks with tp > 1 is not supported: demotion "
                "copies device rows to host per pool, and a sharded pool "
                "would demote per-shard fragments the prefetch upload "
                "cannot re-place")
        if prefetch_ticks < 1:
            raise ValueError(
                f"prefetch_ticks must be >= 1, got {prefetch_ticks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.block_size = block_size
        self.blocks_per_seq = math.ceil(max_len / block_size)
        if n_blocks is None:
            # default: the dense pool's capacity in blocks (same worst case)
            n_blocks = n_slots * self.blocks_per_seq
        if n_blocks < self.blocks_per_seq:
            raise ValueError(
                f"n_blocks={n_blocks} cannot hold even one full sequence "
                f"({self.blocks_per_seq} blocks of {block_size} for "
                f"max_len={max_len})")
        self.n_blocks = n_blocks
        import jax.numpy as jnp

        from simple_distributed_machine_learning_tpu.models.gpt import (
            QuantKV,
            _cache_dtype,
            _check_cache_quantization,
            _is_quantized_dtype,
        )
        _check_cache_quantization(cache_dtype, "PagedKVPool", paged=True)
        cd = _cache_dtype(cache_dtype)
        self.cache_dtype = cd
        self.quantized = _is_quantized_dtype(cache_dtype)
        # +1: physical block 0 is the trash block, never allocated
        shape = (n_layers, n_blocks + 1, n_heads, block_size, head_dim)
        if self.quantized:
            # narrow block data + per-(position, head) f32 scale planes as
            # ONE pytree buffer per cache (models/gpt.py::QuantKV): every
            # compiled step, the CoW copy, donation and TP placement
            # thread the pair together
            self.kc = QuantKV(jnp.zeros(shape, cd),
                              jnp.zeros(shape[:-1], jnp.float32))
            self.vc = QuantKV(jnp.zeros(shape, cd),
                              jnp.zeros(shape[:-1], jnp.float32))
        else:
            self.kc = jnp.zeros(shape, cd)
            self.vc = jnp.zeros(shape, cd)
        # PER-SHARD bytes (heads split tp ways by the TP serving programs):
        # the gauge tracks what one chip actually pins, which is the number
        # TP sharding exists to shrink — and what the analyzer's
        # predict_kv_bytes_resident must agree with per shard
        self.bytes_per_block = kv_block_bytes(n_layers, n_heads // self.tp,
                                              block_size, head_dim, cd)
        # block bookkeeping (host-side, authoritative)
        self.ref = np.zeros(n_blocks + 1, np.int64)
        self._free_blocks: list[int] = list(range(1, n_blocks + 1))[::-1]
        self._cached: dict[int, set[bytes]] = {}       # block -> prefix keys
        self._prefix: dict[bytes, tuple[int, int]] = {}  # key -> (block, fill)
        # bumped on every _prefix mutation (register/drop/evict): versions
        # the per-request probe memo in _probe_cached
        self._registry_epoch = 0
        # block -> the slot that ALLOCATED it and may still write it in
        # place while sharers hold references (see ensure_writable):
        # sharers only ever trust rows below their registered fill, and
        # they copy-on-write before their own first write, so the
        # writer's tail rows can land in place without a copy — and
        # without consuming a reservation its admission budget never
        # included (the overrun guard tests/test_paged_attention.py's
        # mid-decode sharing scenario exposed)
        self._block_writer: dict[int, int] = {}
        self._lru: collections.OrderedDict[int, None] = (
            collections.OrderedDict())                 # reclaimable, LRU order
        self._reserved = 0
        # per-slot sequence state
        self.tables: list[list[int]] = [[] for _ in range(n_slots)]
        self._resv = np.zeros(n_slots, np.int64)
        # per-slot prefix-cache namespace, set at bind: register_prefix
        # publishes this slot's blocks under the SAME adapter scope its
        # probe matched in, so cross-tenant K/V sharing is structurally
        # impossible (serve/adapters.py)
        self._slot_ns: list[bytes] = [b""] * n_slots
        # lifetime counters (ServeMetrics reads the deltas)
        self.prefix_hit_blocks_total = 0
        self.cow_copies_total = 0
        self.evictions_total = 0
        # -- host offload tier (module docstring, "Host offload tier") ----
        self.host_cache_blocks = host_cache_blocks
        self.prefetch_ticks = prefetch_ticks
        # host_id -> {"keys": {key: fill}, "kc": ..., "vc": ...} where
        # kc/vc are host (numpy) pytrees of one block's rows, LRU order
        self._host: collections.OrderedDict[int, dict] = (
            collections.OrderedDict())
        self._host_prefix: dict[bytes, tuple[int, int]] = {}
        self._next_host_id = 0
        # in-flight uploads: {"entries": [(key, fill, host_id)],
        # "blocks": [phys], "ticks_left": int}
        self._inflight: list[dict] = []
        self.host_demotes_total = 0
        self.host_promotes_total = 0
        self.host_evictions_total = 0
        self.host_prefetch_hits_total = 0
        self.host_prefetch_misses_total = 0
        self.host_transfer_bytes_total = 0

    # -- capacity ----------------------------------------------------------

    @property
    def blocks_in_use(self) -> int:
        """Blocks referenced by live requests (cached-only blocks excluded —
        they are reclaimable memory, not working-set)."""
        return int((self.ref[1:] > 0).sum())

    @property
    def blocks_cached(self) -> int:
        return len(self._lru)

    @property
    def blocks_available(self) -> int:
        """Blocks a NEW sequence could still claim: free + reclaimable
        (cached, ref 0) minus outstanding reservations."""
        return len(self._free_blocks) + len(self._lru) - self._reserved

    def bytes_resident(self) -> int:
        return self.blocks_in_use * self.bytes_per_block

    @staticmethod
    def _rows_needed(prompt_len: int, max_new_tokens: int) -> int:
        # positions written: prefill [0, prompt_len) + one decode write per
        # consumed token — the final emitted token is never consumed, so the
        # highest written position is prompt_len + max_new - 2
        return prompt_len + max_new_tokens - 1

    def blocks_for(self, rows: int) -> int:
        return math.ceil(rows / self.block_size)

    # -- admission ---------------------------------------------------------

    def can_admit(self, request) -> bool:
        """Paged admission gate: a free slot AND enough blocks for this
        request's worst-case budget after prefix sharing (shared FULL blocks
        are never written, so they cost nothing; a shared partial tail still
        budgets one block for its copy-on-write).

        Chain blocks sitting in the reclaimable LRU (cached, ref 0) are
        counted OUT of availability here: sharing them revives them
        (``_ref_block`` pulls them from the LRU), which shrinks
        ``blocks_available`` without consuming reservation — counting them
        as both "shared, free of charge" and "reclaimable headroom" would
        approve a request ``begin_seq`` cannot actually fund.

        A request whose prefix an in-flight host->HBM upload covers is
        additionally held back (:meth:`prefetch_blocked`): boarding now
        would recompute — or worse, share half-uploaded rows — instead of
        waiting the tick or two for the registered blocks to land."""
        return (bool(self._free) and self.admit_shortfall(request) == 0
                and not self.prefetch_blocked(request))

    def admit_shortfall(self, request) -> int:
        """Blocks ``request`` is short of admission (0 = the block budget
        fits; a free slot is checked separately). The PriorityScheduler's
        preemption precheck compares this against the victims' guaranteed
        :meth:`freeable_blocks` so eviction never discards work that could
        not possibly let the requester board."""
        _shared_len, chain = self._probe_cached(request)
        n_shared_full = sum(1 for _, fill in chain if fill == self.block_size)
        n_shared_reclaimable = sum(1 for b, _ in chain if self.ref[b] == 0)
        budget = self.blocks_for(
            self._rows_needed(int(np.asarray(_bind_seq_of(request)).shape[0]),
                              _bind_budget_of(request))) - n_shared_full
        return max(0, budget - (self.blocks_available - n_shared_reclaimable))

    def freeable_blocks(self, slot: int) -> int:
        """Blocks GUARANTEED back into availability-for-an-admission if
        ``slot``'s sequence ends now: its unused reservation plus its
        solely-referenced UNCACHED table blocks (ref drops to 0, straight
        to the free list). Shared blocks stay with their referents, and
        cached (registered-prefix) blocks are deliberately excluded even at
        ref 1: they land on the reclaimable LRU, where an admission probe
        that SHARES them re-discounts them as reclaimable chain blocks
        (``admit_shortfall``'s n_shared_reclaimable) — counting them here
        would let the preemption precheck approve evictions that cannot
        actually fund the requester. Conservative: may under-report (a
        missed preemption), never over-report (work destroyed for
        nothing)."""
        return int(self._resv[slot]) + sum(
            1 for b in self.tables[slot]
            if self.ref[b] == 1 and not self._cached.get(b))

    def begin_seq(self, slot: int, prompt: np.ndarray,
                  max_new_tokens: int, ns: bytes = b"") -> int:
        """Attach a sequence to an acquired slot: match the longest
        registered prompt prefix (incref'ing the shared blocks into this
        slot's table) and reserve the worst-case budget for the rest.
        Returns ``shared_len`` — the first prompt position the engine's
        chunked prefill must actually compute (always < prompt_len: at
        least the last prompt position is recomputed so the first token is
        sampled from a real forward pass)."""
        if self.tables[slot] or self._resv[slot]:
            raise RuntimeError(
                f"begin_seq on slot {slot} with a live block table or "
                f"reservation — the previous sequence was never ended")
        prompt = np.asarray(prompt)
        self._slot_ns[slot] = ns
        shared_len, chain = self._probe_prefix(prompt, ns)
        for block, _fill in chain:
            self._ref_block(block)
            self.tables[slot].append(block)
        n_shared_full = sum(1 for _, fill in chain if fill == self.block_size)
        budget = self.blocks_for(
            self._rows_needed(int(prompt.shape[0]), max_new_tokens)
        ) - n_shared_full
        if budget > self.blocks_available:
            raise RuntimeError(
                f"begin_seq short of blocks (need {budget}, have "
                f"{self.blocks_available}) — the scheduler must check "
                f"can_admit first")
        self._reserved += budget
        self._resv[slot] = budget
        self.prefix_hit_blocks_total += len(chain)
        return shared_len

    def bind_seq(self, request) -> int | None:
        # resume_seq/resume_max_new: identical to prompt/max_new_tokens for
        # fresh requests; after a preemption they cover the already-emitted
        # tokens whose K/V re-admission must recompute (serve/request.py)
        return self.begin_seq(request.slot, _bind_seq_of(request),
                              _bind_budget_of(request), ns=_ns_of(request))

    def unbind_seq(self, slot: int) -> None:
        self.end_seq(slot)

    def end_seq(self, slot: int) -> None:
        """Detach the slot's sequence: decref every table block (cached
        blocks become reclaimable, uncached ones free) and return the unused
        reservation. The slot itself is released separately (scheduler)."""
        for block in self.tables[slot]:
            # surviving sharers lose the in-place-writer privilege with
            # the allocator gone (they fall back to plain CoW-at-ref>1)
            if self._block_writer.get(block) == slot:
                del self._block_writer[block]
            self._unref_block(block)
        self.tables[slot] = []
        self._slot_ns[slot] = b""
        self._reserved -= int(self._resv[slot])
        self._resv[slot] = 0

    # -- write-path allocation + copy-on-write -----------------------------

    def ensure_writable(self, slot: int, position: int
                        ) -> tuple[int, int] | None:
        """Make ``position``'s block privately writable by ``slot``'s
        sequence, allocating on demand as positions advance. Returns a
        ``(src, dst)`` physical pair when copy-on-write fired — the CALLER
        must copy the device block rows before writing — else ``None``.

        In-place writes into a singly-referenced block drop any registered
        prefix whose covered rows extend past the write offset (the write
        would silently corrupt what the registry promises future sharers).
        """
        if not 0 <= position < self.max_len:
            raise ValueError(f"position {position} outside [0, "
                             f"{self.max_len})")
        table = self.tables[slot]
        j = position // self.block_size
        if j > len(table):          # pragma: no cover - guard
            raise RuntimeError(
                f"slot {slot} write at position {position} skips logical "
                f"block {len(table)} — positions must advance contiguously")
        if j == len(table):
            table.append(self._alloc_block(slot))
            return None
        phys = table[j]
        if self.ref[phys] > 1 and self._block_writer.get(phys) != slot:
            # a SHARED-IN block: this slot referenced it through the
            # prefix registry, so its own rows must land in a private copy
            dst = self._alloc_block(slot)
            table[j] = dst
            self._unref_block(phys)
            self.cow_copies_total += 1
            return (phys, dst)
        # singly-referenced, or shared but THIS slot allocated it (sharers
        # trust only rows below their registered fill and copy before
        # writing, so the allocator's tail writes are invisible to them):
        # in-place, but invalidate stale prefix promises
        off = position % self.block_size
        for key in list(self._cached.get(phys, ())):
            if self._prefix[key][1] > off:
                self._drop_key(key)
        return None

    def _alloc_block(self, slot: int) -> int:
        if self._resv[slot] <= 0:   # pragma: no cover - guard
            raise RuntimeError(
                f"slot {slot} allocates past its reservation — the "
                f"admission budget was computed wrong")
        if self._free_blocks:
            block = self._free_blocks.pop()
        elif self._lru:
            block, _ = self._lru.popitem(last=False)   # evict LRU cached
            if self.host_cache_blocks:
                # demote-to-host BEFORE the keys drop: the evicted prefix
                # survives in the offload tier instead of dying
                self._demote(block)
            for key in list(self._cached.get(block, ())):
                del self._prefix[key]
            self._cached.pop(block, None)
            self._registry_epoch += 1
            self.evictions_total += 1
        else:                       # pragma: no cover - guard
            raise RuntimeError(
                "block pool exhausted despite reservation accounting — "
                "free/reserve bookkeeping corrupted")
        if self.ref[block] != 0:    # pragma: no cover - guard
            raise RuntimeError(
                f"allocated block {block} has ref {self.ref[block]} — "
                f"double allocation")
        if block == self.TRASH:     # pragma: no cover - guard
            raise RuntimeError("the trash block leaked into the free list")
        self.ref[block] = 1
        self._block_writer[block] = slot
        self._resv[slot] -= 1
        self._reserved -= 1
        return block

    def _ref_block(self, block: int) -> None:
        if self.ref[block] == 0:
            # was cached-reclaimable; sharing revives it
            self._lru.pop(block, None)
        self.ref[block] += 1

    def _unref_block(self, block: int) -> None:
        if self.ref[block] <= 0:
            raise RuntimeError(f"unref of unreferenced block {block} — "
                               f"double free")
        self.ref[block] -= 1
        if self.ref[block] == 0:
            self._block_writer.pop(block, None)
            if self._cached.get(block):
                self._lru[block] = None        # reclaimable, newest last
            else:
                self._free_blocks.append(block)

    # -- prefix registry ---------------------------------------------------

    def shared_prefix_len(self, prompt, ns: bytes = b"") -> int:
        """The paged affinity signal: longest registered prefix of
        ``prompt`` (in positions) this pool already holds in namespace
        ``ns``. A pure probe — no referencing, no memo, no registry
        mutation — so the router may ask every replica without perturbing
        any pool."""
        return self._probe_prefix(np.asarray(prompt, np.int32), ns)[0]

    def _probe_cached(self, request) -> tuple[int, list[tuple[int, int]]]:
        """Probe memoized on the request, keyed by the registry epoch AND
        the bind sequence's length — a blocked head-of-line request is
        re-probed every tick by ``can_admit``, and without the memo each
        probe re-hashes up to ``block_size`` prompt prefixes per block. The
        epoch bumps on every registry mutation, and a preemption grows the
        request's bind sequence, so a stale chain can never be returned."""
        seq = np.asarray(_bind_seq_of(request))
        key = (self._registry_epoch, int(seq.shape[0]))
        memo = getattr(request, "_prefix_probe", None)
        if memo is not None and memo[0] == key:
            return memo[1], memo[2]
        shared_len, chain = self._probe_prefix(seq, _ns_of(request))
        request._prefix_probe = (key, shared_len, chain)
        return shared_len, chain

    def _probe_prefix(self, prompt: np.ndarray, ns: bytes = b""
                      ) -> tuple[int, list[tuple[int, int]]]:
        """Longest registered chain prefixing ``prompt`` within namespace
        ``ns`` (capped at ``prompt_len - 1`` so at least one position is
        always recomputed). Returns ``(shared_len, [(block, fill), ...])``
        without mutating."""
        prompt = np.asarray(prompt, np.int32)
        cap = int(prompt.shape[0]) - 1
        bs = self.block_size
        chain: list[tuple[int, int]] = []
        shared = 0
        j = 0
        while True:
            hit = None
            # the longest key covering block j that still prefixes prompt:
            # full block first, then partial fills from longest down
            for length in range(min(cap, (j + 1) * bs), j * bs, -1):
                entry = self._prefix.get(ns + prompt[:length].tobytes())
                if entry is not None:
                    hit = (entry[0], length - j * bs)
                    break
            if hit is None:
                break
            chain.append(hit)
            shared = j * bs + hit[1]
            if hit[1] < bs:         # partial tail ends the chain
                break
            j += 1
        return shared, chain

    def register_prefix(self, slot: int, prompt: np.ndarray) -> None:
        """Publish ``slot``'s freshly prefilled prompt blocks to the
        registry: one key per full block boundary plus the partial tail, so
        later requests with the same prefix share instead of recompute.
        First writer wins — an existing key keeps its block. Keys are
        published under the slot's bind-time namespace, so an identical
        prompt under a DIFFERENT adapter probes past them — cross-tenant
        K/V sharing is the one bug this scoping makes impossible."""
        prompt = np.asarray(prompt, np.int32)
        ns = self._slot_ns[slot]
        bs = self.block_size
        table = self.tables[slot]
        plen = int(prompt.shape[0])
        for j in range(self.blocks_for(plen)):
            fill = min(plen - j * bs, bs)
            key = ns + prompt[:j * bs + fill].tobytes()
            if key in self._prefix:
                continue
            block = table[j]
            self._prefix[key] = (block, fill)
            self._cached.setdefault(block, set()).add(key)
            self._registry_epoch += 1

    def _drop_key(self, key: bytes) -> None:
        block, _ = self._prefix.pop(key)
        self._registry_epoch += 1
        keys = self._cached.get(block)
        if keys:
            keys.discard(key)
            if not keys:
                del self._cached[block]
                if self.ref[block] == 0 and block in self._lru:
                    # was reclaimable via the registry alone — hand the
                    # block back outright
                    del self._lru[block]
                    self._free_blocks.append(block)

    # -- host offload tier -------------------------------------------------

    def _block_to_host(self, cache, block: int):
        """One physical block's rows as a host (numpy) pytree — a QuantKV
        cache's narrow data and f32 scale planes travel together."""
        import jax
        return jax.tree.map(lambda a: np.asarray(a[:, block]), cache)

    def _demote(self, block: int) -> None:
        """Copy an evicted cached block's rows (and its registered prefix
        keys) into the host tier before the device registry forgets them.
        A key already host-resident is re-pointed at the fresh copy (the
        content is identical — the key IS the token prefix, which fully
        determines the block's K/V); capacity overflow drops the LRU host
        entry (``host_evictions_total`` — the tier's true end of life)."""
        keys = {k: self._prefix[k][1] for k in self._cached.get(block, ())}
        if not keys:                # pragma: no cover - LRU blocks are cached
            return
        hid = self._next_host_id
        self._next_host_id += 1
        for key in keys:
            old = self._host_prefix.get(key)
            if old is not None:
                self._drop_host_key(key, old[0])
        self._host[hid] = {"keys": keys,
                           "kc": self._block_to_host(self.kc, block),
                           "vc": self._block_to_host(self.vc, block)}
        for key, fill in keys.items():
            self._host_prefix[key] = (hid, fill)
        self.host_demotes_total += 1
        self.host_transfer_bytes_total += self.bytes_per_block
        while len(self._host) > self.host_cache_blocks:
            ev_id, ev = self._host.popitem(last=False)
            for key in ev["keys"]:
                if self._host_prefix.get(key, (None, 0))[0] == ev_id:
                    del self._host_prefix[key]
            self.host_evictions_total += 1

    def _drop_host_key(self, key: bytes, hid: int) -> None:
        entry = self._host.get(hid)
        if entry is None:           # pragma: no cover - guard
            return
        entry["keys"].pop(key, None)
        if not entry["keys"]:
            del self._host[hid]

    def host_prefix_len(self, prompt, ns: bytes = b"") -> int:
        """The host-tier affinity signal: longest host-resident prefix of
        ``prompt`` (in positions) under the ``ns`` adapter namespace. A
        pure probe, like :meth:`shared_prefix_len` — the router may ask
        freely."""
        return self._probe_host(np.asarray(prompt, np.int32), ns)[0]

    def _probe_host(self, prompt: np.ndarray, ns: bytes = b""
                    ) -> tuple[int, list[tuple[bytes, int, int]]]:
        """:meth:`_probe_prefix`'s walk against the HOST registry. Host
        keys are the demoted device-registry keys, so they already carry
        the adapter namespace — probing just prepends the same ``ns``.
        Returns ``(shared_len, [(key, fill, host_id), ...])`` without
        mutating."""
        prompt = np.asarray(prompt, np.int32)
        cap = int(prompt.shape[0]) - 1
        bs = self.block_size
        chain: list[tuple[bytes, int, int]] = []
        shared = 0
        j = 0
        while True:
            hit = None
            for length in range(min(cap, (j + 1) * bs), j * bs, -1):
                key = ns + prompt[:length].tobytes()
                entry = self._host_prefix.get(key)
                if entry is not None:
                    hit = (key, length - j * bs, entry[0])
                    break
            if hit is None:
                break
            chain.append(hit)
            shared = j * bs + hit[1]
            if hit[1] < bs:         # partial tail ends the chain
                break
            j += 1
        return shared, chain

    def prefetch(self, prompt, ns: bytes = b"") -> bool:
        """Routing-time async upload: start moving ``prompt``'s
        host-resident prefix blocks back into HBM so they are registered
        (and shareable) before the request's slot boards. Returns True on
        a prefetch HIT — a new upload started, or the same keys are
        already in flight; False (a MISS) when the host tier adds nothing
        past the device registry or availability cannot fund the upload
        without touching reservations. Free blocks fund first; reclaimable
        LRU blocks fund the rest by the allocator's own evict path — WITH
        demotion, so the displaced prefix moves to host instead of dying
        (the offload-thrash cycle under hot-prefix churn).

        The uploaded keys stay INVISIBLE until :meth:`advance_transfers`
        completes them; until then :meth:`can_admit` blocks any request
        the in-flight keys prefix (``prefetch_blocked``) — boarding
        against half-uploaded rows is the one way this tier could corrupt
        a stream, so it is structurally impossible."""
        if not self.host_cache_blocks:
            return False
        prompt = np.asarray(prompt, np.int32)
        host_len, chain = self._probe_host(prompt, ns)
        dev_len = self._probe_prefix(prompt, ns)[0]
        chain = [(k, f, hid) for (k, f, hid) in chain
                 if k not in self._prefix]
        if host_len <= dev_len or not chain:
            self.host_prefetch_misses_total += 1
            return False
        inflight_keys = {k for t in self._inflight
                         for (k, _f, _hk, _hv) in t["entries"]}
        fresh = [(k, f, hid) for (k, f, hid) in chain
                 if k not in inflight_keys]
        if not fresh:
            return True             # already on its way; counted at start
        n = len(fresh)
        if n > self.blocks_available:
            self.host_prefetch_misses_total += 1
            return False
        # capture the host arrays BEFORE claiming device blocks: claiming
        # may evict-and-demote LRU victims, and the demotion's host-LRU
        # overflow could drop the very entries this upload reads from
        entries = []
        for key, fill, hid in fresh:
            e = self._host[hid]
            self._host.move_to_end(hid)        # a prefetch touch is a use
            entries.append((key, fill, e["kc"], e["vc"]))
        blocks = []
        for _ in range(n):
            if self._free_blocks:
                blocks.append(self._free_blocks.pop())
                continue
            # _alloc_block's eviction path, verbatim: oldest cached block
            # demotes to host, its device keys drop, the block funds the
            # upload (blocks_available already proved reservations survive)
            block, _ = self._lru.popitem(last=False)
            self._demote(block)
            for k in list(self._cached.get(block, ())):
                del self._prefix[k]
            self._cached.pop(block, None)
            self._registry_epoch += 1
            self.evictions_total += 1
            blocks.append(block)
        self._inflight.append({"entries": entries, "blocks": blocks,
                               "ticks_left": self.prefetch_ticks})
        self.host_prefetch_hits_total += 1
        return True

    def prefetch_blocked(self, request) -> bool:
        if not self._inflight:
            return False
        seq_b = _ns_of(request) + np.asarray(
            _bind_seq_of(request), np.int32).tobytes()
        for t in self._inflight:
            for key, _f, _hk, _hv in t["entries"]:
                if len(key) < len(seq_b) and seq_b.startswith(key):
                    return True
        return False

    def advance_transfers(self) -> None:
        """One engine tick of upload progress: decrement every in-flight
        countdown and COMPLETE the ones that reach zero — device rows land,
        the keys register (epoch bump), the blocks join the reclaimable LRU
        as cached ref-0 blocks exactly as if a local request had registered
        them. The paged engine calls this at the top of every step, BEFORE
        admission, so a request blocked on its upload boards the same tick
        the blocks become real. A key registered on-device while the upload
        flew wins (first writer, the registry's one rule) and the upload's
        block goes straight back to the free list."""
        if not self._inflight:
            return
        import jax
        done = [t for t in self._inflight if t["ticks_left"] <= 1]
        for t in self._inflight:
            t["ticks_left"] -= 1
        self._inflight = [t for t in self._inflight if t["ticks_left"] > 0]
        for t in done:
            blocks = list(t["blocks"])
            for key, fill, hk, hv in t["entries"]:
                block = blocks.pop(0)
                if key in self._prefix:
                    self._free_blocks.append(block)
                    continue
                self.kc = jax.tree.map(
                    lambda d, h: d.at[:, block].set(h), self.kc, hk)
                self.vc = jax.tree.map(
                    lambda d, h: d.at[:, block].set(h), self.vc, hv)
                self._prefix[key] = (block, fill)
                self._cached.setdefault(block, set()).add(key)
                self._lru[block] = None        # cached ref-0, reclaimable
                self._registry_epoch += 1
                self.host_promotes_total += 1
                self.host_transfer_bytes_total += self.bytes_per_block

    def host_bytes_resident(self) -> int:
        """Host-tier mirror of :meth:`bytes_resident`: bytes the offload
        tier pins in host RAM, ``host blocks x bytes_per_block`` — the
        same :func:`kv_block_bytes` formula, so the analyzer's host-tier
        prediction reconciles exactly (``analysis/programs.py``)."""
        return len(self._host) * self.bytes_per_block

    # -- tick inputs -------------------------------------------------------

    def device_table(self, slot: int) -> np.ndarray:
        """This slot's block table padded to the static program width with
        trash entries (masked out by position in the compiled step)."""
        t = np.full(self.blocks_per_seq, self.TRASH, np.int32)
        table = self.tables[slot]
        t[:len(table)] = table
        return t

    def stats(self) -> dict:
        s = {
            "blocks_total": self.n_blocks,
            "blocks_in_use": self.blocks_in_use,
            "blocks_cached": self.blocks_cached,
            "blocks_free": len(self._free_blocks),
            "kv_bytes_resident": self.bytes_resident(),
            "prefix_hit_blocks_total": self.prefix_hit_blocks_total,
            "cow_copies_total": self.cow_copies_total,
            "evictions_total": self.evictions_total,
        }
        if self.host_cache_blocks:
            s.update({
                "host_blocks": len(self._host),
                "host_bytes_resident": self.host_bytes_resident(),
                "host_inflight_blocks": sum(
                    len(t["blocks"]) for t in self._inflight),
                "host_demotes_total": self.host_demotes_total,
                "host_promotes_total": self.host_promotes_total,
                "host_evictions_total": self.host_evictions_total,
                "host_prefetch_hits_total": self.host_prefetch_hits_total,
                "host_prefetch_misses_total":
                    self.host_prefetch_misses_total,
                "host_transfer_bytes_total": self.host_transfer_bytes_total,
            })
        return s
