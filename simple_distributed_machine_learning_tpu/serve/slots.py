"""Slot-based KV-cache pool: the serving engine's one device-resident state.

A *slot* is one row of every layer's K/V cache — the static-shape home of one
in-flight sequence. The pool owns:

- device buffers ``kc``/``vc`` of shape ``[L, n_slots, H, max_len, dh]``
  (bf16-capable via the same ``cache_dtype`` rule as every one-shot decoder:
  ``models/gpt.py::_cache_dtype``);
- host-side per-slot position counters (the next cache index each slot
  writes) and last-token values — tiny arrays fed into every compiled tick;
- the free-slot list with invariant guards: acquiring an occupied slot or
  releasing a free one raises instead of silently corrupting a neighbor's
  cache (the scheduler invariants pinned in tests/test_serve.py).

Shapes never change at runtime: admission writes INTO a slot row at its own
offsets, retirement just returns the row to the free list — one compiled
decode program serves every occupancy.

Stale-write safety: an idle slot keeps its stale position, and the batched
decode step keeps writing garbage K/V there while the slot is unoccupied.
That is safe by construction — a row at cache index ``p`` only ever becomes
visible to attention at the tick that FIRST reaches position ``p``, and that
same tick overwrites index ``p`` with the real K/V before attending; prefill
likewise overwrites ``[0, prompt_len)`` on admission and resets the counter.
"""

from __future__ import annotations

import numpy as np


class KVCachePool:
    """Fixed-capacity slot pool; see module docstring."""

    def __init__(self, n_layers: int, n_slots: int, n_heads: int,
                 max_len: int, head_dim: int, cache_dtype=None) -> None:
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if max_len < 2:
            raise ValueError(f"max_len must be >= 2 (a prompt token plus a "
                             f"generated one), got {max_len}")
        import jax.numpy as jnp

        from simple_distributed_machine_learning_tpu.models.gpt import (
            _cache_dtype,
        )
        self.n_slots = n_slots
        self.max_len = max_len
        shape = (n_layers, n_slots, n_heads, max_len, head_dim)
        cd = _cache_dtype(cache_dtype)
        self.kc = jnp.zeros(shape, cd)
        self.vc = jnp.zeros(shape, cd)
        # host mirrors of per-slot decode state (assembled into each tick's
        # device inputs; the authoritative copy lives here, not on device)
        self.positions = np.zeros(n_slots, np.int32)
        self.last_token = np.zeros(n_slots, np.int32)
        self._occupant: list[int | None] = [None] * n_slots
        self._free: list[int] = list(range(n_slots))[::-1]   # pop() -> slot 0 first

    # -- occupancy accounting ---------------------------------------------

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    def active_slots(self) -> list[int]:
        return [s for s, r in enumerate(self._occupant) if r is not None]

    def occupant(self, slot: int) -> int | None:
        return self._occupant[slot]

    def acquire(self, rid: int) -> int:
        """Claim a free slot for request ``rid``; raises when full or on a
        double-occupancy attempt (the invariant, not a best-effort)."""
        if not self._free:
            raise RuntimeError("KVCachePool.acquire on a full pool — the "
                               "scheduler must check n_free first")
        slot = self._free.pop()
        if self._occupant[slot] is not None:     # pragma: no cover - guard
            raise RuntimeError(
                f"slot {slot} already occupied by request "
                f"{self._occupant[slot]} — free-list corruption")
        self._occupant[slot] = rid
        return slot

    def release(self, slot: int) -> None:
        if self._occupant[slot] is None:
            raise RuntimeError(f"release of already-free slot {slot}")
        self._occupant[slot] = None
        self._free.append(slot)

    # -- per-slot decode state --------------------------------------------

    def seat(self, slot: int, prompt_len: int, first_token: int) -> None:
        """Post-prefill seating: the slot's next write position is
        ``prompt_len`` (the first generated token's position) and its
        pending input token is the freshly sampled one."""
        if not 0 < prompt_len < self.max_len:
            raise ValueError(f"prompt_len {prompt_len} outside (0, "
                             f"{self.max_len})")
        self.positions[slot] = prompt_len
        self.last_token[slot] = int(first_token)

    def advance(self, slot: int, next_token: int) -> None:
        self.positions[slot] += 1
        self.last_token[slot] = int(next_token)
