"""The append-only request journal: serving's crash-durable source of truth.

The training side survives a crash because every epoch lands in a validated
:class:`~..resilience.store.CheckpointStore`; the serving side's equivalent
durable state is much smaller — *which requests exist and which tokens they
have been handed* — and much hotter, so it gets a write-ahead journal
instead of checkpoints: one fsync'd JSONL line per submission, per emitted
token (carrying the request's live PRNG key state, so a recovered decode
continues on the exact key stream), per completion and per shed.  The serve
supervisor (``serve/supervisor.py``) writes it on the way in and rebuilds
the whole in-flight picture from it on the way out of a crash — nothing of
a dead engine's memory is trusted.

Record grammar (one JSON object per line; field names kept short because a
line is written per token)::

    {"ev":"submit","rid":3,"prompt":[...],"max_new":8,"temp":0.0,
     "top_k":null,"top_p":null,"eos":null,"seed":3,"cls":"interactive",
     "prio":2,"ttft_dl":0.08,"dl":0.4,"t":12.5}
    {"ev":"tok","rid":3,"tok":17,"kd":[123,456],"dkd":null,"t":13.1}
    {"ev":"done","rid":3,"reason":"length","t":14.0}
    {"ev":"shed","rid":5,"reason":"deadline","t":14.2}
    {"ev":"restart","n":1,"degraded":false,"cause":"EngineCrash"}
    {"ev":"snap","rid":3,"prompt":[...],"max_new":8,...,"state":"queued",
     "reason":null,"toks":[17,4],"kd":[123,456],"dkd":null,"ftt":13.1,
     "dt":null,"why":"handoff"}
    {"ev":"handoff","rid":3,"dst":2,"tick":7}

A ``snap`` record is one request's ENTIRE recovered state in a single
line — everything the per-event records would fold to. Two writers emit
them: :meth:`RequestJournal.rotate` (compaction: the whole journal is
rewritten as one snap per request, so a long-lived replica's cold restart
stops re-reading the full token history) and cross-replica migration
(``ServeSupervisor.adopt``: the adopting replica journals the migrated
request's snapshot first, so ITS journal alone recovers the adoptee
through any later crash). Ordinary ``tok``/``done``/``shed`` records keep
folding on top of a ``snap``, so a rotated journal appends exactly like
an unrotated one.

``why`` is the snap's MIGRATION CAUSE — ``"failure"`` (a replica loss
moved the request) vs ``"handoff"`` (the disaggregated fleet's planned
end-of-prefill move; ``serve/fleet.py``) — so recovery tooling and the
report CLI can tell unplanned migrations from routine handoffs. The JSON
key is ``why`` (not ``reason``: that key already carries
``finish_reason`` in snap records, a grammar fact older journals bake
in); the Python API surface calls it ``reason``
(:meth:`RequestJournal.log_snapshot`, ``ServeSupervisor.adopt``).
Like ``tick``, it is absent when the writer supplies none — journals
written before the field existed recover unchanged (regression-pinned).

``adp`` is the request's ADAPTER NAME (multi-tenant LoRA serving;
``serve/adapters.py``) on ``submit`` and ``snap`` records — part of the
request's identity, because recovery must re-admit the request onto the
same adapter or its continued stream would come from the wrong model.
Absent for base-model requests AND in pre-adapter journals, which is the
whole compatibility story: :func:`_request_from` reads it with
``ev.get("adp")``, so old journals recover every request as base-model
byte-identically (regression-pinned in tests/test_adapters.py).

A ``handoff`` record marks a rid as MOVED OUT of this journal: the
source replica writes it when the fleet hands the request to a decode
replica (whose own journal now carries the authoritative ``snap``), and
:func:`recover_state` DROPS the rid — so a later loss of the source
replica can never re-adopt, and double-serve, a request that left.

Corruption tolerance mirrors ``CheckpointStore.latest_valid``: a crash can
tear at most the tail, so :func:`read_journal` keeps the longest prefix of
fully valid lines (a line is valid iff it is newline-terminated and parses
to a JSON object with an ``ev`` field) and reopening for append TRUNCATES
the file to that prefix — a torn half-line can never corrupt later
appends.  :func:`recover_state` folds the valid events into per-request
:class:`~.request.Request` snapshots, including the journaled-but-not-acked
corner: a request whose last journaled token already finished it (EOS or
budget) is marked DONE at recovery instead of being re-admitted, so its
stream is identical whether or not the ``done`` record made it to disk.
"""

from __future__ import annotations

import json
import os

import numpy as np

from simple_distributed_machine_learning_tpu.serve.request import (
    DONE,
    QUEUED,
    SHED,
    Request,
)


def read_journal(path: str) -> tuple[list[dict], int]:
    """``(events, valid_bytes)`` of the longest valid prefix of ``path``
    (``([], 0)`` when the file does not exist).  Scanning stops at the
    FIRST invalid line — everything after a torn write is suspect, exactly
    like the checkpoint store falling back past a corrupt generation."""
    if not os.path.exists(path):
        return [], 0
    events: list[dict] = []
    valid = 0
    with open(path, "rb") as f:
        raw = f.read()
    for line in raw.split(b"\n"):
        # the final segment of a newline-terminated file is b"": stop
        # cleanly; a non-empty segment without its newline is a torn tail
        if not line:
            break
        if valid + len(line) + 1 > len(raw):
            break                      # no trailing newline: torn mid-write
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            break
        if not isinstance(ev, dict) or "ev" not in ev:
            break
        events.append(ev)
        valid += len(line) + 1
    return events, valid


def _request_from(ev: dict) -> Request:
    """The journaled request identity (submit and snap records share it):
    one builder, so a field added to the journal grammar cannot silently
    diverge between the fresh-submission fold and the rotation/migration
    fold recovery is pinned byte-identical across."""
    r = Request(
        rid=int(ev["rid"]),
        prompt=np.asarray(ev["prompt"], np.int32),
        max_new_tokens=int(ev["max_new"]),
        temperature=float(ev["temp"]),
        top_k=ev["top_k"],
        top_p=ev["top_p"],
        eos_id=ev["eos"],
        seed=int(ev["seed"]),
        cls=ev["cls"],
        priority=int(ev["prio"]),
        ttft_deadline_s=ev["ttft_dl"],
        deadline_s=ev["dl"],
        # adapter identity: key absent = base model, which is also how
        # every pre-adapter journal reads (module docstring)
        adapter=ev.get("adp"))
    r.submit_time = ev["t"]
    return r


def recover_state(events: list[dict]) -> dict[int, Request]:
    """Fold journal events into per-request snapshots, keyed by rid.

    Each snapshot is a :class:`Request` carrying the journaled prompt,
    sampling params, deadlines, emitted tokens and the LIVE key state
    (``key_data``/``draft_key_data`` from the last token record — what
    makes the continued decode bit-exact).  ``state`` is ``DONE``/``SHED``
    for acknowledged requests, ``QUEUED`` for in-flight ones — including a
    request that crashed mid-prefill (no tokens yet: its stream restarts
    from the prompt on the seed's own key).  A request whose last journaled
    token already finished it is promoted to ``DONE`` here (the ``done``
    record died with the crash; the stream is complete and identical)."""
    reqs: dict[int, Request] = {}
    for ev in events:
        kind = ev["ev"]
        if kind == "submit":
            r = _request_from(ev)
            reqs[r.rid] = r
        elif kind == "tok":
            r = reqs[int(ev["rid"])]
            r.tokens.append(int(ev["tok"]))
            r.key_data = np.asarray(ev["kd"], np.uint32)
            if ev.get("dkd") is not None:
                r.draft_key_data = np.asarray(ev["dkd"], np.uint32)
            if r.first_token_time is None and ev.get("t") is not None:
                r.first_token_time = ev["t"]
        elif kind == "done":
            r = reqs[int(ev["rid"])]
            r.state = DONE
            r.finish_reason = ev["reason"]
            r.done_time = ev.get("t")
        elif kind == "shed":
            r = reqs[int(ev["rid"])]
            r.state = SHED
            r.finish_reason = ev["reason"]
            r.done_time = ev.get("t")
        elif kind == "snap":
            # one request's whole folded state (rotation / migration):
            # REPLACES any earlier state for the rid — the writer already
            # folded everything the replaced records said
            r = _request_from(ev)
            r.state = ev["state"]
            r.finish_reason = ev["reason"]
            r.tokens[:] = [int(t) for t in ev["toks"]]
            if ev["kd"] is not None:
                r.key_data = np.asarray(ev["kd"], np.uint32)
            if ev.get("dkd") is not None:
                r.draft_key_data = np.asarray(ev["dkd"], np.uint32)
            r.first_token_time = ev["ftt"]
            r.done_time = ev["dt"]
            # migration cause: absent in pre-disaggregation journals (the
            # pinned tolerance), and distinct from the "reason" key above
            # (finish_reason — see module docstring)
            r.snap_reason = ev.get("why")
            reqs[r.rid] = r
        elif kind == "handoff":
            # the request moved to another replica's journal: drop it here
            # so a source-replica loss can never re-adopt (double-serve) it
            reqs.pop(int(ev["rid"]), None)
        # "restart" records are observability only
    for r in reqs.values():
        if r.state == QUEUED and r.tokens:
            reason = r.finished_by(r.tokens[-1])
            if reason is not None:
                # the not-acked corner: finished at the crash boundary
                r.state = DONE
                r.finish_reason = reason
    return reqs


class RequestJournal:
    """One serving run's journal file, opened for durable appends.

    Opening an existing path first truncates it to its longest valid
    prefix (:func:`read_journal`) — the previous process's torn tail is
    discarded BEFORE anything new lands after it.  ``sync=True`` (default)
    fsyncs every append: a record the supervisor acted on is on disk, the
    property the recovery guarantees rest on.  ``sync=False`` keeps the
    write-ordering guarantees (flush per append) without the disk round
    trip — for tests and virtual-clock scenario runs where the OS page
    cache is durability enough.
    """

    def __init__(self, path: str, sync: bool = True) -> None:
        self.path = path
        self.sync = sync
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        events, valid = read_journal(path)
        self._recovered_events = events
        if os.path.exists(path) and os.path.getsize(path) != valid:
            with open(path, "r+b") as f:
                f.truncate(valid)
        self._f = open(path, "ab")
        self.bytes = valid

    # -- write side --------------------------------------------------------

    def append(self, ev: dict) -> None:
        line = (json.dumps(ev, separators=(",", ":")) + "\n").encode()
        self._f.write(line)
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())
        self.bytes += len(line)

    @staticmethod
    def _tick_field(tick) -> dict:
        """The monotonic ``tick`` rider (the supervisor's restart-surviving
        counter): present when the writer supplies one, absent otherwise —
        which is also the backward-compat story: :func:`recover_state`
        never reads it, so journals written before the field existed (and
        writers that never pass it) stay cold-restartable unchanged. Its
        purpose is the FORENSIC join — post-mortem bundle flight-recorder
        rows carry the same tick, so journal lines and engine snapshots
        line up exactly."""
        return {} if tick is None else {"tick": int(tick)}

    def log_submit(self, *, rid: int, prompt, max_new: int, temp: float,
                   top_k, top_p, eos, seed: int, cls, prio: int,
                   ttft_dl, dl, t, tick=None, adapter=None) -> None:
        self.append({"ev": "submit", "rid": rid,
                     "prompt": [int(x) for x in np.asarray(prompt)],
                     "max_new": int(max_new), "temp": float(temp),
                     "top_k": top_k, "top_p": top_p, "eos": eos,
                     "seed": int(seed), "cls": cls, "prio": int(prio),
                     "ttft_dl": ttft_dl, "dl": dl, "t": t,
                     **({} if adapter is None else {"adp": adapter}),
                     **self._tick_field(tick)})

    def log_token(self, request: Request, token: int, tick=None) -> None:
        """One emitted token WITH the request's post-emit key state (the
        engine updates ``key_data`` before ``emit`` fires the callback, so
        at call time the fields are exactly what the continuation needs).
        ``t`` rides only on the first token — it restores
        ``first_token_time`` (the TTFT endpoint) across a recovery.

        Speculative-tick caveat: a tick that accepts several tokens emits
        them all under the tick's single post-verify key state, so those
        records share one ``kd`` — a SAMPLED speculative stream is
        therefore recoverable at tick granularity only.  Every in-process
        recovery path (the injected faults fire at tick boundaries) and
        every greedy stream (greedy consumes no key splits at all) stays
        exactly bit-exact; the one exposure is a hard process kill landing
        BETWEEN two fsyncs of the same sampled speculative tick, where a
        cold restart resumes that request deterministically but off the
        tick-atomic key sequence."""
        dkd = request.draft_key_data
        self.append({
            "ev": "tok", "rid": request.rid, "tok": int(token),
            "kd": [int(x) for x in np.asarray(request.key_data)],
            "dkd": None if dkd is None else [int(x) for x in
                                             np.asarray(dkd)],
            **({"t": request.first_token_time}
               if len(request.tokens) == 1 else {}),
            **self._tick_field(tick)})

    def log_done(self, *, rid: int, reason: str, t=None, tick=None) -> None:
        self.append({"ev": "done", "rid": rid, "reason": reason, "t": t,
                     **self._tick_field(tick)})

    def log_shed(self, *, rid: int, reason: str, t=None, tick=None) -> None:
        self.append({"ev": "shed", "rid": rid, "reason": reason, "t": t,
                     **self._tick_field(tick)})

    def log_restart(self, n: int, degraded: bool, cause: str,
                    tick=None) -> None:
        self.append({"ev": "restart", "n": int(n),
                     "degraded": bool(degraded), "cause": cause,
                     **self._tick_field(tick)})

    def log_snapshot(self, request: Request, tick=None,
                     reason: str | None = None) -> None:
        """One request's ENTIRE state as a single ``snap`` record (module
        docstring grammar) — what :meth:`rotate` compacts to and what
        cross-replica migration writes into the adopting replica's
        journal so it alone can recover the adoptee. ``reason`` is the
        migration cause (``"failure"``/``"handoff"``), journaled under
        the ``why`` key and absent when None — see the module docstring
        for why it cannot ride the ``reason`` key."""
        kd, dkd = request.key_data, request.draft_key_data
        self.append({
            "ev": "snap", "rid": request.rid,
            "prompt": [int(x) for x in np.asarray(request.prompt)],
            "max_new": int(request.max_new_tokens),
            "temp": float(request.temperature),
            "top_k": request.top_k, "top_p": request.top_p,
            "eos": request.eos_id, "seed": int(request.seed),
            "cls": request.cls, "prio": int(request.priority),
            "ttft_dl": request.ttft_deadline_s, "dl": request.deadline_s,
            **({} if getattr(request, "adapter", None) is None
               else {"adp": request.adapter}),
            "t": request.submit_time, "state": request.state,
            "reason": request.finish_reason,
            "toks": [int(t) for t in request.tokens],
            "kd": None if kd is None else [int(x) for x in np.asarray(kd)],
            "dkd": (None if dkd is None
                    else [int(x) for x in np.asarray(dkd)]),
            "ftt": request.first_token_time, "dt": request.done_time,
            **({} if reason is None else {"why": reason}),
            **self._tick_field(tick)})

    def log_handoff(self, *, rid: int, dst=None, tick=None) -> None:
        """The rid moved to replica ``dst``'s journal (a prefill->decode
        handoff): terminal for THIS journal — recovery drops the rid."""
        self.append({"ev": "handoff", "rid": int(rid),
                     "dst": None if dst is None else int(dst),
                     **self._tick_field(tick)})

    def rotate(self, tick=None) -> int:
        """Compact the journal in place: fold everything durable into
        per-request snapshots and rewrite the file as ONE ``snap`` record
        per rid (rid order), atomically (write-then-rename, the checkpoint
        store's discipline — a crash mid-rotation leaves either the old
        journal or the new one, never a hybrid). Returns bytes reclaimed.

        The pinned contract (tests/test_fleet.py): ``recover_state`` over
        the rotated journal yields byte-identical snapshots to recovery
        from the unrotated one — rotation changes the replay COST of a
        cold restart (no more re-reading the full token history), never
        its result. Restart records are observability-only and dropped."""
        self._f.flush()
        events, old_bytes = read_journal(self.path)
        snaps = recover_state(events)
        tmp = self.path + ".rotate"
        writer = RequestJournal.__new__(RequestJournal)
        writer.path, writer.sync, writer.bytes = tmp, self.sync, 0
        writer._recovered_events = []
        writer._f = open(tmp, "wb")
        try:
            for rid in sorted(snaps):
                # a recovered migration cause survives compaction (None for
                # never-migrated rids and pre-field journals: key absent,
                # so rotation stays byte-identical for them)
                writer.log_snapshot(snaps[rid], tick=tick,
                                    reason=snaps[rid].snap_reason)
        finally:
            writer.close()
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")
        self.bytes = os.path.getsize(self.path)
        return old_bytes - self.bytes

    def tail(self, n: int = 64) -> list[dict]:
        """The last ``n`` valid journal events, re-read from disk — the
        post-mortem bundle's journal block (bundles are rare; the re-read
        keeps this as honest as :meth:`recovered_state`)."""
        self._f.flush()
        events, _ = read_journal(self.path)
        return events[-n:]

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            if self.sync:
                os.fsync(self._f.fileno())
            self._f.close()

    # -- read side ---------------------------------------------------------

    @property
    def recovered_events(self) -> list[dict]:
        """The valid events found on disk when this journal was OPENED —
        the cold-start recovery input (empty for a fresh file)."""
        return self._recovered_events

    def recovered_state(self) -> dict[int, Request]:
        """Re-read the file from disk and fold it into request snapshots —
        the crash-recovery entry point.  Deliberately NOT served from
        in-process memory: recovery must believe only what an fsync made
        durable, or the bit-exactness claim is about the wrong state."""
        self._f.flush()
        events, _ = read_journal(self.path)
        return recover_state(events)
