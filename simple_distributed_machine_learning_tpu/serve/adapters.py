"""AdapterStore: per-replica residency of named LoRA adapters (ISSUE 20).

Multi-tenant serving keeps ONE copy of the base weights and a small
device-resident BANK of stacked low-rank adapters
(:mod:`..models.lora`); every decode program gathers each slot's A/B
rows by a per-slot adapter index, so one compiled program serves any
adapter mix per tick. This module owns the bookkeeping around that bank:

- **Named adapters, host-side.** :meth:`AdapterStore.register` validates
  shapes and parks the weights in a host dict — NO device work. The host
  dict is shared with the supervisor's engine factory, so a crash-rebuilt
  engine starts with every registered tenant intact (residency resets;
  rows re-upload on demand when recovered requests re-admit).
- **Tick-boundary uploads only.** Device writes happen exclusively
  through :func:`~..models.gpt.make_adapter_bank_update` (one memoized
  donated-bank program) and only from :meth:`retain`/:meth:`ensure_resident`,
  which the engine's admission gate calls inside ``step()`` — between
  program dispatches, never mid-tick. A hot-swap is a bank-row rewrite
  of traced data: no decode program ever retraces.
- **Refcounted residency, never-refuse.** The bank has ``n_slots + 1``
  rows (row 0 = the all-zero base row, never evicted). An admitted
  request holds one ref on its adapter's row until it finishes,
  preempts, or cancels. Admission needs a free slot first, so at most
  ``n_slots - 1`` rows are referenced when a new request boards —
  structurally there is ALWAYS an evictable zero-ref row, and admission
  can never refuse for lack of bank space.
- **Version-pinned hot-swap.** Re-registering a live adapter bumps its
  version host-side; in-flight requests keep decoding from the old row
  (their token streams stay bit-exact vs the OLD merged-dense anchor),
  while the next admission uploads the new version to a fresh row. The
  old row is reclaimed once its last ref drops.

``serve_adapter_resident_bytes`` is the whole static bank
(:func:`~..models.lora.bank_bytes` — the same formula the analyzer's
``predict_adapter_bytes`` uses, which makes the parity pin exact), and
``serve_adapter_swaps_total`` counts device row uploads.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp

from simple_distributed_machine_learning_tpu.models import lora
from simple_distributed_machine_learning_tpu.models.gpt import (
    make_adapter_bank_update,
)


def validate_adapter_name(name: str) -> None:
    """Adapter names key the journal and the prefix-cache namespace —
    reject the empty string and NUL (the namespace delimiter)."""
    if not isinstance(name, str) or not name:
        raise ValueError("adapter name must be a non-empty string")
    if "\x00" in name:
        raise ValueError("adapter name must not contain NUL — it "
                         "delimits the prefix-cache namespace")


def adapter_namespace(name: str | None) -> bytes:
    """The prefix-cache key namespace for a request's adapter: tenants
    must NEVER share K/V blocks across adapters (the cached values were
    computed under a different model). ``None`` (base model) maps to the
    EMPTY namespace so pre-adapter cache keys stay byte-identical; a
    named adapter prefixes ``name + NUL`` — unambiguous because names
    reject NUL."""
    return b"" if name is None else name.encode() + b"\x00"


class AdapterStore:
    """Residency manager for one engine's adapter bank.

    ``host`` is the shared ``{name: adapter weights}`` dict; pass the
    same dict into every rebuild (the supervisor's engine factory does)
    so registered tenants survive crash recovery. Entries already in
    ``host`` at construction are validated and served on demand.
    """

    # per-process store identity: a fleet's replicas share ONE ServeMetrics,
    # and the lifetime->delta swap accounting must be kept per store or N
    # stores' counters ratchet to the max instead of summing
    _ids = itertools.count()

    def __init__(self, cfg, rank: int, n_slots: int, host: dict | None = None):
        lora._check_rank(cfg.d_model, rank)
        if n_slots < 1:
            raise ValueError("AdapterStore needs at least one slot")
        self.cfg = cfg
        self.rank = int(rank)
        self.n_rows = int(n_slots) + 1
        self._host: dict = host if host is not None else {}
        for name, weights in self._host.items():
            validate_adapter_name(name)
            lora.check_adapter_shapes(weights, cfg, rank)
        self._update = make_adapter_bank_update()
        self._zero = lora.zero_adapter(cfg, rank)
        self.bank = lora.stack_adapters([self._zero] * self.n_rows)
        self._ver: dict[str, int] = {}          # name -> host version
        self._rows: list = [None] * self.n_rows  # row -> (name, ver) | None
        self._refs = [0] * self.n_rows           # row -> in-flight requests
        self._latest: dict[str, int] = {}        # name -> row of current ver
        self._swaps = 0                          # lifetime device uploads
        self._sid = next(AdapterStore._ids)

    # -- host side (no device work) ------------------------------------

    def register(self, name: str, weights: dict) -> None:
        """Add or hot-swap a named adapter, host-side only. Re-register
        of a live name bumps the version: in-flight requests keep the
        old row, the next admission uploads the new weights. The version
        counts registrations THIS store saw (not host-dict membership —
        N fleet stores share one host dict, and each must version
        identically regardless of registration order)."""
        validate_adapter_name(name)
        lora.check_adapter_shapes(weights, self.cfg, self.rank)
        self._host[name] = weights
        self._ver[name] = self._ver.get(name, -1) + 1
        self._latest.pop(name, None)  # any resident row is now stale

    def names(self) -> tuple:
        return tuple(sorted(self._host))

    def is_registered(self, name: str) -> bool:
        return name in self._host

    def is_resident(self, name: str) -> bool:
        """True when the CURRENT version of ``name`` is uploaded — the
        router's adapter-affinity probe."""
        return name in self._latest

    def namespace_of(self, name: str | None) -> bytes:
        """The VERSION-QUALIFIED prefix-cache namespace for ``name``'s
        current registration (``None`` = the base model's empty
        namespace). The version rides in the key prefix so a hot-swap
        implicitly invalidates the old version's cached K/V — blocks a
        superseded adapter computed are exactly as wrong for the new one
        as another tenant's."""
        if name is None:
            return b""
        return adapter_namespace(f"{name}@{self._ver.get(name, 0)}")

    def row_of(self, name: str) -> int:
        return self._latest[name]

    # -- device side (tick-boundary only: called from the engine's
    #    admission gate inside step()) ---------------------------------

    def ensure_resident(self, name: str) -> int:
        """Upload ``name``'s current version if needed; return its row."""
        if name not in self._host:
            raise KeyError(f"adapter {name!r} is not registered")
        row = self._latest.get(name)
        if row is not None:
            return row
        row = self._alloc()
        self.bank = self._update(self.bank, jnp.int32(row),
                                 self._host[name])
        self._rows[row] = (name, self._ver.get(name, 0))
        self._latest[name] = row
        self._swaps += 1
        return row

    def _alloc(self) -> int:
        """Pick a zero-ref row to overwrite: never row 0, prefer empty
        rows, then stale versions, then evict a resident mapping. The
        n_slots+1 sizing guarantees a candidate exists whenever the
        engine has a free slot to admit into."""
        def key(i):
            held = self._rows[i]
            if held is None:
                return 0
            return 1 if self._latest.get(held[0]) != i else 2

        free = [i for i in range(1, self.n_rows) if self._refs[i] == 0]
        if not free:  # pragma: no cover - structurally unreachable
            raise RuntimeError(
                "adapter bank exhausted: every row referenced — admission "
                "gating should have made this impossible")
        row = min(free, key=lambda i: (key(i), i))
        held = self._rows[row]
        if held is not None and self._latest.get(held[0]) == row:
            del self._latest[held[0]]
        self._rows[row] = None
        return row

    def retain(self, name: str) -> int:
        """Admission-gate entry: ensure residency and take a ref; the
        request releases it (by row) when it leaves the engine."""
        row = self.ensure_resident(name)
        self._refs[row] += 1
        return row

    def release(self, row: int) -> None:
        if row <= 0:
            return
        if self._refs[row] <= 0:  # pragma: no cover - double-release bug
            raise RuntimeError(f"adapter bank row {row} released with no "
                               f"outstanding refs")
        self._refs[row] -= 1

    # -- accounting ----------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        """HBM the bank pins — the whole static allocation, matching the
        analyzer's ``predict_adapter_bytes`` by shared formula."""
        return lora.bank_bytes(self.n_rows, self.cfg.n_layers,
                               self.cfg.d_model, self.rank)

    @property
    def swaps_total(self) -> int:
        return self._swaps

    def stats(self) -> dict:
        """The metrics hook payload (``on_tick(adapter_stats=...)``).
        ``store`` identifies THIS store so a fleet's shared ServeMetrics
        can delta each store's lifetime swap counter separately."""
        return {"resident_bytes": self.resident_bytes,
                "swaps_total": self._swaps,
                "n_resident": len(self._latest),
                "n_rows": self.n_rows,
                "rank": self.rank,
                "store": self._sid}
