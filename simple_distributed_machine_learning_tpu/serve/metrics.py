"""Serving telemetry on the PR-4 ``MetricsRegistry``: JSONL + Prometheus.

The serving-standard latency split, as registry instruments:

- ``serve_ttft_ms`` (histogram) — time to first token: queue wait + prefill,
  per request. The latency a user perceives before anything streams.
- ``serve_tpot_ms`` (histogram) — time per output token after the first:
  the decode-tick cadence, one observation per generated token.
- ``serve_queue_depth`` / ``serve_slots_active`` / ``serve_slots_total``
  (gauges) and ``serve_slot_occupancy`` (histogram of active/total per
  tick) — how full the continuous batch runs; occupancy is what batched
  decoding converts into aggregate throughput.
- ``serve_requests_submitted_total`` / ``serve_requests_completed_total`` /
  ``serve_tokens_generated_total`` (counters) and ``serve_tokens_per_sec``
  (gauge) — lifetime request/token counters and aggregate throughput over
  the wall-clock window from first submit to last token.

Paged-pool instruments (populated only by ``kv_layout="paged"`` engines —
the engine hands the pool's stats to :meth:`ServeMetrics.on_tick`):

- ``serve_blocks_in_use`` / ``serve_blocks_free`` / ``serve_blocks_cached``
  / ``serve_blocks_total`` (gauges) — block-pool occupancy: live working
  set, allocatable headroom, reclaimable prefix cache;
- ``serve_kv_bytes_resident`` (gauge) — bytes of K/V live requests
  actually pin (the number the paged layout shrinks vs dense rows);
- ``serve_prefix_hit_blocks_total`` / ``serve_cow_copies_total`` /
  ``serve_block_evictions_total`` (counters) — prefix-share hits at
  admission, copy-on-write block copies, LRU cache evictions;
- ``serve_prefill_chunk_ms`` (histogram) — per-chunk prefill latency: the
  quantity chunked prefill bounds so decode ticks stay steady.

Sharded + speculative instruments (ISSUE 9):

- ``serve_tp`` / ``serve_spec_k`` (gauges) — the deployment shape: tensor-
  parallel width and speculative verify width (0 = plain decode);
- ``serve_attn_kernel_fused`` (gauge, 0/1) — which attention path the
  paged decode/verify ticks compile: 0 = gather-then-dense (the parity
  anchor), 1 = the fused Pallas paged-attention kernel (one HBM pass of
  resident K/V per tick; ``ops/paged_attention.py``) — dashboards
  correlate per-tick latency shifts with the kernel path in play;
- ``serve_spec_proposed_tokens_total`` / ``serve_spec_accepted_tokens_total``
  / ``serve_spec_rejected_tokens_total`` (counters) and
  ``serve_spec_accept_rate`` (histogram, one observation per speculative
  tick) — how much of the draft's work the target agreed with; accept
  rate is what converts ``spec_k`` into real tokens/tick.

Traffic-class instruments (populated when requests carry ``cls`` — the
scenario suite's per-class SLO accounting, ``resilience/scenarios.py``):

- ``serve_class_ttft_ms{class=...}`` / ``serve_class_tpot_ms{class=...}``
  (histograms) — the per-class latency split SLO attainment is computed
  from (:meth:`ServeMetrics.attainment` via the registry histograms'
  ``fraction_below``);
- ``serve_class_completed_total{class=...}`` and
  ``serve_class_preemptions_total{class=...}`` (counters), plus the global
  ``serve_preemptions_total`` — how often priority scheduling evicted
  best-effort traffic to protect an interactive class.

Crash-restart + overload-control instruments (fed by the serve supervisor,
``serve/supervisor.py``):

- ``serve_restarts_total`` (counter) — engine rebuilds after a recoverable
  failure;
- ``serve_recovered_requests_total`` (counter) — in-flight requests
  re-admitted from the journal across those restarts;
- ``serve_shed_total{reason=deadline|backpressure|class}`` (counter) and
  ``serve_class_shed_total{class=...}`` — structured rejections: expired
  deadlines, queue-depth backpressure, per-class token-bucket/degraded
  lockout;
- ``serve_degraded`` (gauge, 0/1) — whether the supervisor is in a
  degraded mode (fallback engine layout after repeated crashes, or the
  overload best-effort lockout);
- ``serve_journal_bytes`` (gauge) — the request journal's durable size
  (under a fleet: summed over every alive replica's journal).

Fleet instruments (fed by the multi-replica fleet, ``serve/fleet.py``):

- ``serve_fleet_replicas`` (gauge) — alive replicas currently IN ROTATION
  (healthy per the supervisor state machine and past the re-entry
  hysteresis): the capacity the router is actually spreading load over;
- ``serve_fleet_replica_losses_total`` (counter) — whole-replica deaths
  the fleet absorbed (injected ``replica-kill`` faults and replicas whose
  supervisor exhausted its restart budget);
- ``serve_fleet_migrations_total`` (counter) — in-flight requests
  re-admitted onto a SURVIVING replica from a dead replica's journal
  alone (the cross-replica migration path — each one's token stream stays
  bit-exact vs the uninterrupted run);
- ``serve_route_affinity_hits_total`` (counter) — routing decisions that
  landed on a replica already holding the request's prompt prefix in its
  paged pool's registry (the prefix-cache-aware half of the router; the
  hot-prefix-skew scenario pins this strictly above round-robin);
- ``serve_fleet_scale_outs_total`` / ``serve_fleet_retired_total``
  (counters) — autoscaler actions: replicas added on sustained backlog,
  replicas drained-then-retired on sustained idleness;
- ``serve_route_alert_demotions_total`` (counter) — routing decisions
  where the best prefix-affinity candidate was skipped because its
  per-replica SLO burn alert was firing (the alert→router feedback loop;
  the burn-rate / alert instruments themselves are documented alongside
  the SLO engine, ``telemetry/slo.py``, and the TTFT attribution
  histogram alongside ``telemetry/attribution.py``).

Disaggregated-pool + host-offload-tier instruments (ISSUE 17 — fed by
the disaggregated fleet, ``serve/fleet.py``, and the paged pool's host
tier, ``serve/slots.py``):

- ``serve_fleet_handoffs_total`` (counter) — planned prefill→decode
  migrations: requests moved at end-of-prefill by the same journal
  snap/adopt move failure migration uses, each handed-off token stream
  bit-exact vs the symmetric single-pool run;
- ``serve_pool_replicas{pool=prefill|decode}`` (gauge) — alive replicas
  per role pool: the independently-sized halves of a disaggregated
  fleet;
- ``serve_pool_queue_depth{pool=...}`` / ``serve_pool_slots_active{pool=...}``
  (gauges) — per-pool backlog and occupancy: the imbalance signal the
  disaggregated scenarios pin (prefill-heavy vs decode-heavy mixes);
- ``serve_host_blocks`` / ``serve_host_bytes_resident`` (gauges) —
  host-RAM offload tier occupancy: blocks demoted from HBM that live on
  in host memory, and the bytes they pin there (the analyzer's
  ``predict_host_kv_bytes`` reconciles the byte gauge exactly);
- ``serve_host_inflight_blocks`` (gauge) — blocks mid async host→HBM
  prefetch upload: reserved on device, keys not yet registered;
- ``serve_host_demotes_total`` / ``serve_host_promotes_total`` /
  ``serve_host_evictions_total`` (counters) — tier traffic: HBM
  evictions demoted to host instead of dying, completed uploads that
  re-registered their prefix keys in HBM, and host-side LRU drops at
  ``host_cache_blocks`` capacity;
- ``serve_host_prefetch_hits_total`` / ``serve_host_prefetch_misses_total``
  (counters) — routing-time prefetch outcomes: a hit started (or joined)
  the async upload of a host-resident prefix, a miss found nothing the
  HBM registry didn't already cover or no free blocks to upload into;
- ``serve_host_transfer_bytes_total`` (counter) — bytes moved across the
  HBM↔host boundary in either direction (demotes down, promotes up) —
  the transfer-bandwidth bill ``predict_transfer_bytes`` reconciles with
  the same drift-must-be-zero discipline as ``serve_kv_drift_bytes``.

Multi-tenant adapter instruments (ISSUE 20 — fed by the engine's
per-tick ``AdapterStore.stats()`` payload, the router, and completion):

- ``serve_adapter_resident_bytes`` (gauge) — HBM the device adapter bank
  pins: the whole static ``[n_rows, L, d, r]`` stacked-A/B allocation
  (``models/lora.py::bank_bytes`` — the analyzer's
  ``predict_adapter_bytes`` reconciles this gauge EXACTLY, the same
  parity discipline as ``serve_kv_bytes_predicted``);
- ``serve_adapter_swaps_total`` (counter) — adapter bank-row uploads:
  tick-boundary device writes that seated a tenant's weights (a
  hot-swap or first admission; never a retrace — the bank is traced
  data);
- ``serve_route_adapter_affinity_hits_total`` (counter) — routing
  decisions made by adapter residency: the request landed on a replica
  already holding its adapter's current version on device, skipping a
  bank-row upload (the hot-adapter-churn scenario pins this strictly
  above round-robin);
- ``serve_class_adapter`` (counter, labeled ``class=<adapter name>``) —
  completed requests per TENANT: the per-adapter traffic split the
  telemetry report's tenant block renders.

Model-drift instruments (ISSUE 12 — the PR-8 static model checked as a
runtime invariant, fed every tick from ``engine.kv_drift``):

- ``serve_kv_bytes_predicted`` (gauge) — the analyzer's
  ``predict_kv_bytes_resident`` over the live sequences' written-row
  counts: what the static HBM model says the pool must be pinning;
- ``serve_kv_drift_bytes`` (gauge) — live resident bytes minus the
  prediction: exactly 0 without prefix sharing, ≤ 0 with it (sharing only
  shrinks the truth), > 0 only on a block-accounting leak — the invariant
  the clean-run tests pin at zero.

``emit()`` writes one ``kind: "serve"`` record to ``metrics.jsonl`` and
refreshes ``metrics.prom`` — the same two artifact formats the training
telemetry session emits, so one scrape config covers both.
"""

from __future__ import annotations

import os
import time

from simple_distributed_machine_learning_tpu.telemetry.registry import (
    MetricsRegistry,
    append_jsonl,
)

METRICS_FILE = "metrics.jsonl"
PROM_FILE = "metrics.prom"

# pool-stat counter keys -> instrument names (the pool reports lifetime
# totals; the registry's counters are fed the per-tick deltas)
_POOL_COUNTERS = {
    "prefix_hit_blocks_total": "serve_prefix_hit_blocks_total",
    "cow_copies_total": "serve_cow_copies_total",
    "evictions_total": "serve_block_evictions_total",
}

# host-offload-tier counter keys -> instrument names (same lifetime-total
# to per-tick-delta conversion; present in ``stats()`` only when the pool
# runs with ``host_cache_blocks > 0``)
_HOST_COUNTERS = {
    "host_demotes_total": "serve_host_demotes_total",
    "host_promotes_total": "serve_host_promotes_total",
    "host_evictions_total": "serve_host_evictions_total",
    "host_prefetch_hits_total": "serve_host_prefetch_hits_total",
    "host_prefetch_misses_total": "serve_host_prefetch_misses_total",
    "host_transfer_bytes_total": "serve_host_transfer_bytes_total",
}


class ServeMetrics:
    """One serving run's instruments; see module docstring."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 outdir: str | None = None,
                 clock=time.monotonic) -> None:
        self.registry = registry if registry is not None else MetricsRegistry()
        self.outdir = outdir
        self._clock = clock
        self._t_first_submit: float | None = None
        self._t_last_token: float | None = None
        r = self.registry
        self.queue_depth = r.gauge("serve_queue_depth")
        self.slots_active = r.gauge("serve_slots_active")
        self.slots_total = r.gauge("serve_slots_total")
        self.occupancy = r.histogram("serve_slot_occupancy")
        self.ttft_ms = r.histogram("serve_ttft_ms")
        self.tpot_ms = r.histogram("serve_tpot_ms")
        self.submitted = r.counter("serve_requests_submitted_total")
        self.completed = r.counter("serve_requests_completed_total")
        self.tokens = r.counter("serve_tokens_generated_total")
        self.tokens_per_sec = r.gauge("serve_tokens_per_sec")
        # paged block-pool instruments (stay at zero under a dense engine;
        # summary() includes their block only once block stats arrive)
        self.blocks_total = r.gauge("serve_blocks_total")
        self.blocks_in_use = r.gauge("serve_blocks_in_use")
        self.blocks_free = r.gauge("serve_blocks_free")
        self.blocks_cached = r.gauge("serve_blocks_cached")
        self.kv_bytes_resident = r.gauge("serve_kv_bytes_resident")
        # model-drift gauges (both layouts; fed per tick by the engine)
        self.kv_bytes_predicted = r.gauge("serve_kv_bytes_predicted")
        self.kv_drift_bytes = r.gauge("serve_kv_drift_bytes")
        self._drift_seen = False
        self.prefill_chunk_ms = r.histogram("serve_prefill_chunk_ms")
        self._pool_counters = {k: r.counter(v)
                               for k, v in _POOL_COUNTERS.items()}
        self._pool_counter_seen = dict.fromkeys(_POOL_COUNTERS, 0)
        self._paged_seen = False
        # sharded + speculative serving instruments: the engine feeds the
        # shape gauges every tick and the spec counters per verify
        self.tp_gauge = r.gauge("serve_tp")
        self.spec_k_gauge = r.gauge("serve_spec_k")
        self.attn_kernel_gauge = r.gauge("serve_attn_kernel_fused")
        self.spec_proposed = r.counter("serve_spec_proposed_tokens_total")
        self.spec_accepted = r.counter("serve_spec_accepted_tokens_total")
        self.spec_rejected = r.counter("serve_spec_rejected_tokens_total")
        self.spec_accept_rate = r.histogram("serve_spec_accept_rate")
        self._shape_seen = False
        self._spec_seen = False
        self.preemptions = r.counter("serve_preemptions_total")
        # crash-restart + overload-control instruments (the supervisor's
        # hooks; the summary's resilience block appears once any fires)
        self.restarts_total = r.counter("serve_restarts_total")
        self.recovered_total = r.counter("serve_recovered_requests_total")
        self.degraded_gauge = r.gauge("serve_degraded")
        self.journal_bytes_gauge = r.gauge("serve_journal_bytes")
        self._shed_reasons: dict[str, object] = {}
        self._resilience_seen = False
        # fleet instruments (serve/fleet.py; the summary's fleet block
        # appears once the fleet sets its replica gauge)
        self.fleet_replicas = r.gauge("serve_fleet_replicas")
        self.fleet_losses = r.counter("serve_fleet_replica_losses_total")
        self.fleet_migrations = r.counter("serve_fleet_migrations_total")
        self.route_affinity_hits = r.counter(
            "serve_route_affinity_hits_total")
        self.fleet_scale_outs = r.counter("serve_fleet_scale_outs_total")
        self.fleet_retired = r.counter("serve_fleet_retired_total")
        self.fleet_handoffs = r.counter("serve_fleet_handoffs_total")
        self.route_alert_demotions = r.counter(
            "serve_route_alert_demotions_total")
        self._fleet_seen = False
        # optional streaming SLO engine (telemetry/slo.py): when bound,
        # every TTFT/TPOT/shed observation is forwarded with the replica
        # index the fleet sets around each per-replica step/submit (None
        # under a single supervisor — class-level series only)
        self.slo = None
        self._slo_replica: int | None = None
        # disaggregated per-pool gauges (labeled by role; fed by the fleet
        # once per tick when it runs with prefill_replicas > 0)
        self._pool_gauges: dict[tuple, object] = {}
        self._pool_names: set[str] = set()
        self._pools_seen = False
        # host offload tier (paged pools with host_cache_blocks > 0;
        # gauges set and counters delta-fed from block_stats exactly like
        # the _POOL_COUNTERS discipline)
        self.host_blocks = r.gauge("serve_host_blocks")
        self.host_bytes_resident = r.gauge("serve_host_bytes_resident")
        self.host_inflight = r.gauge("serve_host_inflight_blocks")
        self._host_counters = {k: r.counter(v)
                               for k, v in _HOST_COUNTERS.items()}
        self._host_counter_seen = dict.fromkeys(_HOST_COUNTERS, 0)
        self._host_seen = False
        # multi-tenant adapter instruments (engines built with an
        # AdapterStore feed the gauge/swap counter per tick; the fleet
        # router feeds the affinity counter; completion feeds per-tenant)
        self.adapter_resident_bytes = r.gauge(
            "serve_adapter_resident_bytes")
        self.adapter_swaps = r.counter("serve_adapter_swaps_total")
        self.route_adapter_hits = r.counter(
            "serve_route_adapter_affinity_hits_total")
        # lifetime->delta swap accounting PER STORE (a fleet's replicas
        # each own an AdapterStore but share this metrics object; one
        # scalar would ratchet to the max instead of summing)
        self._adapter_swaps_seen: dict[int, int] = {}
        self._adapter_seen = False
        self._adapter_names: set[str] = set()
        self._classes: set[str] = set()
        if outdir:
            os.makedirs(outdir, exist_ok=True)

    # -- per-class series (scenario suite) ---------------------------------

    def _class_hist(self, name: str, cls: str):
        self._classes.add(cls)
        return self.registry.histogram(name, labels={"class": cls})

    def _class_counter(self, name: str, cls: str):
        self._classes.add(cls)
        return self.registry.counter(name, labels={"class": cls})

    # -- event hooks (engine-driven) --------------------------------------

    def on_submit(self) -> None:
        if self._t_first_submit is None:
            self._t_first_submit = self._clock()
        self.submitted.inc()

    def bind_slo(self, slo) -> None:
        """Attach a :class:`telemetry.slo.SLOEngine`; subsequent latency
        and shed observations stream into its windowed series."""
        self.slo = slo

    def on_first_token(self, ttft_s: float, cls: str | None = None) -> None:
        self.ttft_ms.observe(ttft_s * 1e3)
        if cls is not None:
            self._class_hist("serve_class_ttft_ms", cls).observe(ttft_s * 1e3)
            if self.slo is not None:
                self.slo.observe_ttft(cls, ttft_s * 1e3,
                                      replica=self._slo_replica)
        self._on_any_token()

    def on_token(self, tpot_s: float, cls: str | None = None) -> None:
        self.tpot_ms.observe(tpot_s * 1e3)
        if cls is not None:
            self._class_hist("serve_class_tpot_ms", cls).observe(tpot_s * 1e3)
            if self.slo is not None:
                self.slo.observe_tpot(cls, tpot_s * 1e3,
                                      replica=self._slo_replica)
        self._on_any_token()

    def on_preempt(self, cls: str | None = None) -> None:
        self.preemptions.inc()
        if cls is not None:
            self._class_counter("serve_class_preemptions_total", cls).inc()

    # -- supervisor hooks (crash restart + overload control) ---------------

    def on_restart(self) -> None:
        self._resilience_seen = True
        self.restarts_total.inc()

    def on_recovered(self, n: int) -> None:
        """``n`` in-flight requests re-admitted from the journal."""
        self._resilience_seen = True
        if n:
            self.recovered_total.inc(n)

    def on_shed(self, reason: str, cls: str | None = None) -> None:
        """One structured rejection; ``reason`` is the label value
        (``deadline`` | ``backpressure`` | ``class``)."""
        self._resilience_seen = True
        counter = self._shed_reasons.get(reason)
        if counter is None:
            counter = self._shed_reasons[reason] = self.registry.counter(
                "serve_shed_total", labels={"reason": reason})
        counter.inc()
        if cls is not None:
            self._class_counter("serve_class_shed_total", cls).inc()
            if self.slo is not None:
                self.slo.observe_shed(cls, replica=self._slo_replica)

    def set_degraded(self, degraded) -> None:
        self._resilience_seen = True
        self.degraded_gauge.set(int(bool(degraded)))

    def set_journal_bytes(self, n: int) -> None:
        self._resilience_seen = True
        self.journal_bytes_gauge.set(int(n))

    # -- fleet hooks (serve/fleet.py) ---------------------------------------

    def set_fleet_replicas(self, n: int) -> None:
        """Alive in-rotation replicas after this fleet tick."""
        self._fleet_seen = True
        self.fleet_replicas.set(int(n))

    def on_replica_loss(self) -> None:
        self._fleet_seen = True
        self.fleet_losses.inc()

    def on_fleet_migrated(self, n: int) -> None:
        """``n`` in-flight requests migrated off a dead replica."""
        self._fleet_seen = True
        if n:
            self.fleet_migrations.inc(n)

    def on_affinity_hit(self) -> None:
        self._fleet_seen = True
        self.route_affinity_hits.inc()

    def on_adapter_affinity_hit(self) -> None:
        """The router's decision was made by adapter residency — the
        destination already holds the request's adapter on device."""
        self._fleet_seen = True
        self._adapter_seen = True
        self.route_adapter_hits.inc()

    def on_alert_demotion(self) -> None:
        """The router skipped the best affinity candidate because its
        per-replica burn alert was firing (the alert feedback loop)."""
        self._fleet_seen = True
        self.route_alert_demotions.inc()

    def on_scale_out(self) -> None:
        self._fleet_seen = True
        self.fleet_scale_outs.inc()

    def on_retire(self) -> None:
        self._fleet_seen = True
        self.fleet_retired.inc()

    def on_handoff(self, n: int = 1) -> None:
        """``n`` planned prefill→decode handoffs fired this fleet tick."""
        self._fleet_seen = True
        if n:
            self.fleet_handoffs.inc(n)

    def _pool_gauge(self, name: str, pool: str):
        key = (name, pool)
        g = self._pool_gauges.get(key)
        if g is None:
            g = self._pool_gauges[key] = self.registry.gauge(
                name, labels={"pool": pool})
        return g

    def set_pool_stats(self, pool: str, *, replicas: int,
                       queue_depth: int, slots_active: int) -> None:
        """One role pool's end-of-tick shape (disaggregated fleets only):
        alive replicas, summed queue depth, summed active slots."""
        self._pools_seen = True
        self._pool_names.add(pool)
        self._pool_gauge("serve_pool_replicas", pool).set(int(replicas))
        self._pool_gauge("serve_pool_queue_depth",
                         pool).set(int(queue_depth))
        self._pool_gauge("serve_pool_slots_active",
                         pool).set(int(slots_active))

    def _on_any_token(self) -> None:
        self.tokens.inc()
        self._t_last_token = self._clock()
        span = self.window_s
        if span and span > 0:
            self.tokens_per_sec.set(self.tokens.value / span)

    def on_complete(self, cls: str | None = None,
                    adapter: str | None = None) -> None:
        self.completed.inc()
        if cls is not None:
            self._class_counter("serve_class_completed_total", cls).inc()
        if adapter is not None:
            # per-tenant traffic split; the label namespace is the
            # adapter name (distinct from self._classes — tenants are
            # not traffic classes)
            self._adapter_seen = True
            self._adapter_names.add(adapter)
            self.registry.counter("serve_class_adapter",
                                  labels={"class": adapter}).inc()

    def on_prefill_chunk(self, chunk_ms: float) -> None:
        """One prefill chunk's wall latency (paged engines; the dense
        layout's monolithic prefill is inside TTFT instead)."""
        self.prefill_chunk_ms.observe(chunk_ms)

    def on_spec(self, proposed: int, accepted: int) -> None:
        """One speculative tick's draft-token accounting: ``proposed``
        draft tokens were verified, ``accepted`` survived (the rest were
        rejected at or after the first target disagreement). The
        acceptance-rate histogram gets one per-tick observation — with
        draft == target it pins at 1.0 (tests)."""
        self._spec_seen = True
        rejected = proposed - accepted
        self.spec_proposed.inc(proposed)
        if accepted:
            self.spec_accepted.inc(accepted)
        if rejected:
            self.spec_rejected.inc(rejected)
        self.spec_accept_rate.observe(accepted / proposed)

    def on_tick(self, queue_depth: int, active: int, total: int,
                decode_active: int | None = None,
                block_stats: dict | None = None,
                tp: int | None = None, spec_k: int | None = None,
                kv_predicted: int | None = None,
                kv_drift: int | None = None,
                attn_kernel: str | None = None,
                adapter_stats: dict | None = None) -> None:
        """End-of-tick gauges; ``decode_active`` is the occupancy the tick's
        batched decode ran at (sampled BEFORE same-tick retirement — the
        number batching converts into throughput). Ticks that ran no decode
        (``decode_active == 0``) skip the occupancy observation.
        ``block_stats`` is ``PagedKVPool.stats()`` — lifetime counters are
        converted to registry increments here. ``kv_predicted``/``kv_drift``
        are the engine's per-tick model check (``engine.kv_drift``).
        ``adapter_stats`` is ``AdapterStore.stats()`` (engines serving
        multi-tenant adapters) — same lifetime-to-delta discipline for
        the swap counter."""
        self.queue_depth.set(queue_depth)
        self.slots_active.set(active)
        self.slots_total.set(total)
        if kv_predicted is not None:
            self._drift_seen = True
            self.kv_bytes_predicted.set(kv_predicted)
            self.kv_drift_bytes.set(kv_drift or 0)
        if tp is not None:
            self._shape_seen = True
            self.tp_gauge.set(tp)
            self.spec_k_gauge.set(spec_k or 0)
        if attn_kernel is not None:
            self.attn_kernel_gauge.set(int(attn_kernel == "fused"))
        if adapter_stats is not None:
            self._adapter_seen = True
            self.adapter_resident_bytes.set(
                adapter_stats["resident_bytes"])
            sid = adapter_stats.get("store", 0)
            delta = (adapter_stats["swaps_total"]
                     - self._adapter_swaps_seen.get(sid, 0))
            if delta > 0:
                self.adapter_swaps.inc(delta)
                self._adapter_swaps_seen[sid] = \
                    adapter_stats["swaps_total"]
        occ = active if decode_active is None else decode_active
        if occ and total:
            self.occupancy.observe(occ / total)
        if block_stats is not None:
            self._paged_seen = True
            self.blocks_total.set(block_stats["blocks_total"])
            self.blocks_in_use.set(block_stats["blocks_in_use"])
            self.blocks_free.set(block_stats["blocks_free"])
            self.blocks_cached.set(block_stats["blocks_cached"])
            self.kv_bytes_resident.set(block_stats["kv_bytes_resident"])
            for key, counter in self._pool_counters.items():
                delta = block_stats[key] - self._pool_counter_seen[key]
                if delta > 0:
                    counter.inc(delta)
                    self._pool_counter_seen[key] = block_stats[key]
            if "host_blocks" in block_stats:
                self._host_seen = True
                self.host_blocks.set(block_stats["host_blocks"])
                self.host_bytes_resident.set(
                    block_stats["host_bytes_resident"])
                self.host_inflight.set(
                    block_stats["host_inflight_blocks"])
                for key, counter in self._host_counters.items():
                    delta = (block_stats[key]
                             - self._host_counter_seen[key])
                    if delta > 0:
                        counter.inc(delta)
                        self._host_counter_seen[key] = block_stats[key]

    # -- aggregation -------------------------------------------------------

    @property
    def window_s(self) -> float | None:
        """First submit -> last token wall-clock span (the throughput
        denominator; None before any token)."""
        if self._t_first_submit is None or self._t_last_token is None:
            return None
        return self._t_last_token - self._t_first_submit

    def class_summary(self, cls: str) -> dict:
        """One traffic class's latency/throughput block."""
        r3 = (lambda v: None if v is None else round(v, 3))
        ttft = self._class_hist("serve_class_ttft_ms", cls)
        tpot = self._class_hist("serve_class_tpot_ms", cls)
        return {
            "completed": int(
                self._class_counter("serve_class_completed_total",
                                    cls).value),
            "preemptions": int(
                self._class_counter("serve_class_preemptions_total",
                                    cls).value),
            "shed": int(
                self._class_counter("serve_class_shed_total", cls).value),
            "ttft_ms_p50": r3(ttft.quantile(0.5)),
            "ttft_ms_p95": r3(ttft.quantile(0.95)),
            "tpot_ms_p50": r3(tpot.quantile(0.5)),
            "tpot_ms_p95": r3(tpot.quantile(0.95)),
        }

    def attainment(self, cls: str, ttft_slo_ms: float | None = None,
                   tpot_slo_ms: float | None = None) -> dict:
        """SLO attainment for one class, straight from the registry
        histograms: the weighted fraction of observations within target
        (``Histogram.fraction_below``). None targets are skipped; a class
        with no observations reports None attainment (the scenario runner
        treats that as failure — silence is not attainment)."""
        out = dict(self.class_summary(cls))
        if ttft_slo_ms is not None:
            out["ttft_slo_ms"] = ttft_slo_ms
            out["ttft_attainment"] = self._class_hist(
                "serve_class_ttft_ms", cls).fraction_below(ttft_slo_ms)
        if tpot_slo_ms is not None:
            out["tpot_slo_ms"] = tpot_slo_ms
            out["tpot_attainment"] = self._class_hist(
                "serve_class_tpot_ms", cls).fraction_below(tpot_slo_ms)
        return out

    def summary(self) -> dict:
        """The serving record block (bench rows and ``emit`` embed it)."""
        r3 = (lambda v: None if v is None else round(v, 3))
        out = {
            "requests_submitted": int(self.submitted.value),
            "requests_completed": int(self.completed.value),
            "tokens_generated": int(self.tokens.value),
            "tokens_per_sec": round(self.tokens_per_sec.value, 1),
            "ttft_ms_p50": r3(self.ttft_ms.quantile(0.5)),
            "ttft_ms_p95": r3(self.ttft_ms.quantile(0.95)),
            "tpot_ms_p50": r3(self.tpot_ms.quantile(0.5)),
            "tpot_ms_p95": r3(self.tpot_ms.quantile(0.95)),
            "slot_occupancy_mean": r3(self.occupancy.mean),
        }
        if self._shape_seen:
            out["tp"] = int(self.tp_gauge.value)
            out["spec_k"] = int(self.spec_k_gauge.value)
        if self._spec_seen:
            proposed = int(self.spec_proposed.value)
            accepted = int(self.spec_accepted.value)
            out.update({
                "spec_proposed_tokens": proposed,
                "spec_accepted_tokens": accepted,
                "spec_rejected_tokens": int(self.spec_rejected.value),
                "spec_accept_rate": (round(accepted / proposed, 4)
                                     if proposed else None),
            })
        if self.preemptions.value:
            out["preemptions"] = int(self.preemptions.value)
        if self._resilience_seen:
            shed = {reason: int(c.value)
                    for reason, c in sorted(self._shed_reasons.items())
                    if c.value}
            out.update({
                "restarts": int(self.restarts_total.value),
                "recovered_requests": int(self.recovered_total.value),
                "shed_total": sum(shed.values()),
                "shed_by_reason": shed,
                "degraded": int(self.degraded_gauge.value),
                "journal_bytes": int(self.journal_bytes_gauge.value),
            })
        if self._fleet_seen:
            out.update({
                "fleet_replicas": int(self.fleet_replicas.value),
                "fleet_replica_losses": int(self.fleet_losses.value),
                "fleet_migrations": int(self.fleet_migrations.value),
                "route_affinity_hits": int(self.route_affinity_hits.value),
                "fleet_scale_outs": int(self.fleet_scale_outs.value),
                "fleet_retired": int(self.fleet_retired.value),
                "fleet_handoffs": int(self.fleet_handoffs.value),
                "route_alert_demotions": int(
                    self.route_alert_demotions.value),
            })
        if self._pools_seen:
            out["pools"] = {
                pool: {
                    "replicas": int(self._pool_gauge(
                        "serve_pool_replicas", pool).value),
                    "queue_depth": int(self._pool_gauge(
                        "serve_pool_queue_depth", pool).value),
                    "slots_active": int(self._pool_gauge(
                        "serve_pool_slots_active", pool).value),
                } for pool in sorted(self._pool_names)}
        if self._host_seen:
            out.update({
                "host_blocks": int(self.host_blocks.value),
                "host_bytes_resident": int(self.host_bytes_resident.value),
                "host_inflight_blocks": int(self.host_inflight.value),
                "host_demotes": int(self._host_counters[
                    "host_demotes_total"].value),
                "host_promotes": int(self._host_counters[
                    "host_promotes_total"].value),
                "host_evictions": int(self._host_counters[
                    "host_evictions_total"].value),
                "host_prefetch_hits": int(self._host_counters[
                    "host_prefetch_hits_total"].value),
                "host_prefetch_misses": int(self._host_counters[
                    "host_prefetch_misses_total"].value),
                "host_transfer_bytes": int(self._host_counters[
                    "host_transfer_bytes_total"].value),
            })
        if self._adapter_seen:
            out.update({
                "adapter_resident_bytes": int(
                    self.adapter_resident_bytes.value),
                "adapter_swaps": int(self.adapter_swaps.value),
                "route_adapter_affinity_hits": int(
                    self.route_adapter_hits.value),
            })
            if self._adapter_names:
                out["per_adapter_completed"] = {
                    a: int(self.registry.counter(
                        "serve_class_adapter",
                        labels={"class": a}).value)
                    for a in sorted(self._adapter_names)}
        if self._drift_seen:
            out["kv_bytes_predicted"] = int(self.kv_bytes_predicted.value)
            out["kv_drift_bytes"] = int(self.kv_drift_bytes.value)
        if self._classes:
            out["per_class"] = {cls: self.class_summary(cls)
                                for cls in sorted(self._classes)}
        if self._paged_seen:
            out.update({
                "blocks_total": int(self.blocks_total.value),
                "blocks_in_use": int(self.blocks_in_use.value),
                "blocks_cached": int(self.blocks_cached.value),
                "kv_bytes_resident": int(self.kv_bytes_resident.value),
                "prefix_hit_blocks": int(
                    self._pool_counters["prefix_hit_blocks_total"].value),
                "cow_copies": int(
                    self._pool_counters["cow_copies_total"].value),
                "block_evictions": int(
                    self._pool_counters["evictions_total"].value),
                "prefill_chunk_ms_p50": r3(
                    self.prefill_chunk_ms.quantile(0.5)),
                "prefill_chunk_ms_p95": r3(
                    self.prefill_chunk_ms.quantile(0.95)),
            })
        return out

    def emit(self, extra: dict | None = None) -> dict | None:
        """Append one ``kind: "serve"`` JSONL record and rewrite the
        Prometheus exposition into ``outdir`` (no-op without one)."""
        if not self.outdir:
            return None
        rec = {"kind": "serve", **self.summary(), **(extra or {})}
        rec = append_jsonl(os.path.join(self.outdir, METRICS_FILE), rec)
        with open(os.path.join(self.outdir, PROM_FILE), "w") as f:
            f.write(self.registry.prometheus_text())
        return rec
