"""Reference-compatible CLI and process bootstrap.

Drop-in replacement for the reference's ``__main__`` block
(``/root/reference/simple_distributed.py:138-186``): the same flags launch TPU
hosts instead of RPC processes —

    python -m simple_distributed_machine_learning_tpu.cli --rank=0 --world_size=2 \
        --master_addr=10.0.0.1 --master_port=29500

Flag mapping (north star, BASELINE.json): ``--rank`` → process_id,
``--world_size`` → num_processes, ``--master_addr``/``--master_port`` →
coordinator address for ``jax.distributed.initialize``; ``--interface`` is
accepted for compatibility (the reference exports it as GLOO/TP_SOCKET_IFNAME,
``:164-165``; ICI needs no ifname pinning).

Semantic shift (MPMD → SPMD): in the reference, rank 0 runs the whole trainer
and other ranks idle serving RPCs (``:176-184``). Here every rank runs the
same program on the same data; sharding places each pipeline stage's compute
on its owning devices, and only process 0 prints. There is no shutdown
barrier to call — collectives in the compiled step are the synchronization.

Extensions beyond the reference CLI (hyperparameters surfaced as flags,
model/topology selection) are listed under "framework options".
"""

from __future__ import annotations

import argparse

import jax


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Distributed Machine Learning (TPU-native)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    # -- reference-compatible flags (simple_distributed.py:144-156) --
    p.add_argument('--rank', type=int, metavar='R',
                   help="Number of rank")
    p.add_argument('--world_size', type=int, default=1, metavar='N',
                   help="Number of workers (processes)")
    p.add_argument('--interface', type=str, default="eth0", metavar='I',
                   help="Accepted for reference compatibility; unused on TPU "
                        "(ICI/DCN need no socket ifname pinning)")
    p.add_argument('--master_addr', type=str, default="localhost", metavar='MA',
                   help="Address of the coordinator (master)")
    p.add_argument('--master_port', type=str, default="29500", metavar='MP',
                   help="Port the coordinator is listening on")
    # -- framework options --
    g = p.add_argument_group("framework options")
    g.add_argument('--model', choices=("lenet", "mlp", "gpt"), default="lenet",
                   help="model family (lenet = the reference's workload)")
    g.add_argument('--stages', type=int, default=None,
                   help="pipeline stages (default: 2 if enough devices else 1)")
    g.add_argument('--microbatches', type=int, default=1,
                   help="GPipe microbatches per step (1 = reference's "
                        "sequential schedule)")
    g.add_argument('--schedule', choices=("gpipe", "1f1b"), default="gpipe",
                   help="pipeline schedule: gpipe = scanned fwd sweep + "
                        "autodiff backward (activation memory grows with "
                        "microbatches); 1f1b = one-forward-one-backward "
                        "(PipeDream-flush) with recompute (memory bounded by "
                        "the stage count; composes with --dp/--tp/--sp/--ep)")
    g.add_argument('--dp', type=int, default=1,
                   help="data-parallel mesh width (batch must divide by "
                        "dp * microbatches)")
    g.add_argument('--tp', type=int, default=1,
                   help="tensor-parallel width: for --model=mlp each stage "
                        "becomes a column->row sharded pair (needs exactly "
                        "2*stages layers in --mlp-dims, hidden widths "
                        "divisible by tp); for --model=gpt every block's "
                        "QKV/O and MLP shard Megatron-style over a 'model' "
                        "mesh axis (n_heads and 4*d_model divisible by tp)")
    g.add_argument('--overlap', choices=("none", "ring"), default="none",
                   help="collective schedule for the tensor-parallel "
                        "all-reduces and the expert-parallel dispatch: none "
                        "= monolithic psum/all_to_all (the chip blocks for "
                        "the whole collective); ring = ppermute-chunked "
                        "latency-hiding collective matmuls "
                        "(parallel/overlap.py) — each chunk's ICI hop hides "
                        "under another chunk's compute, same losses to "
                        "float tolerance")
    g.add_argument('--epochs', type=int, default=10)
    g.add_argument('--batch-size', type=int, default=60)
    g.add_argument('--lr', type=float, default=0.1)
    g.add_argument('--momentum', type=float, default=0.5)
    g.add_argument('--optimizer', choices=("sgd", "adamw"), default="sgd",
                   help="sgd = the reference's SGD(momentum); adamw = "
                        "torch-semantics decoupled weight decay")
    g.add_argument('--weight-decay', type=float, default=0.01,
                   help="weight decay for --optimizer adamw")
    g.add_argument('--lr-schedule',
                   choices=("constant", "cosine", "warmup-cosine", "step"),
                   default="constant",
                   help="learning-rate schedule over the whole run "
                        "(epochs * batches steps); evaluated inside the "
                        "compiled step")
    g.add_argument('--warmup-steps', type=int, default=0,
                   help="linear-warmup steps for --lr-schedule warmup-cosine")
    g.add_argument('--lr-step-size', type=int, default=100,
                   help="steps between decays for --lr-schedule step")
    g.add_argument('--lr-gamma', type=float, default=0.1,
                   help="decay factor for --lr-schedule step")
    g.add_argument('--clip-norm', type=float, default=0.0,
                   help="clip gradients to this global L2 norm before the "
                        "update (torch clip_grad_norm_ semantics; 0 "
                        "disables); replication-corrected on tp/ep meshes")
    g.add_argument('--zero1', action='store_true',
                   help="ZeRO-1: shard optimizer state over the data axis "
                        "(cuts its memory by dp; GSPMD inserts the "
                        "collectives)")
    g.add_argument('--data-root', type=str, default="data",
                   help="directory with MNIST IDX files (synthetic fallback "
                        "if absent)")
    g.add_argument('--seed', type=int, default=0)
    g.add_argument('--shuffle', action='store_true',
                   help="seeded per-epoch shuffle of the train set (off by "
                        "default: the reference trains in fixed order)")
    g.add_argument('--mlp-dims', type=str, default="784,512,10",
                   help="comma-separated layer widths for --model=mlp")
    g.add_argument('--checkpoint-dir', type=str, default=None,
                   help="write a checkpoint after every epoch and auto-resume "
                        "from it on restart (the reference loses all progress "
                        "on a crash)")
    g.add_argument('--no-resume', action='store_true',
                   help="with --checkpoint-dir: start fresh, ignore an "
                        "existing checkpoint")
    g.add_argument('--async-checkpoint', action='store_true',
                   help="overlap the checkpoint file write with the next "
                        "epoch (the sharded gather stays synchronous)")
    g.add_argument('--eval-only', action='store_true',
                   help="skip training: evaluate the checkpoint-restored "
                        "(or fresh-initialized) params on the test set and "
                        "exit")
    g.add_argument('--experts', type=int, default=0,
                   help="for --model=gpt: replace each block's MLP with a "
                        "top-2-routed mixture of this many experts (0 = dense)")
    g.add_argument('--sp', type=int, default=1,
                   help="sequence-parallel width for --model=gpt: shards the "
                        "token axis over a 'seq' mesh axis (requires "
                        "--attn ring or ulysses)")
    g.add_argument('--ep', type=int, default=1,
                   help="expert-parallel width for --model=gpt with "
                        "--experts: shards expert weights over an 'expert' "
                        "mesh axis with all-to-all dispatch")
    g.add_argument('--generate', type=int, default=0, metavar="N",
                   help="for --model=gpt: after training, decode N tokens "
                        "from the trained model (KV-cache, straight from "
                        "the live param buffer) and print them on rank 0 — "
                        "with --text-corpus, decoded bytes as text")
    g.add_argument('--serve-sim', type=int, default=0, metavar="N",
                   help="for --model=gpt: skip training and serve N "
                        "simulated requests through the continuous-batching "
                        "inference engine (serve/): seeded Poisson arrivals, "
                        "FCFS admission into a block-table paged KV-cache "
                        "pool (prefix sharing + copy-on-write + chunked "
                        "prefill), EOS/budget retirement freeing memory "
                        "mid-flight; "
                        "params restore from --checkpoint-dir when a "
                        "checkpoint exists, else fresh init; TTFT/TPOT and "
                        "occupancy metrics land in --telemetry-dir")
    g.add_argument('--serve-rate', type=float, default=8.0, metavar="R",
                   help="with --serve-sim: mean request arrival rate "
                        "(req/s) of the open-loop Poisson trace")
    g.add_argument('--serve-slots', type=int, default=4, metavar="S",
                   help="with --serve-sim: KV-cache pool slots (the "
                        "continuous batch's max occupancy)")
    g.add_argument('--serve-max-new', type=int, default=16, metavar="T",
                   help="with --serve-sim: tokens generated per request "
                        "(EOS may retire a request earlier)")
    g.add_argument('--serve-block-size', type=int, default=16, metavar="B",
                   help="with --serve-sim: positions per K/V block of the "
                        "paged cache pool (serve/slots.py PagedKVPool) — "
                        "smaller blocks waste less tail memory and share "
                        "prefixes at finer grain, larger blocks gather "
                        "fewer pages per attention step")
    g.add_argument('--serve-prefill-chunk', type=int, default=0, metavar="C",
                   help="with --serve-sim: prompt positions prefilled per "
                        "engine tick (chunked prefill — each tick runs at "
                        "most one chunk, then the batched decode step, so "
                        "a long prompt cannot stall in-flight decodes); "
                        "0 = whole prompt in one chunk")
    g.add_argument('--serve-shared-prefix', type=int, default=0, metavar="N",
                   help="with --serve-sim: prepend ONE seeded common "
                        "N-token prefix to every simulated prompt (the "
                        "system-prompt case) — the paged pool serves the "
                        "prefix from shared physical blocks, copy-on-write "
                        "at divergence")
    g.add_argument('--serve-tp', type=int, default=1, metavar="T",
                   help="with --serve-sim: tensor-parallel width of the "
                        "serving programs — every tick runs head-sharded "
                        "QKV/O + collective-matmul MLP over T chips of the "
                        "mesh's model axis and the K/V pool shards its "
                        "head axis, so per-chip KV bytes drop by T "
                        "(needs T devices; T must divide n_heads)")
    g.add_argument('--serve-spec-k', type=int, default=0, metavar="K",
                   help="with --serve-sim: speculative decoding — a small "
                        "draft model (half the target's layers, fresh "
                        "init) proposes K tokens per slot per tick and "
                        "the target verifies all K in ONE batched step, "
                        "emitting 1..K tokens; greedy streams stay "
                        "bit-exact vs solo decode. 0 = plain one-token "
                        "decode; K >= 2 enables the draft/verify tick")
    g.add_argument('--serve-chaos', type=str, default=None, metavar='SPEC',
                   help="with --serve-sim: serve under a deterministic "
                        "fault schedule through the crash-restartable "
                        "serve supervisor (serve/supervisor.py) — on an "
                        "injected engine-crash/wedged-device the engine "
                        "is rebuilt and every in-flight request recovers "
                        "BIT-EXACT from the fsync'd request journal "
                        "(resume from the last journaled token, key "
                        "stream intact). Same grammar as --chaos, e.g. "
                        "'engine-crash@serve.tick=5'; sites serve.tick "
                        "and serve.admit")
    g.add_argument('--serve-deadline-ms', type=float, default=0.0,
                   metavar='D',
                   help="with --serve-sim: per-request completion "
                        "deadline in ms, enforced by the serve "
                        "supervisor at tick boundaries — an expired "
                        "request is SHED with a structured rejection and "
                        "its slot/block budget refunded (0 = no "
                        "deadline). The run exits 0 when every request "
                        "either completed or was structurally shed")
    g.add_argument('--serve-max-restarts', type=int, default=3,
                   help="with --serve-chaos: engine-rebuild budget before "
                        "the serve supervisor fails the run loudly")
    g.add_argument('--serve-replicas', type=int, default=0, metavar='N',
                   help="with --serve-sim: serve through a FLEET of N "
                        "supervised engine replicas behind a health-aware "
                        "router (serve/fleet.py) — prefix-cache-affinity "
                        "routing, per-replica journals "
                        "(journal-r<i>.jsonl), and journal-backed "
                        "cross-replica migration: killing a whole replica "
                        "(--serve-chaos 'replica-kill@fleet.tick=5') "
                        "re-admits its in-flight requests onto the "
                        "survivors bit-exact from its journal alone. "
                        "0 = the single-engine paths above")
    g.add_argument('--serve-route',
                   choices=("affinity", "least-loaded", "round-robin"),
                   default="affinity",
                   help="with --serve-replicas: routing policy — "
                        "affinity routes to the replica whose paged pool "
                        "already holds the prompt's registered prefix "
                        "blocks (least-loaded fallback); least-loaded "
                        "orders by queue depth then occupancy; "
                        "round-robin is the affinity-blind baseline")
    g.add_argument('--serve-autoscale', type=str, default=None,
                   metavar='MIN,MAX',
                   help="with --serve-replicas: enable the fleet "
                        "autoscaler between MIN and MAX replicas — "
                        "scale-out on sustained queue backlog (or paged "
                        "KV residency), drain-then-retire on idle "
                        "(serve/fleet.py::AutoscalePolicy)")
    g.add_argument('--serve-prefill-replicas', type=int, default=0,
                   metavar='N',
                   help="with --serve-replicas: DISAGGREGATE the fleet — "
                        "the first N replicas form the prefill pool (new "
                        "requests board there only) and the rest the "
                        "decode pool; every request hands off at "
                        "end-of-prefill by the journal snap/adopt move "
                        "(serve/fleet.py). Mutually exclusive with "
                        "--serve-autoscale")
    g.add_argument('--serve-host-blocks', type=int, default=0, metavar='N',
                   help="with --serve-sim: host-RAM offload tier of N "
                        "blocks per replica behind the paged KV pool — "
                        "LRU-evicted prefix blocks demote to host instead "
                        "of dying, and a router affinity hit on a "
                        "host-resident prefix starts the async prefetch "
                        "upload at routing time (serve/slots.py)")
    g.add_argument('--serve-prefetch-ticks', type=int, default=1,
                   metavar='T',
                   help="with --serve-host-blocks: engine ticks one "
                        "host->HBM prefetch upload takes (the modeled "
                        "PCIe/DMA latency; boarding blocks until the "
                        "upload lands)")
    g.add_argument('--serve-adapters', type=int, default=0, metavar='N',
                   help="with --serve-sim: multi-tenant LoRA serving "
                        "(serve/adapters.py) — register N per-tenant "
                        "low-rank adapters (tenant-0..tenant-N-1) over "
                        "the SHARED base weights and split arrivals "
                        "evenly across them; each decode tick gathers "
                        "per-slot adapter rows from one device-resident "
                        "bank, so ONE compiled program serves any tenant "
                        "mix (no per-tenant retrace, no merged weight "
                        "copies). With --serve-replicas the router "
                        "prefers a replica where the request's adapter "
                        "is already resident (adapter-affinity)")
    g.add_argument('--serve-adapter-rank', type=int, default=4,
                   metavar='R',
                   help="with --serve-adapters: the low-rank dimension r "
                        "of every adapter's A/B factors (bank HBM scales "
                        "linearly with r; see models/lora.py bank_bytes)")
    g.add_argument('--serve-trace', action='store_true',
                   help="with --serve-sim/--scenario and --telemetry-dir: "
                        "request-scoped tracing (serve/tracing.py) — a "
                        "per-rid async span timeline (queue wait, prefill "
                        "chunks, decode/spec ticks, preempt/resume, crash "
                        "re-admission) written as serve_trace*.json "
                        "(chrome://tracing / Perfetto) plus a "
                        "request_timeline*.jsonl the report CLI reads "
                        "(python -m ...telemetry.report). Off by default: "
                        "the hot path pays nothing when disabled, and "
                        "spans join across supervisor restarts (the "
                        "journal rid is the trace id)")
    g.add_argument('--text-corpus', default=None, metavar="PATH",
                   help="for --model=gpt: train on the BYTES of this local "
                        "file (vocab=256, next-byte LM, contiguous "
                        "train/test split) instead of the synthetic Markov "
                        "stream — the reference's real-data-first sourcing "
                        "mapped to a zero-egress environment")
    g.add_argument('--attn', choices=("dense", "flash", "ring", "ulysses"),
                   default="dense",
                   help="attention implementation for --model=gpt (flash = "
                        "Pallas fused kernel; ring/ulysses = sequence-"
                        "parallel collectives, used with --sp)")
    g.add_argument('--flash-blocks', type=str, default=None, metavar='Q,K',
                   help="with --attn flash: kernel block sizes, e.g. "
                        "512,512 (defaults 128,128; tune with "
                        "benchmarks/flash_tune.py)")
    g.add_argument('--bf16', action='store_true',
                   help="bfloat16 compute (float32 master params and loss): "
                        "doubles MXU throughput, halves HBM traffic")
    g.add_argument('--remat', action='store_true',
                   help="rematerialize stage activations in backward "
                        "(jax.checkpoint): trades FLOPs for memory")
    g.add_argument('--metrics-json', type=str, default=None, metavar='PATH',
                   help='append one JSON line of metrics per epoch (epoch, '
                        'step, train_loss, samples_per_sec, eval_loss, '
                        'accuracy, plus the raw correct/n_eval counts) — '
                        'the machine-readable counterpart of the '
                        'reference-format console output')
    g.add_argument('--profile', type=str, default=None, metavar='DIR',
                   help="capture an XProf/TensorBoard trace of the whole run "
                        "into DIR")
    g.add_argument('--telemetry-dir', type=str, default=None, metavar='DIR',
                   help="structured run telemetry (telemetry/): per-epoch "
                        "metrics.jsonl (step-latency p50/p95, examples/sec "
                        "and tokens/sec, live-array bytes, pipeline bubble "
                        "fraction, expected ICI bytes/step), trace.json "
                        "(Chrome-trace host spans for feed/step/eval — open "
                        "in chrome://tracing or ui.perfetto.dev, no XProf "
                        "needed) and metrics.prom (Prometheus text "
                        "exposition) written into DIR")
    g.add_argument('--telemetry-every', type=int, default=1, metavar='N',
                   help="with --telemetry-dir: fence the device and sample "
                        "step latency every Nth step; 1 = exact per-step "
                        "latency, larger N keeps async dispatch overlapped "
                        "and attributes each fenced window to its N steps")
    g.add_argument('--max-steps-per-epoch', type=int, default=None,
                   metavar='N',
                   help="cap every training epoch at N batches (full "
                        "epochs by default) — the knob short CI runs and "
                        "the --chaos smoke use to keep multi-epoch runs "
                        "cheap without collapsing them to one epoch like "
                        "--dryrun does")
    g.add_argument('--sentinel', action='store_true',
                   help="self-healing training (resilience/sentinel.py): "
                        "check every step's loss/grad-norm for NaN/Inf and "
                        "EWMA loss spikes, keep a bounded in-memory ring of "
                        "host snapshots, and on an anomaly roll back to the "
                        "newest pre-anomaly snapshot, quarantine the "
                        "offending batch (appended to quarantine.jsonl "
                        "under --checkpoint-dir and deterministically "
                        "skipped from then on) and replay forward — "
                        "bit-exact vs a run that never saw the fault. "
                        "Repeated anomalies escalate to the --chaos elastic "
                        "supervisor (full disk restore). Also arms the "
                        "numeric fault sites nan-grad@train.grad, "
                        "corrupt-batch@data.batch, loss-spike@train.step "
                        "for --chaos drills")
    g.add_argument('--sentinel-window', type=int, default=16, metavar='W',
                   help="with --sentinel: EWMA horizon for the loss-spike "
                        "detector AND the escalation window (more than "
                        "ring-size anomalies within W steps raise to the "
                        "supervisor)")
    g.add_argument('--sentinel-snapshot-every', type=int, default=4,
                   metavar='K',
                   help="with --sentinel: steps between in-memory snapshot-"
                        "ring entries (rollback replays at most K-1 steps; "
                        "smaller K = cheaper recovery, more frequent host "
                        "gathers)")
    g.add_argument('--chaos', type=str, default=None, metavar='SPEC',
                   help="resilience drill (resilience/): train under a "
                        "deterministic fault-injection schedule with the "
                        "elastic checkpoint-restart supervisor — on an "
                        "injected host-kill (or other recoverable fault) "
                        "the run restores the latest VALID checkpoint from "
                        "--checkpoint-dir (checksum-verified manifest), "
                        "repacks it onto the surviving stage count and "
                        "resumes. SPEC grammar: 'kind@site[=step]"
                        "[,key=val...]' entries joined by ';' — e.g. "
                        "'host-kill@train.step=6'; kinds: host-kill, "
                        "frozen-peer, slow-tick, ckpt-write-crash, "
                        "wedged-device. Requires --checkpoint-dir; "
                        "--model mlp or gpt")
    g.add_argument('--chaos-stages', type=str, default=None, metavar='S1,S2',
                   help="with --chaos: the stage-count ladder the "
                        "supervisor falls back through on host/peer loss "
                        "(largest first, e.g. 2,1 = restart-and-repack "
                        "onto 1 stage after losing a host at 2); default: "
                        "stay at the launch stage count")
    g.add_argument('--chaos-max-restarts', type=int, default=3,
                   help="with --chaos: recoverable-failure restart budget "
                        "before the run FAILS loudly")
    g.add_argument('--scenario', type=str, default=None, metavar='NAME',
                   help="run one SLO-gated serving scenario "
                        "(resilience/scenarios.py): deterministic bursty/"
                        "diurnal/multi-tenant traffic with per-class "
                        "TTFT/TPOT targets through the continuous-batching "
                        "engine on a virtual clock; priority scheduling "
                        "with prefill preemption protects interactive "
                        "traffic. Exits nonzero unless every class attains "
                        "its SLOs and every request completes; per-class "
                        "attainment lands in --telemetry-dir. NAME 'list' "
                        "prints the catalog")
    g.add_argument('--dryrun', type=int, default=0, metavar='N',
                   help="smoke mode: train only N batches of a single epoch "
                        "(then the normal eval) and exit — the cheap "
                        "end-to-end check CI pairs with --telemetry-dir")
    g.add_argument('--lint', action='store_true',
                   help="static-analysis preflight (analysis/): trace the "
                        "exact compiled steps this run is about to execute "
                        "and lint them before any device executes one — "
                        "train+eval steps for a training run (ppermute "
                        "deadlocks, unreduced gradients, mesh-axis "
                        "validity, dtype drift, donation hazards); the "
                        "whole serving-program registry for --serve-sim "
                        "(KV scatter-bounds, donated-buffer flow through "
                        "the tick, retrace policy, HBM bytes/tick); abort "
                        "on ERROR findings")
    g.add_argument('--lint-only', action='store_true',
                   help="run the --lint preflight and exit without "
                        "training/serving (exit 0 clean, 2 on ERROR "
                        "findings)")
    g.add_argument('--peer-timeout', type=float, default=60.0,
                   help="multi-process dead-peer watchdog: abort with a "
                        "nonzero exit if a peer crashes or stops "
                        "heartbeating for this many seconds (0 disables; "
                        "the reference hangs forever on a dead peer)")
    g.add_argument('--heartbeat-port', type=int, default=None,
                   help="TCP port for the dead-peer watchdog "
                        "(default: master_port + 1)")
    return p


def _apply_env_platform() -> None:
    """Honor JAX_PLATFORMS / xla_force_host_platform_device_count even when a
    sitecustomize imported jax at interpreter startup (which latches the
    platform choice before env vars are read — seen with preloaded TPU
    plugins). Re-applies both through the live config; harmless no-op if
    backends are already initialized."""
    import os
    import re

    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        jax.config.update("jax_platforms", plat)
        m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        if m and plat == "cpu":
            from simple_distributed_machine_learning_tpu.parallel.compat import (
                set_host_device_count,
            )
            set_host_device_count(int(m.group(1)))
    except RuntimeError:
        pass  # backends already up: keep whatever exists


def main(argv: list[str] | None = None) -> None:
    _apply_env_platform()
    args = build_parser().parse_args(argv)
    assert args.rank is not None or args.world_size == 1, \
        "Must provide rank argument."  # reference :160

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        bootstrap_distributed,
    )

    bootstrap_distributed(args.rank or 0, args.world_size,
                          args.master_addr, args.master_port)

    watchdog = None
    if args.world_size > 1 and args.peer_timeout > 0:
        from simple_distributed_machine_learning_tpu.utils.failure import (
            spawn_watchdog,
        )
        hb_port = (args.heartbeat_port if args.heartbeat_port is not None
                   else int(args.master_port) + 1)
        # a SUBPROCESS, not threads: in-process watchdog threads freeze when
        # the main thread blocks in a native collective holding the GIL
        # (utils/failure.py module docstring)
        watchdog = spawn_watchdog(
            args.rank or 0, args.world_size, args.master_addr, hb_port,
            timeout=args.peer_timeout)

    try:
        _dispatch(args)
    except BaseException:
        # crash path: kill the monitor abruptly (no goodbye — peers must
        # read the disconnect as a failure) and disarm its kill_parent, so
        # a programmatic main() caller that catches this exception is not
        # SIGKILLed by an orphaned monitor minutes later
        if watchdog is not None:
            watchdog.abort()
        raise
    # goodbye ONLY on success
    if watchdog is not None:
        watchdog.stop()


def _dispatch(args) -> None:
    n_dev = len(jax.devices())
    n_stages = args.stages if args.stages is not None else (2 if n_dev >= 2 else 1)

    key = jax.random.key(args.seed)
    if args.dryrun < 0:
        raise SystemExit(f"--dryrun needs a non-negative step count, got "
                         f"{args.dryrun}")
    if args.tp > 1 and args.model not in ("mlp", "gpt"):
        raise SystemExit("--tp is only supported with --model=mlp or gpt")
    if args.sp > 1 and args.model != "gpt":
        raise SystemExit("--sp is only supported with --model=gpt")
    if args.ep > 1 and (args.model != "gpt" or args.experts < 1):
        raise SystemExit("--ep needs --model=gpt with --experts > 0")
    if args.generate > 0 and args.model != "gpt":
        raise SystemExit("--generate is only supported with --model=gpt")
    if args.max_steps_per_epoch is not None and args.max_steps_per_epoch < 1:
        raise SystemExit(f"--max-steps-per-epoch must be >= 1, got "
                         f"{args.max_steps_per_epoch}")
    if args.sentinel_window < 2:
        raise SystemExit(f"--sentinel-window must be >= 2, got "
                         f"{args.sentinel_window}")
    if args.sentinel_snapshot_every < 1:
        raise SystemExit(f"--sentinel-snapshot-every must be >= 1, got "
                         f"{args.sentinel_snapshot_every}")
    if args.scenario is not None:
        _run_scenario(args, n_stages, key)
        return
    if args.chaos is not None:
        _run_chaos(args, n_stages, key)
        return
    if args.serve_sim > 0:
        if args.model != "gpt":
            raise SystemExit("--serve-sim is only supported with "
                             "--model=gpt")
        if args.experts > 0 or args.sp > 1 or args.tp > 1 or args.ep > 1:
            raise SystemExit(
                "--serve-sim serves a dense single-device build (the "
                "make_cached_decoder restrictions): drop "
                "--experts/--sp/--tp/--ep")
        _run_serve(args, n_stages, key)
        return
    if args.model == "gpt":
        _run_gpt(args, n_stages, key)
        return
    if args.model == "lenet":
        from simple_distributed_machine_learning_tpu.models.lenet import (
            make_lenet_stages,
        )
        stages, wire_dim, out_dim = make_lenet_stages(key, n_stages)
        in_is_image = True
    elif args.tp > 1:
        from simple_distributed_machine_learning_tpu.parallel.tensor import (
            make_mlp_tp_stages,
        )
        dims = [int(d) for d in args.mlp_dims.split(",")]
        stages, wire_dim, out_dim = make_mlp_tp_stages(key, dims, n_stages,
                                                       args.tp,
                                                       overlap=args.overlap)
        in_is_image = False
    else:
        from simple_distributed_machine_learning_tpu.models.mlp import (
            make_mlp_stages,
        )
        dims = [int(d) for d in args.mlp_dims.split(",")]
        stages, wire_dim, out_dim = make_mlp_stages(key, dims, n_stages)
        in_is_image = False

    from simple_distributed_machine_learning_tpu.data.mnist import (
        Dataset,
        load_mnist,
    )
    train_ds, test_ds = load_mnist(args.data_root)
    if not in_is_image:
        train_ds = Dataset(train_ds.x.reshape(len(train_ds.x), -1), train_ds.y)
        test_ds = Dataset(test_ds.x.reshape(len(test_ds.x), -1), test_ds.y)

    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
    from simple_distributed_machine_learning_tpu.train.trainer import (
        Trainer,
    )

    mesh = make_mesh(n_stages=n_stages, n_data=args.dp, n_model=args.tp)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim,
                    n_microbatches=args.microbatches,
                    compute_dtype=_compute_dtype(args), remat=args.remat,
                    schedule=args.schedule, overlap=args.overlap)
    config = _train_config(args)
    _fit(args, Trainer(pipe, train_ds, test_ds, config,
                       opt=_make_opt(args, _total_steps(args, train_ds),
                                     pipe),
                       telemetry=_telemetry(args)))


def _compute_dtype(args):
    if not args.bf16:
        return None
    import jax.numpy as jnp
    return jnp.bfloat16


def _train_config(args):
    from simple_distributed_machine_learning_tpu.train.trainer import (
        TrainConfig,
    )
    return TrainConfig(
        # --dryrun N: N batches of one epoch, the cheap end-to-end smoke;
        # --max-steps-per-epoch caps every epoch without collapsing to one
        epochs=1 if args.dryrun else args.epochs,
        max_steps_per_epoch=args.dryrun or args.max_steps_per_epoch,
        batch_size=args.batch_size,
        learning_rate=args.lr, momentum=args.momentum,
        seed=args.seed, checkpoint_dir=args.checkpoint_dir,
        resume=not args.no_resume, zero1=args.zero1,
        async_checkpoint=args.async_checkpoint,
        shuffle=args.shuffle,
        metrics_json=args.metrics_json,
        sentinel=args.sentinel,
        sentinel_window=args.sentinel_window,
        sentinel_snapshot_every=args.sentinel_snapshot_every)


def _telemetry(args):
    if not args.telemetry_dir:
        return None
    if args.telemetry_every < 1:
        raise SystemExit(f"--telemetry-every must be >= 1, got "
                         f"{args.telemetry_every}")
    from simple_distributed_machine_learning_tpu.telemetry import Telemetry
    return Telemetry(args.telemetry_dir, every=args.telemetry_every)


def _make_opt(args, total_steps: int, pipe=None):
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        adamw,
        clip_by_global_norm,
        sgd,
    )
    from simple_distributed_machine_learning_tpu.train import schedules

    if args.lr_schedule == "cosine":
        lr = schedules.cosine(args.lr, total_steps)
    elif args.lr_schedule == "warmup-cosine":
        lr = schedules.warmup_cosine(args.lr, args.warmup_steps, total_steps)
    elif args.lr_schedule == "step":
        lr = schedules.step_decay(args.lr, args.lr_step_size, args.lr_gamma)
    else:
        lr = args.lr
    if args.optimizer == "adamw":
        opt = adamw(lr, weight_decay=args.weight_decay)
    else:
        opt = sgd(lr, args.momentum)
    if args.clip_norm > 0:
        weights = pipe.replication_weights() if pipe is not None else None
        opt = clip_by_global_norm(opt, args.clip_norm, weights)
    return opt


def _total_steps(args, train_ds) -> int:
    """The LR-schedule horizon: steps the run will actually execute —
    honoring --max-steps-per-epoch, so a capped run's cosine/warmup
    schedule sweeps its full range instead of idling at the initial LR."""
    per_epoch = max(1, -(-len(train_ds.x) // args.batch_size))
    if args.max_steps_per_epoch is not None:
        per_epoch = min(per_epoch, args.max_steps_per_epoch)
    return args.epochs * per_epoch


def _fit(args, trainer) -> None:
    if args.lint or args.lint_only:
        # the preflight gate: lint the EXACT compiled steps this trainer is
        # about to execute (same pipeline, optimizer, donation and batch
        # shapes) — zero FLOPs, no device buffers touched
        from simple_distributed_machine_learning_tpu.analysis.preflight import (
            lint_trainer,
        )
        report = lint_trainer(trainer)
        trainer._print(report.format(costs=True))
        if not report.ok():
            raise SystemExit(2)
        trainer._print("| --lint: preflight clean")
        if args.lint_only:
            return
    if args.eval_only:
        # evaluate the restored (or fresh-init, if no checkpoint) params
        # without training — the companion to --checkpoint-dir resume
        if args.checkpoint_dir and trainer.start_epoch == 1:
            trainer._print("| --eval-only: no checkpoint found, evaluating "
                           "fresh-initialized params")
        trainer.evaluate()
        if trainer.telemetry is not None:
            trainer.telemetry.close()    # eval spans -> trace.json
        return
    # graceful preemption: SIGTERM/SIGINT finish the in-flight step, write
    # a synchronous checkpoint carrying the mid-epoch data cursor, flush
    # the quarantine journal + telemetry and exit 0 — the training mirror
    # of the --serve-sim handler (a rollout must not look like a fault)
    import signal

    def _on_signal(signum, frame):
        trainer.request_stop(signum)

    old_handlers = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            old_handlers[s] = signal.signal(s, _on_signal)
    except ValueError:
        old_handlers = {}              # not the main thread: no handlers
    try:
        if args.profile:
            from simple_distributed_machine_learning_tpu.utils.profiler import (
                trace,
            )
            with trace(args.profile):
                trainer.fit()
        else:
            trainer.fit()
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)
    stats = trainer.sentinel_stats()
    if stats is not None:
        trainer._print(
            f"| sentinel: absorbed {stats['anomalies']} anomal"
            f"{'y' if stats['anomalies'] == 1 else 'ies'} "
            f"({stats['rollbacks']} rollback(s), "
            f"{stats['quarantined_batches']} quarantined batch(es), "
            f"ring {stats['snapshot_ring_bytes']} bytes)")
    if trainer.preempted:
        trainer._print(
            "| train: graceful shutdown complete — "
            + ("resume with the same --checkpoint-dir to continue "
               "bit-exact" if trainer.preempt_persisted
               else "no --checkpoint-dir was configured, so the "
               "interrupted progress was NOT persisted"))


def _run_gpt(args, n_stages: int, key) -> None:
    """--model gpt: tiny-GPT LM on a synthetic Markov token stream
    (BASELINE.json config 5), same trainer/console surface."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.data.text import synthetic_tokens
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
    from simple_distributed_machine_learning_tpu.train.trainer import (
        Trainer,
    )

    fb = {}
    if args.flash_blocks:
        if args.attn != "flash":
            raise SystemExit("--flash-blocks needs --attn flash")
        try:
            bq, bk = (int(v) for v in args.flash_blocks.split(","))
        except ValueError:
            raise SystemExit(
                f"--flash-blocks expects Q,K integers, got "
                f"{args.flash_blocks!r}") from None
        fb = {"flash_block_q": bq, "flash_block_k": bk}
    cfg = GPTConfig(vocab=256 if args.text_corpus else 128,
                    n_experts=args.experts,
                    moe_top_k=min(2, max(1, args.experts)),
                    attn_impl=args.attn, n_seq=args.sp,
                    n_expert_parallel=args.ep,
                    n_tensor_parallel=args.tp, overlap=args.overlap, **fb)
    stages, wire_dim, out_shape = make_gpt_stages(key, cfg, n_stages)
    def as_ds(x, y):
        return Dataset(x.astype(np.float32), y)

    if args.text_corpus:
        # real data: next-byte LM over a local file (data/text.py)
        from simple_distributed_machine_learning_tpu.data.text import (
            byte_corpus,
        )
        tr, te = byte_corpus(args.text_corpus, cfg.seq_len)
        train_ds, test_ds = as_ds(*tr), as_ds(*te)
    else:
        # one Markov chain, disjoint train/test sequences (a different seed
        # would regenerate a different transition matrix — nothing would
        # transfer)
        all_data = synthetic_tokens(7000, cfg.seq_len, cfg.vocab,
                                    seed=args.seed)
        train_ds = as_ds(all_data.x[:6000], all_data.y[:6000])
        test_ds = as_ds(all_data.x[6000:], all_data.y[6000:])

    mesh = make_mesh(n_stages=n_stages, n_data=args.dp, n_model=args.tp,
                     n_seq=args.sp, n_expert=args.ep)
    pipe = Pipeline(stages, mesh, wire_dim, out_shape,
                    n_microbatches=args.microbatches,
                    compute_dtype=_compute_dtype(args), remat=args.remat,
                    schedule=args.schedule, overlap=args.overlap)
    config = _train_config(args)
    trainer = Trainer(pipe, train_ds, test_ds, config,
                      opt=_make_opt(args, _total_steps(args, train_ds),
                                    pipe),
                      telemetry=_telemetry(args))
    _fit(args, trainer)
    if args.generate > 0:
        _print_sample(args, trainer, cfg, test_ds)


def _run_serve(args, n_stages: int, key) -> None:
    """--serve-sim N: continuous-batching inference over a simulated
    open-loop Poisson trace (serve/). Params come from --checkpoint-dir
    when a checkpoint exists (the same build the training run wrote),
    otherwise fresh init; no training happens. Exits nonzero if any
    request fails to complete."""
    import os

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
        ServeMetrics,
        SimConfig,
        TrafficClass,
        simulate,
    )

    if args.serve_slots < 1:
        raise SystemExit(f"--serve-slots must be >= 1, got "
                         f"{args.serve_slots}")
    if args.serve_max_new < 1:
        raise SystemExit(f"--serve-max-new must be >= 1, got "
                         f"{args.serve_max_new}")
    if args.serve_block_size < 1:
        raise SystemExit(f"--serve-block-size must be >= 1, got "
                         f"{args.serve_block_size}")
    if args.serve_prefill_chunk < 0:
        raise SystemExit(f"--serve-prefill-chunk must be >= 1 (or 0 for "
                         f"whole-prompt chunks), got "
                         f"{args.serve_prefill_chunk}")
    if args.serve_shared_prefix < 0:
        raise SystemExit(f"--serve-shared-prefix must be >= 0, got "
                         f"{args.serve_shared_prefix}")
    if args.serve_tp < 1:
        raise SystemExit(f"--serve-tp must be >= 1, got {args.serve_tp}")
    if args.serve_spec_k == 1 or args.serve_spec_k < 0:
        raise SystemExit(f"--serve-spec-k must be 0 (plain decode) or "
                         f">= 2, got {args.serve_spec_k}")
    if args.serve_deadline_ms < 0:
        raise SystemExit(f"--serve-deadline-ms must be >= 0 (0 = none), "
                         f"got {args.serve_deadline_ms}")
    if args.serve_max_restarts < 0:
        raise SystemExit(f"--serve-max-restarts must be >= 0, got "
                         f"{args.serve_max_restarts}")
    if args.serve_replicas < 0:
        raise SystemExit(f"--serve-replicas must be >= 0 (0 = single "
                         f"engine), got {args.serve_replicas}")
    if args.serve_route != "affinity" and not args.serve_replicas:
        raise SystemExit("--serve-route needs --serve-replicas (a single "
                         "engine has nothing to route between)")
    autoscale = None
    if args.serve_autoscale:
        if not args.serve_replicas:
            raise SystemExit("--serve-autoscale needs --serve-replicas")
        from simple_distributed_machine_learning_tpu.serve import (
            AutoscalePolicy,
        )
        try:
            lo, hi = (int(v) for v in args.serve_autoscale.split(","))
            autoscale = AutoscalePolicy(min_replicas=lo, max_replicas=hi)
        except ValueError as e:
            raise SystemExit(f"bad --serve-autoscale (expected MIN,MAX "
                             f"integers): {e}") from None
        if not lo <= args.serve_replicas <= hi:
            raise SystemExit(
                f"--serve-replicas {args.serve_replicas} outside the "
                f"--serve-autoscale bounds [{lo}, {hi}]")
    if args.serve_prefill_replicas:
        if not args.serve_replicas:
            raise SystemExit("--serve-prefill-replicas needs "
                             "--serve-replicas (pools split a fleet)")
        if not 0 < args.serve_prefill_replicas < args.serve_replicas:
            raise SystemExit(
                f"--serve-prefill-replicas must leave at least one decode "
                f"replica (0 < N < {args.serve_replicas}), got "
                f"{args.serve_prefill_replicas}")
        if args.serve_autoscale:
            raise SystemExit("--serve-prefill-replicas and "
                             "--serve-autoscale are mutually exclusive "
                             "(the autoscaler assumes one symmetric pool)")
    if args.serve_host_blocks < 0:
        raise SystemExit(f"--serve-host-blocks must be >= 0 (0 = no host "
                         f"tier), got {args.serve_host_blocks}")
    if args.serve_adapters < 0:
        raise SystemExit(f"--serve-adapters must be >= 0 (0 = base model "
                         f"only), got {args.serve_adapters}")
    if args.serve_adapters and args.serve_adapter_rank < 1:
        raise SystemExit(f"--serve-adapter-rank must be >= 1, got "
                         f"{args.serve_adapter_rank}")
    if args.serve_prefetch_ticks < 1:
        raise SystemExit(f"--serve-prefetch-ticks must be >= 1, got "
                         f"{args.serve_prefetch_ticks}")
    serve_plan = None
    if args.serve_chaos:
        from simple_distributed_machine_learning_tpu.resilience import (
            faults,
        )
        try:
            serve_plan = faults.FaultPlan.parse(args.serve_chaos)
        except ValueError as e:
            raise SystemExit(f"bad --serve-chaos spec: {e}") from None
        if not args.serve_replicas and any(
                s.site == "fleet.tick" for s in serve_plan.specs):
            # only the fleet probes fleet.tick: without replicas the spec
            # would never fire and the drill would pass vacuously — the
            # FaultSpec typo'd-site rule's CLI twin
            raise SystemExit(
                "--serve-chaos at site fleet.tick needs --serve-replicas "
                "(a single engine never probes the fleet site, so the "
                "fault would never fire)")
    fleet_mode = args.serve_replicas > 0
    supervised = (not fleet_mode
                  and bool(args.serve_chaos or args.serve_deadline_ms))
    cfg = GPTConfig(vocab=256 if args.text_corpus else 128)
    if cfg.n_heads % args.serve_tp:
        raise SystemExit(f"--serve-tp {args.serve_tp} must divide the "
                         f"model's head count ({cfg.n_heads})")
    longest = args.serve_shared_prefix + max(GPT_SERVE_PROMPTS)
    if longest + 1 > cfg.seq_len:
        raise SystemExit(
            f"--serve-shared-prefix {args.serve_shared_prefix} leaves no "
            f"room to generate: prefix + longest simulated prompt "
            f"({max(GPT_SERVE_PROMPTS)}) + 1 token must fit seq_len "
            f"{cfg.seq_len}")
    stages, wire_dim, out_shape = make_gpt_stages(key, cfg, n_stages)
    # the serving deployment shape: stages stay the dense unsharded build
    # (the engine slices per shard itself), the serve cfg carries the TP
    # width and the mesh binds the model axis the shard_map programs need
    serve_cfg = cfg
    mesh = None
    if args.serve_tp > 1:
        import dataclasses as _dc

        import jax as _jax

        from simple_distributed_machine_learning_tpu.parallel.mesh import (
            make_mesh,
        )
        if len(_jax.devices()) < args.serve_tp:
            raise SystemExit(
                f"--serve-tp {args.serve_tp} needs {args.serve_tp} "
                f"devices, have {len(_jax.devices())} (on CPU: "
                f"XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{args.serve_tp})")
        serve_cfg = _dc.replace(cfg, n_tensor_parallel=args.serve_tp)
        mesh = make_mesh(n_stages=1, n_data=1, n_model=args.serve_tp)
    draft_stages = draft_cfg = None
    if args.serve_spec_k:
        # the draft: same config family at half the layers, fresh init off
        # a folded key — proposals only steer which tokens get verified,
        # so an untrained draft costs acceptance rate, never correctness
        import dataclasses as _dc

        import jax as _jax
        draft_cfg = _dc.replace(cfg,
                                n_layers=max(1, cfg.n_layers // 2))
        draft_stages, _dw, _do = make_gpt_stages(
            _jax.random.fold_in(key, 1), draft_cfg, 1)
    if args.lint or args.lint_only:
        # the serve-path preflight gate: trace and lint the EXACT compiled
        # programs the ticks below will execute (block/position contracts
        # via the scatter-bounds interval pass, donated-buffer flow through
        # the composite tick, retrace policy against the simulator's
        # prompt buckets, HBM-bytes-per-tick table) — zero FLOPs, nothing
        # allocated yet
        from simple_distributed_machine_learning_tpu.analysis.programs import (
            ServeSpec,
            lint_serve,
        )
        buckets = tuple(args.serve_shared_prefix + p
                        for p in GPT_SERVE_PROMPTS)
        report = lint_serve(stages, ServeSpec(
            serve_cfg, n_slots=args.serve_slots, kv_layout="paged",
            block_size=args.serve_block_size,
            prefill_chunk=(args.serve_prefill_chunk or None),
            prompt_lens=buckets, spec_k=args.serve_spec_k,
            draft_cfg=draft_cfg,
            # the engine's AdapterStore sizes the bank n_slots + 1 (row 0
            # = the zero base row), so the linted layouts are the EXACT
            # programs the adapter ticks below will execute
            n_adapters=(args.serve_slots + 1 if args.serve_adapters
                        else 0),
            adapter_rank=(args.serve_adapter_rank if args.serve_adapters
                          else 0)), mesh=mesh, draft_stages=draft_stages)
        print(report.format(costs=True))
        if not report.ok():
            raise SystemExit(2)
        # the protocol gate rides the same preflight: bounded model check
        # of the fleet snap/adopt/handoff discipline (pure stdlib, <1s) —
        # a serving stack whose PROTOCOL double-serves is as broken as one
        # whose kernels scatter out of bounds
        from simple_distributed_machine_learning_tpu.analysis.protocol import (
            check_protocol,
        )
        proto = check_protocol()
        print(f"| serve --lint protocol: {proto.verdict}")
        if not proto.ok():
            print(proto.format(costs=False))
            raise SystemExit(2)
        print("| serve --lint: preflight clean")
        if args.lint_only:
            return
    params = None
    ckpt = (os.path.join(args.checkpoint_dir, "state.npz")
            if args.checkpoint_dir else None)
    if ckpt and os.path.exists(ckpt):
        # restore the TRAINED params: same build (model flags + --stages +
        # --seed) the training run used, unpacked from the packed buffer
        from simple_distributed_machine_learning_tpu.parallel.mesh import (
            make_mesh,
        )
        from simple_distributed_machine_learning_tpu.parallel.pipeline import (
            Pipeline,
        )
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            restore_checkpoint,
        )
        pipe = Pipeline(stages, make_mesh(n_stages=n_stages), wire_dim,
                        out_shape)
        st = restore_checkpoint(ckpt, pipe=pipe)
        params = pipe.unpack(st["params"])
        print(f"| serve: restored params from {ckpt} "
              f"(step {st['step']})")
    else:
        print("| serve: fresh-initialized params"
              + (f" (no checkpoint at {ckpt})" if ckpt else ""))
    metrics = ServeMetrics(outdir=args.telemetry_dir)
    trace = None
    if args.serve_trace:
        if not args.telemetry_dir:
            raise SystemExit("--serve-trace needs --telemetry-dir (the "
                             "trace artifacts land next to metrics.jsonl)")
        from simple_distributed_machine_learning_tpu.serve import (
            ServeTrace,
        )
        trace = ServeTrace(outdir=args.telemetry_dir)
    engine_kw = dict(
        params=params, n_slots=args.serve_slots,
        block_size=args.serve_block_size,
        prefill_chunk=(args.serve_prefill_chunk or None),
        host_cache_blocks=args.serve_host_blocks,
        prefetch_ticks=args.serve_prefetch_ticks,
        metrics=metrics, mesh=mesh, draft_stages=draft_stages,
        draft_cfg=draft_cfg, spec_k=args.serve_spec_k)
    if args.serve_adapters:
        if fleet_mode or supervised:
            # the engine factory builds (and rebuilds, after a crash)
            # each engine's AdapterStore over one shared host dict
            engine_kw["adapter_rank"] = args.serve_adapter_rank
        else:
            from simple_distributed_machine_learning_tpu.serve.adapters import (  # noqa: E501
                AdapterStore,
            )
            engine_kw["adapters"] = AdapterStore(
                serve_cfg, args.serve_adapter_rank, args.serve_slots)
    tmpdir = None
    if fleet_mode:
        # the multi-replica path: N supervised engines behind the
        # health-aware router — fleet-unique rids, per-replica journals,
        # journal-backed cross-replica migration on replica loss
        import tempfile

        from simple_distributed_machine_learning_tpu.serve import (
            ServeFleet,
            engine_factory,
        )
        if args.telemetry_dir:
            journal_dir = args.telemetry_dir
        else:
            tmpdir = tempfile.TemporaryDirectory(prefix="sdml-fleet-")
            journal_dir = tmpdir.name
        engine = ServeFleet(
            engine_factory(stages, serve_cfg, **engine_kw), journal_dir,
            n_replicas=args.serve_replicas,
            prefill_replicas=args.serve_prefill_replicas,
            route=args.serve_route,
            metrics=metrics, autoscale=autoscale,
            max_restarts=args.serve_max_restarts,
            default_deadline_s=(args.serve_deadline_ms / 1e3
                                if args.serve_deadline_ms else None),
            trace=trace,
            # crash forensics whenever artifacts are kept, like the
            # single-supervisor path: bundles are tagged -r<idx> so the
            # replicas sharing this dir never collide
            postmortem_dir=args.telemetry_dir or None)
        print(f"| serve: fleet of {args.serve_replicas} replica(s), "
              f"route {args.serve_route} (journals "
              f"{journal_dir}/journal-r*.jsonl"
              + (f", disaggregated {args.serve_prefill_replicas} prefill "
                 f"+ {args.serve_replicas - args.serve_prefill_replicas} "
                 f"decode" if args.serve_prefill_replicas else "")
              + (f", autoscale [{autoscale.min_replicas}, "
                 f"{autoscale.max_replicas}]" if autoscale else "")
              + (f", chaos {args.serve_chaos!r}" if args.serve_chaos
                 else "") + ")")
    elif supervised:
        # the crash-restartable path: the engine lives behind the serve
        # supervisor — journaled submissions/tokens, engine rebuild +
        # journal recovery on injected faults, deadline shedding
        import tempfile

        from simple_distributed_machine_learning_tpu.serve import (
            ServeSupervisor,
            engine_factory,
        )
        if args.telemetry_dir:
            journal_path = os.path.join(args.telemetry_dir,
                                        "journal.jsonl")
            if os.path.exists(journal_path):
                os.unlink(journal_path)        # each --serve-sim run is fresh
        else:
            tmpdir = tempfile.TemporaryDirectory(prefix="sdml-serve-")
            journal_path = os.path.join(tmpdir.name, "journal.jsonl")
        engine = ServeSupervisor(
            engine_factory(stages, serve_cfg, **engine_kw), journal_path,
            metrics=metrics, max_restarts=args.serve_max_restarts,
            default_deadline_s=(args.serve_deadline_ms / 1e3
                                if args.serve_deadline_ms else None),
            trace=trace,
            # crash forensics whenever artifacts are kept: a post-mortem
            # bundle per restart / drain-timeout / shed burst next to the
            # journal (serve/flight.py)
            postmortem_dir=args.telemetry_dir or None)
        print(f"| serve: supervised (journal {journal_path}"
              + (f", chaos {args.serve_chaos!r}" if args.serve_chaos
                 else "")
              + (f", deadline {args.serve_deadline_ms:g} ms"
                 if args.serve_deadline_ms else "") + ")")
    else:
        engine = InferenceEngine(stages, serve_cfg, trace=trace,
                                 **engine_kw)
    if args.serve_adapters:
        # seeded per-tenant weights off the run key: register on the
        # serving target (engine / supervisor / fleet — one call shape);
        # device rows upload lazily at each replica's admission ticks
        import jax as _jax

        from simple_distributed_machine_learning_tpu.models import lora
        for k in range(args.serve_adapters):
            engine.register_adapter(
                f"tenant-{k}",
                lora.init_lora_adapter(_jax.random.fold_in(key, 7000 + k),
                                       serve_cfg,
                                       args.serve_adapter_rank))
        print(f"| serve: {args.serve_adapters} LoRA tenant(s) rank "
              f"{args.serve_adapter_rank} over shared base weights")
    max_new = min(args.serve_max_new, cfg.seq_len - longest)
    if max_new < args.serve_max_new:
        print(f"| serve: --serve-max-new {args.serve_max_new} clamped to "
              f"{max_new} (seq_len {cfg.seq_len} minus the longest "
              f"{longest}-token simulated prompt)")
    sim = SimConfig(n_requests=args.serve_sim, rate=args.serve_rate,
                    seed=args.seed, prompt_lens=GPT_SERVE_PROMPTS,
                    max_new_tokens=max_new,
                    shared_prefix_len=args.serve_shared_prefix,
                    # multi-tenant adapters: arrivals split evenly across
                    # the tenants, each request decoding its own adapter
                    classes=tuple(
                        TrafficClass(name=f"tenant-{k}",
                                     adapter=f"tenant-{k}")
                        for k in range(args.serve_adapters)))
    # graceful shutdown: SIGTERM/SIGINT stop admission, drain in-flight
    # requests, flush metrics + journal and exit 0 — the operational
    # complement of crash recovery (a rollout must not look like a fault)
    import signal

    stop = {"sig": None}

    def _on_signal(signum, frame):
        stop["sig"] = signum

    old_handlers = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            old_handlers[s] = signal.signal(s, _on_signal)
    except ValueError:
        old_handlers = {}              # not the main thread: no handlers
    if serve_plan is not None:
        from simple_distributed_machine_learning_tpu.resilience import (
            faults,
        )
        faults.install(serve_plan)
    try:
        report = simulate(engine, sim,
                          should_stop=lambda: stop["sig"] is not None)
    finally:
        if serve_plan is not None:
            faults.uninstall()
        for s, h in old_handlers.items():
            signal.signal(s, h)
        if supervised or fleet_mode:
            engine.close()             # journal(s) flushed + closed
        if trace is not None:
            trace.close()              # chrome trace + timeline flushed
    s = metrics.summary()
    print(f"| serve: {report['completed']}/{report['n_requests']} requests "
          f"completed, {s['tokens_generated']} tokens, "
          f"{s['tokens_per_sec']} tok/s, "
          f"ttft p50/p95 {s['ttft_ms_p50']}/{s['ttft_ms_p95']} ms, "
          f"tpot p50/p95 {s['tpot_ms_p50']}/{s['tpot_ms_p95']} ms, "
          f"occupancy {s['slot_occupancy_mean']}")
    if fleet_mode:
        print(f"| serve: fleet {engine.n_alive} alive "
              f"({engine.n_in_rotation} in rotation), "
              f"{engine.replica_losses} replica loss(es), "
              f"{engine.migrations} migration(s), "
              f"{s.get('route_affinity_hits', 0)} affinity hit(s), "
              f"{s.get('fleet_scale_outs', 0)} scale-out(s), "
              f"{s.get('fleet_retired', 0)} retired, "
              f"{s.get('restarts', 0)} in-place restart(s), "
              f"journals {s.get('journal_bytes', 0)} bytes")
        if args.serve_prefill_replicas:
            print(f"| serve: disaggregated — {engine.handoffs} "
                  f"prefill->decode handoff(s), pools "
                  + ", ".join(
                      f"{p}[{b['replicas']} replica(s), queue "
                      f"{b['queue_depth']}, {b['slots_active']} active]"
                      for p, b in sorted((s.get("pools") or {}).items())))
        if args.serve_host_blocks:
            print(f"| serve: host tier {s.get('host_blocks', 0)} block(s) "
                  f"resident ({s.get('host_bytes_resident', 0)} bytes), "
                  f"{s.get('host_demotes', 0)} demote(s), "
                  f"{s.get('host_promotes', 0)} promote(s), prefetch "
                  f"{s.get('host_prefetch_hits', 0)} hit(s)/"
                  f"{s.get('host_prefetch_misses', 0)} miss(es), "
                  f"{s.get('host_transfer_bytes', 0)} bytes transferred")
    if supervised:
        print(f"| serve: supervisor {engine.state}, "
              f"{s.get('restarts', 0)} restart(s), "
              f"{s.get('recovered_requests', 0)} recovered, "
              f"{report['shed']} shed {s.get('shed_by_reason', {})}, "
              f"journal {s.get('journal_bytes', 0)} bytes")
        if engine.postmortems:
            print(f"| serve: {len(engine.postmortems)} post-mortem "
                  f"bundle(s): "
                  f"{[os.path.basename(p) for p in engine.postmortems]}")
    if args.serve_adapters:
        print(f"| serve: adapters — "
              f"{s.get('adapter_resident_bytes', 0)} bank bytes "
              f"resident, {s.get('adapter_swaps', 0)} bank upload(s), "
              f"{s.get('route_adapter_affinity_hits', 0)} "
              f"adapter-affinity hit(s), per-tenant completed "
              f"{s.get('per_adapter_completed', {})}")
    if "kv_drift_bytes" in s:
        print(f"| serve: kv drift {s['kv_drift_bytes']} bytes vs the "
              f"analyzer model (predicted {s['kv_bytes_predicted']})")
    if trace is not None:
        print(f"| serve: trace {trace.n_events} events -> "
              f"{trace.trace_file} + {trace.timeline_file}")
    if report["stopped"]:
        print(f"| serve: graceful shutdown on signal {stop['sig']} — "
              f"admission stopped, {report['submitted']} submitted "
              f"request(s) drained, metrics/journal flushed")
    print(f"| serve: paged pool {s['blocks_in_use']}/{s['blocks_total']} "
          f"blocks in use ({s['blocks_cached']} cached), "
          f"{s['kv_bytes_resident']} KV bytes resident, "
          f"{s['prefix_hit_blocks']} prefix-share hits, "
          f"{s['cow_copies']} CoW copies, "
          f"prefill chunk p50/p95 {s['prefill_chunk_ms_p50']}/"
          f"{s['prefill_chunk_ms_p95']} ms")
    if args.serve_tp > 1 or args.serve_spec_k:
        spec = (f", spec_k {s.get('spec_k', 0)} accept_rate "
                f"{s.get('spec_accept_rate')} "
                f"({s.get('spec_accepted_tokens', 0)}/"
                f"{s.get('spec_proposed_tokens', 0)} draft tokens)"
                if args.serve_spec_k else "")
        print(f"| serve: tp {args.serve_tp}{spec}")
    if args.telemetry_dir:
        metrics.emit(extra={"rate": sim.rate, "n_slots": args.serve_slots,
                            "block_size": args.serve_block_size,
                            "shared_prefix": args.serve_shared_prefix,
                            "completed": report["completed"]})
    if tmpdir is not None:
        tmpdir.cleanup()
    # success = every SUBMITTED request accounted for: completed, or (a
    # deadline run) structurally shed — a silently lost request fails.
    # A graceful shutdown judges only what was admitted before the signal.
    expected = (report["submitted"] if report["stopped"]
                else report["n_requests"])
    if report["completed"] + report["shed"] != expected:
        raise SystemExit(1)


# prompt-length buckets of the simulated serving workload (each bucket is
# one compiled prefill shape)
GPT_SERVE_PROMPTS = (4, 8, 12)


def _run_scenario(args, n_stages: int, key) -> None:
    """--scenario NAME: one SLO-gated serving scenario (resilience/
    scenarios.py) on a fresh-init GPT build; exits nonzero unless every
    gated class attains its TTFT/TPOT targets and all requests complete."""
    from simple_distributed_machine_learning_tpu.resilience.scenarios import (
        SCENARIOS,
        run_scenario,
    )

    if args.scenario == "list":
        for s in SCENARIOS.values():
            print(f"| {s.name}: {s.description}")
        return
    if args.scenario not in SCENARIOS:
        raise SystemExit(
            f"unknown --scenario {args.scenario!r}; available: "
            f"{', '.join(sorted(SCENARIOS))} (or 'list')")
    if args.serve_sim > 0 or args.chaos is not None:
        raise SystemExit("--scenario runs alone (drop --serve-sim/--chaos)")
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    cfg = GPTConfig()
    stages, _wd, _os = make_gpt_stages(key, cfg, n_stages)
    report = run_scenario(args.scenario, stages, cfg,
                          outdir=args.telemetry_dir,
                          trace=bool(args.serve_trace))
    print(f"| scenario {report['scenario']} ({report['scheduler']}"
          + (", supervised" if report.get("supervised") else "") + "): "
          f"{report['completed']}/{report['n_requests']} completed, "
          f"{report['shed']} shed, "
          f"{report.get('preemptions', 0)} preemptions, "
          + (f"{report['restarts']} restart(s), "
             if report.get("supervised") else "")
          + f"faults fired: "
          f"{report.get('faults', {}).get('total_fired', 0)}")
    fl = report.get("fleet")
    if fl:
        print(f"| scenario: fleet {fl['replicas']} replica(s) "
              f"(route {fl['route']}): {fl['replica_losses']} loss(es), "
              f"{fl['migrations']} migration(s), "
              f"{fl['affinity_hits']} affinity hit(s), "
              f"{fl['scale_outs']} scale-out(s), {fl['retired']} retired")
        for ev in fl["replica_log"]:
            print(f"| scenario:   fleet {ev['event']} replica "
                  f"{ev['replica']} @tick {ev['tick']} "
                  f"(t={ev['t']:g}, {ev['alive']} alive)")
    for cls, att in sorted(report["slo"].items()):
        parts = []
        if "ttft_attainment" in att:
            a = att["ttft_attainment"]
            parts.append(f"ttft p95 {att['ttft_ms_p95']} vms vs SLO "
                         f"{att['ttft_slo_ms']} "
                         f"({'-' if a is None else round(a, 3)})")
        if "tpot_attainment" in att:
            a = att["tpot_attainment"]
            parts.append(f"tpot p95 {att['tpot_ms_p95']} vms vs SLO "
                         f"{att['tpot_slo_ms']} "
                         f"({'-' if a is None else round(a, 3)})")
        print(f"| scenario:   {cls} "
              f"[{'OK' if att['ok'] else 'VIOLATED'}] " + "; ".join(parts))
    sa = report.get("slo_alerts")
    if sa:
        for tr in sa["transitions"]:
            print(f"| scenario:   alert {tr['alert']} {tr['from']} -> "
                  f"{tr['to']} @tick {tr['tick']} (burn fast/slow "
                  f"{tr.get('burn_fast', 0)}/{tr.get('burn_slow', 0)})")
        if not sa["transitions"]:
            print("| scenario:   alerts: no burn-rate transitions "
                  "(error budget never breached)")
    att_blk = report.get("attribution")
    if att_blk:
        print(f"| scenario: attribution {att_blk['requests']} request(s) "
              f"folded, {att_blk['recovered']} recovered, max drift "
              f"{att_blk['max_abs_drift_ms']} ms")
        for a in att_blk["top_slow"]:
            comps = " ".join(f"{c}={v}"
                             for c, v in a["components_ms"].items())
            print(f"| scenario:   slow rid {a['rid']} ({a['cls']}) ttft "
                  f"{a['ttft_ms']} vms: {comps}"
                  + (" [recovered]" if a.get("recovered") else ""))
    if report.get("postmortem_bundles"):
        print(f"| scenario: {report['postmortem_bundles']} post-mortem "
              f"bundle(s) under {args.telemetry_dir}")
    if report.get("trace_events"):
        print(f"| scenario: trace {report['trace_events']} events"
              + (f" under {args.telemetry_dir}" if args.telemetry_dir
                 else " (in-memory; add --telemetry-dir to keep them)"))
    print(f"| scenario: SLO {'ATTAINED' if report['slo_ok'] else 'MISSED'}")
    if not report["slo_ok"]:
        raise SystemExit(1)


def _run_chaos(args, n_stages: int, key) -> None:
    """--chaos SPEC: training under a deterministic fault schedule with the
    elastic checkpoint-restart supervisor (resilience/supervisor.py).

    The supervisor rebuilds the trainer from scratch after every
    recoverable failure — nothing in-memory survives an attempt — restoring
    the latest checksum-valid checkpoint from the store in --checkpoint-dir
    and repacking it onto the surviving stage count from the
    --chaos-stages ladder. Exits 0 only when training ran to completion
    within the restart budget.
    """
    import dataclasses

    import numpy as np

    from simple_distributed_machine_learning_tpu.resilience import (
        CheckpointStore,
        RestartPolicy,
        faults,
        make_elastic_trainer,
        supervise,
    )

    if args.model not in ("mlp", "gpt"):
        raise SystemExit(
            "--chaos supports --model mlp or gpt (the contiguous-split "
            "families repack_checkpoint can rewrite across stage counts; "
            "lenet's conv|fc split is a structural rename)")
    if args.experts > 0 or args.sp > 1 or args.tp > 1 or args.ep > 1 \
            or args.serve_sim > 0:
        raise SystemExit(
            "--chaos drills the pipeline-parallel training path: drop "
            "--experts/--sp/--tp/--ep/--serve-sim")
    if args.world_size > 1:
        raise SystemExit(
            "--chaos supervises in-process (single-process elastic "
            "restart); multi-process peer loss is the watchdog's domain "
            "(--peer-timeout)")
    if not args.checkpoint_dir:
        raise SystemExit("--chaos needs --checkpoint-dir (the supervisor "
                         "restores from its checkpoint store)")
    if args.chaos_max_restarts < 0:
        raise SystemExit(f"--chaos-max-restarts must be >= 0, got "
                         f"{args.chaos_max_restarts}")
    try:
        plan = faults.FaultPlan.parse(args.chaos)
    except ValueError as e:
        raise SystemExit(f"bad --chaos spec: {e}") from None
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        SENTINEL_KINDS,
    )
    numeric = sorted({s.kind for s in plan.specs
                      if s.kind in SENTINEL_KINDS})
    if numeric and not args.sentinel:
        # without the sentinel a numeric fault's standard effect is a
        # raised NumericFault the supervisor treats as a real bug — the
        # drill would fail confusingly instead of being absorbed
        raise SystemExit(
            f"--chaos plan contains sentinel-interpreted kinds "
            f"({', '.join(numeric)}): add --sentinel so the trainer "
            f"absorbs them")
    if args.chaos_stages:
        try:
            topologies = [int(s) for s in args.chaos_stages.split(",")]
        except ValueError:
            raise SystemExit(f"--chaos-stages expects a comma list of "
                             f"stage counts, got {args.chaos_stages!r}"
                             ) from None
        if any(t < 1 for t in topologies):
            raise SystemExit(f"--chaos-stages entries must be >= 1, got "
                             f"{topologies}")
    else:
        topologies = [n_stages]

    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )

    if args.model == "gpt":
        from simple_distributed_machine_learning_tpu.data.text import (
            synthetic_tokens,
        )
        from simple_distributed_machine_learning_tpu.models.gpt import (
            GPTConfig,
            make_gpt_stages,
        )
        cfg = GPTConfig(vocab=256 if args.text_corpus else 128)
        all_data = synthetic_tokens(7000, cfg.seq_len, cfg.vocab,
                                    seed=args.seed)
        train_ds = Dataset(all_data.x[:6000].astype(np.float32),
                           all_data.y[:6000])
        test_ds = Dataset(all_data.x[6000:].astype(np.float32),
                          all_data.y[6000:])

        def build_pipe(n):
            stages, wd, osh = make_gpt_stages(key, cfg, n)
            mesh = make_mesh(n_stages=n, n_data=args.dp,
                             devices=jax.devices()[:n * args.dp])
            return Pipeline(stages, mesh, wd, osh,
                            n_microbatches=args.microbatches,
                            compute_dtype=_compute_dtype(args),
                            remat=args.remat, schedule=args.schedule)
    else:
        from simple_distributed_machine_learning_tpu.data.mnist import (
            load_mnist,
        )
        from simple_distributed_machine_learning_tpu.models.mlp import (
            make_mlp_stages,
        )
        dims = [int(d) for d in args.mlp_dims.split(",")]
        tr, te = load_mnist(args.data_root)
        train_ds = Dataset(tr.x.reshape(len(tr.x), -1), tr.y)
        test_ds = Dataset(te.x.reshape(len(te.x), -1), te.y)

        def build_pipe(n):
            stages, wd, od = make_mlp_stages(key, dims, n)
            mesh = make_mesh(n_stages=n, n_data=args.dp,
                             devices=jax.devices()[:n * args.dp])
            return Pipeline(stages, mesh, wd, od,
                            n_microbatches=args.microbatches,
                            compute_dtype=_compute_dtype(args),
                            remat=args.remat, schedule=args.schedule)

    store = CheckpointStore(args.checkpoint_dir, keep=5)
    # the store owns persistence: the Trainer's own state.npz path stays off
    config = dataclasses.replace(_train_config(args), checkpoint_dir=None)
    total = _total_steps(args, train_ds)

    def build_trainer(n):
        # opt_factory: the optimizer must see the ATTEMPT's pipeline
        # (replication-weighted --clip-norm depends on the topology)
        return make_elastic_trainer(
            build_pipe, n, store, train_ds, test_ds, config,
            opt_factory=lambda pipe: _make_opt(args, total, pipe))

    faults.install(plan)
    try:
        report = supervise(
            build_trainer, topologies,
            policy=RestartPolicy(max_restarts=args.chaos_max_restarts))
    finally:
        faults.uninstall()
    print(f"| chaos: completed after {report['restarts']} restart(s); "
          f"attempts: "
          + " -> ".join(f"{a['n_stages']}st/{a['outcome']}"
                        f"{'(' + a['fault'] + ')' if 'fault' in a else ''}"
                        for a in report["attempts"])
          + f"; faults fired: {plan.stats()['total_fired']}")
    if args.sentinel:
        tot = {"anomalies": 0, "rollbacks": 0}
        quarantined = 0
        for a in report["attempts"]:
            s = a.get("sentinel") or {}
            tot["anomalies"] += s.get("anomalies", 0)
            tot["rollbacks"] += s.get("rollbacks", 0)
            # the journal is cumulative across attempts (loaded from disk):
            # the last attempt's count is the total
            quarantined = s.get("quarantined_batches", quarantined)
        print(f"| chaos: sentinel absorbed {tot['anomalies']} anomal"
              f"{'y' if tot['anomalies'] == 1 else 'ies'} "
              f"({tot['rollbacks']} rollback(s), {quarantined} "
              f"quarantined batch(es))")
    if plan.stats()["total_fired"] == 0:
        # the min_anomalies-style anti-vacuous gate: a chaos drill whose
        # schedule never fired proves nothing — fail it instead of letting
        # a typo'd step number pass green
        raise SystemExit(
            "--chaos plan never fired (scheduled step beyond the run?) — "
            "the drill is vacuous; fix the schedule")


def _print_sample(args, trainer, cfg, test_ds) -> None:
    """--generate N: decode N tokens from the trained model (KV-cache path,
    straight from the live packed buffer) and print them on rank 0 — for a
    --text-corpus run this is the model writing text."""
    import jax

    import numpy as np

    from simple_distributed_machine_learning_tpu.models.gpt import (
        decoder_from_pipeline,
    )

    n_new = min(args.generate, cfg.seq_len - 1)
    t0 = max(1, min(cfg.seq_len - n_new, 16))
    pipe = trainer.pipe
    if cfg.n_experts > 0 or cfg.n_seq > 1 or cfg.n_tensor_parallel > 1:
        trainer._print("| --generate: skipped (MoE/seq-/tensor-parallel "
                       "builds decode via models.make_decoder)")
        return
    if pipe.n_stages >= 2:
        # pipeline-parallel decode: stage-sharded params stay put, so this
        # works on multi-process meshes too (every rank participates; the
        # batch shards over the data axis, hence B = n_data prompts)
        from simple_distributed_machine_learning_tpu.models.pp_decode import (
            make_pp_decoder,
        )
        B = pipe.n_data
        if len(test_ds.x) < B:
            trainer._print("| --generate: skipped (test set smaller than "
                           "the data-parallel width)")
            return
        prompt = np.asarray(test_ds.x[:B, :t0], np.int32)
        dec = make_pp_decoder(pipe, cfg, t0, n_new,
                              cache_dtype=_compute_dtype(args))
    else:
        if jax.process_count() > 1:
            # a 1-stage multi-process buffer is not host-gatherable here
            trainer._print("| --generate: skipped (single-stage multi-"
                           "process run; decode from a checkpoint instead)")
            return
        prompt = np.asarray(test_ds.x[:1, :t0], np.int32)
        dec = decoder_from_pipeline(pipe, cfg, t0, n_new,
                                    cache_dtype=_compute_dtype(args))
    toks = _decode_timed(args, trainer, dec, prompt, n_new)[0]
    if args.text_corpus:
        text = bytes(int(t) for t in toks).decode("latin-1")
        trainer._print(f"| sample ({t0}-byte prompt + {n_new} generated):\n"
                       f"{text!r}")
    else:
        trainer._print(f"| sample tokens (prompt {t0} + {n_new} generated): "
                       f"{toks.tolist()}")


def _decode_timed(args, trainer, dec, prompt, n_new):
    """Run the --generate decode; with --telemetry-dir attached, route its
    timing through the telemetry StepTimer/registry so decode latency and
    tokens/sec land in metrics.jsonl (+ the Prometheus exposition) instead
    of being print-only. The first call is the compile window (StepTimer
    splits it out); a second, different-key decode measures the steady
    latency — distinct inputs so a result-cached re-dispatch cannot fake
    the number (bench.py's measure_decode discipline)."""
    import time as _time

    import jax

    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        _to_host,
    )

    tele = trainer.telemetry
    key = jax.random.key(args.seed)
    if tele is None:
        return _to_host(dec(trainer.buf, prompt, key))
    from simple_distributed_machine_learning_tpu.telemetry.registry import (
        append_jsonl,
    )
    from simple_distributed_machine_learning_tpu.telemetry.timer import (
        StepTimer,
    )
    timer = StepTimer(registry=tele.registry, name="decode_time_ms")
    b, n_tok = prompt.shape[0], prompt.shape[0] * n_new
    t0 = _time.perf_counter()
    toks = _to_host(dec(trainer.buf, prompt, key))
    timer.record_window(_time.perf_counter() - t0, steps=1)   # compile window
    t0 = _time.perf_counter()
    jax.block_until_ready(dec(trainer.buf, prompt,
                              jax.random.fold_in(key, 1)))
    timer.record_window(_time.perf_counter() - t0, steps=1, tokens=n_tok)
    if trainer.is_main:
        import os
        rec = {"kind": "decode", "batch": int(b), "n_new": int(n_new),
               **timer.summary()}
        append_jsonl(os.path.join(tele.outdir, "metrics.jsonl"), rec)
        tele.flush()                     # decode series -> metrics.prom
    return toks


if __name__ == "__main__":
    main()
